#!/usr/bin/env python
"""Benchmark: the BASELINE north-star hot path + model-zoo step time/MFU.

Measures

1. TPE ``suggest()`` latency with 10 000 observations on an 8-dim mixed
   space — the operation BASELINE.md requires to stay flat past 10k trials —
   with the density kernel XLA-compiled on the real TPU chip, compared
   against a faithful numpy implementation of the exact same Parzen/EI math
   (the reference's implementation substrate: pure Python/numpy,
   SURVEY.md §2.9).
2. The flagship trial workloads on the same chip: Transformer-base train-step
   time with analytic-FLOP MFU, and ResNet-50/CIFAR step time (images/s) —
   the per-trial cost behind BASELINE.md's trials/hour north star.
3. A Mosaic (Pallas) compile probe behind a timeout, recording whether the
   backend can build the flash-attention kernel natively or must use the
   chunked XLA twin.

Prints ONE JSON line:
    {"metric": "tpe_suggest_ms_per_point_10k_obs_pool8", "value": <ms>,
     "unit": "ms", "vs_baseline": <numpy_ms / jax_ms speedup>, "extra": ...}
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

from metaopt_tpu.utils.procs import run_with_deadline


def preflight_backend(timeout_s: float = 90.0) -> None:
    """Fall back to CPU if the TPU backend is unreachable (shared doctrine
    in metaopt_tpu.utils.procs.preflight_backend)."""
    from metaopt_tpu.utils.procs import preflight_backend as _pf

    _pf(timeout_s,
        announce="bench preflight: TPU backend unreachable; measuring on CPU")


def build_tpe(n_obs: int, seed: int = 0):
    from metaopt_tpu.algo import TPE
    from metaopt_tpu.space import build_space

    space = build_space(
        {
            "lr": "loguniform(1e-5, 1e-1)",
            "wd": "loguniform(1e-6, 1e-2)",
            "width": "uniform(32, 1024, discrete=True)",
            "depth": "uniform(1, 12, discrete=True)",
            "dropout": "uniform(0.0, 0.5)",
            "momentum": "uniform(0.5, 0.999)",
            "opt": "choices(['adam', 'sgd', 'lamb'])",
            "schedule": "choices(['cosine', 'linear', 'constant'])",
        }
    )
    tpe = TPE(space, seed=seed, n_initial_points=8)
    rng = np.random.default_rng(seed)
    X = rng.random((n_obs, tpe.cube.n_dims))
    y = rng.random(n_obs).tolist()
    tpe._X = list(X)
    tpe._y = y
    tpe._observed = {str(i): y[i] for i in range(n_obs)}
    return tpe


def numpy_ei_reference(tpe) -> float:
    """The same split/fit/sample/score pipeline with numpy densities.

    This is what the reference-era implementation does per suggest call
    (Python/numpy KDE evaluation); timing it on the same data is the
    apples-to-apples baseline for the jitted kernel.
    """
    from scipy.special import logsumexp
    from scipy.stats import norm

    below, above = tpe._split()
    good, bad = tpe._fit_set(below), tpe._fit_set(above)
    cand = tpe._sample_from(good, tpe.n_ei_candidates)

    def np_logpdf(fit, x):
        mu, sig, logw = fit["mu"], fit["sigma"], fit["logw"]
        z = (x[:, None, :] - mu[None, :, :]) / sig[None, :, :]
        log_phi = norm.logpdf(z) - np.log(sig[None, :, :])
        mass = norm.cdf((1 - mu) / sig) - norm.cdf((0 - mu) / sig)
        log_mass = np.log(np.clip(mass, 1e-12, 1.0))
        return logsumexp(
            log_phi - log_mass[None, :, :] + logw[None, :, :], axis=1
        )

    log_l = np_logpdf(good, cand)
    log_g = np_logpdf(bad, cand)
    k = np.maximum(tpe.cube.n_choices, 1)
    cat_idx = np.minimum((cand * k[None, :]).astype(int), (k - 1)[None, :])
    d_idx = np.arange(cand.shape[1])[None, :]
    cat_mask = tpe.cube.categorical_mask
    log_l = np.where(cat_mask[None, :], good["cat_logp"][d_idx, cat_idx], log_l)
    log_g = np.where(cat_mask[None, :], bad["cat_logp"][d_idx, cat_idx], log_g)
    scores = np.sum(log_l - log_g, axis=1)
    return cand[int(np.argmax(scores))]


def time_fn(fn, repeats: int = 20) -> float:
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append((time.perf_counter() - t0) * 1000)
    return float(np.median(times))


#: peak dense bf16 FLOP/s per chip by device-kind substring
_PEAK_FLOPS = [
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
    ("v4", 275e12), ("v6", 918e12),
]


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in kind:
            return peak
    return 0.0  # unknown device / CPU: MFU reported as 0


def transformer_train_flops(b, s, d, layers, d_ff, vocab) -> float:
    """Analytic FLOPs for one train step (fwd + bwd ≈ 3× fwd matmul FLOPs).

    Per-token matmul FLOPs: encoder layer 8d² (qkv/out) + 4·d·d_ff (ffn)
    + 4·S·d (scores+values); decoder layer adds a cross-attention block;
    readout 2·d·V per target token. Embedding gathers are ignored.
    """
    enc = layers * (8 * d * d + 4 * d * d_ff + 4 * s * d)
    dec = layers * (16 * d * d + 4 * d * d_ff + 8 * s * d)
    readout = 2 * d * vocab
    return 3.0 * b * s * (enc + dec + readout)


def bench_transformer(on_tpu: bool) -> dict:
    """Train-step time + MFU for the flagship model on the current backend."""
    import jax
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from metaopt_tpu.models.data import synthetic_seq2seq
    from metaopt_tpu.models.transformer import (
        init_sharded, make_model, make_train_step,
    )
    from metaopt_tpu.parallel.mesh import trial_mesh, use_mesh
    from metaopt_tpu.parallel.sharding import shard_batch

    if on_tpu:  # Transformer-base (BASELINE config 4 trial workload)
        cfg = {"d_model": 512, "n_heads": 8, "n_layers": 6, "d_ff": 2048,
               "vocab": 32000, "dropout": 0.1}
        batch, seq = 32, 64
    else:  # tiny stand-in so a CPU fallback run still emits the fields
        cfg = {"d_model": 64, "n_heads": 4, "n_layers": 2, "d_ff": 256,
               "vocab": 1000, "dropout": 0.1}
        batch, seq = 8, 16

    model = make_model(cfg)
    tx = optax.adamw(1e-3)
    mesh = trial_mesh(tp=1)
    key = jax.random.PRNGKey(0)
    with use_mesh(mesh):
        params, opt_state, shardings = init_sharded(
            model, mesh, tx, (batch, seq)
        )
        step = jax.jit(
            make_train_step(model, tx),
            in_shardings=(shardings[0], shardings[1],
                          NamedSharding(mesh, P("dp")), None),
            out_shardings=(shardings[0], shardings[1], None),
            donate_argnums=(0, 1),
        )
        src, tgt = synthetic_seq2seq(key, batch, seq, model.vocab)
        sharded = shard_batch(mesh, (src, tgt))
        # warm-up/compile
        params, opt_state, loss = step(params, opt_state, sharded, key)
        jax.block_until_ready(loss)
        n_steps = 20 if on_tpu else 5
        t0 = time.perf_counter()
        for i in range(n_steps):
            params, opt_state, loss = step(
                params, opt_state, sharded, jax.random.fold_in(key, i)
            )
        jax.block_until_ready(loss)
        dt_ms = (time.perf_counter() - t0) * 1000 / n_steps

    flops = transformer_train_flops(
        batch, seq, cfg["d_model"], cfg["n_layers"], cfg["d_ff"], cfg["vocab"]
    )
    # the step runs data-parallel over the whole mesh: peak scales with it
    peak = peak_flops(jax.devices()[0]) * mesh.size
    mfu = (flops / (dt_ms / 1000)) / peak if peak else 0.0
    return {
        "transformer_step_ms": round(dt_ms, 3),
        "transformer_tokens_per_s": round(batch * seq / (dt_ms / 1000)),
        "mfu": round(mfu, 4),
        "transformer_config": {**cfg, "batch": batch, "seq": seq},
    }


def bench_resnet(on_tpu: bool) -> dict:
    """ResNet-50/CIFAR train-step time (BASELINE config 3 trial workload)."""
    import jax
    import jax.numpy as jnp
    import optax

    from metaopt_tpu.models.data import synthetic_images
    from metaopt_tpu.models.resnet import ResNet

    depth, batch = (50, 256) if on_tpu else (18, 32)
    model = ResNet(depth=depth)
    key = jax.random.PRNGKey(0)
    x, y = synthetic_images(key, batch, hw=32, channels=3)
    variables = model.init(jax.random.PRNGKey(1), x[:1], train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)
    opt_state = tx.init(params)

    def loss_fn(p, bs):
        logits, new_state = model.apply(
            {"params": p, "batch_stats": bs}, x, train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
        return loss, new_state["batch_stats"]

    @jax.jit
    def step(p, bs, o):
        (loss, bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, bs)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), bs, o, loss

    params, batch_stats, opt_state, loss = step(params, batch_stats, opt_state)
    jax.block_until_ready(loss)
    n_steps = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(n_steps):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state
        )
    jax.block_until_ready(loss)
    dt_ms = (time.perf_counter() - t0) * 1000 / n_steps
    return {
        f"resnet{depth}_step_ms": round(dt_ms, 3),
        f"resnet{depth}_images_per_s": round(batch / (dt_ms / 1000)),
    }


def probe_mosaic(timeout_s: float = 90.0) -> str:
    """Can this backend compile a Pallas (Mosaic) program? child + timeout.

    The axon relay historically hangs compiling any Mosaic program — probing
    in a disposable child turns "would wedge forever" into a recorded
    "timeout", and a future fixed relay flips this to "ok" so the Pallas
    flash path can be enabled on real TPU runs.
    """
    code = (
        "import jax, jax.numpy as jnp\n"
        "from jax.experimental import pallas as pl\n"
        "def k(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...] * 2\n"
        "x = jnp.ones((8, 128), jnp.float32)\n"
        "y = pl.pallas_call("
        "k, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)\n"
        "assert float(y[0, 0]) == 2.0\n"
    )
    rc, _ = run_with_deadline(
        [sys.executable, "-c", code], timeout_s=timeout_s, poll_s=1.0
    )
    if rc is None:
        return "timeout"
    return "ok" if rc == 0 else "error"


def main() -> None:
    preflight_backend()
    import jax

    n_obs = 10_000
    pool = 8  # a producer pool: one fused kernel launch + one readback
    tpe = build_tpe(n_obs)

    # warm-up: compile the kernels for these padded shapes
    tpe.suggest(pool)
    tpe._suggest_one_ei()
    pool_ms = time_fn(lambda: tpe.suggest(pool), repeats=20)
    jax_ms = pool_ms / pool
    # amortized single-suggest: a full prefetch cycle (one launch +
    # pool_prefetch-1 cache pops) divided by the points served — the cost a
    # worker asking for one point at a time actually pays per point — vs
    # the raw one-launch-per-point path
    pp = tpe.pool_prefetch
    single_ms = time_fn(
        lambda: [tpe._suggest_one_ei() for _ in range(pp)], repeats=10
    ) / pp
    single_uncached_ms = time_fn(lambda: tpe._launch_ei(1), repeats=10)

    # the reference substrate refits + rescores per suggestion (host numpy)
    numpy_ms = time_fn(lambda: numpy_ei_reference(tpe), repeats=5)

    # flatness check: per-suggestion latency at 1k vs 10k observations
    tpe1k = build_tpe(1_000)
    tpe1k.suggest(pool)
    jax_1k_ms = time_fn(lambda: tpe1k.suggest(pool), repeats=20) / pool

    on_tpu = jax.default_backend() == "tpu"
    model_stats = {}
    for name in ("transformer", "resnet"):
        # each model bench runs in a child with a deadline: a wedged
        # remote-compile must degrade to a recorded timeout, not sink the
        # TPE metric (or hang the driver)
        rc, out = run_with_deadline(
            [sys.executable, os.path.abspath(__file__), "--stage", name],
            timeout_s=420.0, capture=True,
        )
        parsed = None
        if rc == 0:
            # stderr is merged into the capture and runtime teardown may
            # chatter after the JSON line — scan for the line that parses
            for line in reversed(out.strip().splitlines()):
                try:
                    candidate = json.loads(line)
                except ValueError:
                    continue
                if isinstance(candidate, dict):  # not a stray scalar line
                    parsed = candidate
                    break
        if isinstance(parsed, dict):
            model_stats.update(parsed)
            continue
        model_stats[f"{name}_bench_error"] = (
            "stage timeout (compile wedged?)" if rc is None
            else f"rc={rc}: {out[-200:]}"
        )
    mosaic = probe_mosaic() if on_tpu else "skipped-cpu"

    result = {
        "metric": "tpe_suggest_ms_per_point_10k_obs_pool8",
        "value": round(jax_ms, 3),
        "unit": "ms",
        "vs_baseline": round(numpy_ms / jax_ms, 2),
        "extra": {
            "numpy_reference_ms_per_point": round(numpy_ms, 3),
            "single_suggest_ms": round(single_ms, 3),
            "single_suggest_uncached_ms": round(single_uncached_ms, 3),
            "jax_1k_obs_ms_per_point": round(jax_1k_ms, 3),
            "flatness_10k_over_1k": round(jax_ms / max(jax_1k_ms, 1e-9), 2),
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "mosaic_compile_probe": mosaic,
            **model_stats,
        },
    }
    print(json.dumps(result))


def stage_main(name: str) -> None:
    """Child entry: run one model bench, print its stats as one JSON line."""
    preflight_backend()
    import jax

    on_tpu = jax.default_backend() == "tpu"
    fn = {"transformer": bench_transformer, "resnet": bench_resnet}[name]
    print(json.dumps(fn(on_tpu)))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--stage":
        stage_main(sys.argv[2])
    else:
        main()
