#!/usr/bin/env python
"""Benchmark: the BASELINE north-star hot path + model-zoo step time/MFU.

Measures

1. TPE ``suggest()`` latency with 10 000 observations on an 8-dim mixed
   space — the operation BASELINE.md requires to stay flat past 10k trials —
   with the density kernel XLA-compiled on the real TPU chip, compared
   against a faithful numpy implementation of the exact same Parzen/EI math
   (the reference's implementation substrate: pure Python/numpy,
   SURVEY.md §2.9).
2. The flagship trial workloads on the same chip: Transformer-base train-step
   time with analytic-FLOP MFU at seq 256 and 512 (chunked flash attention,
   the TPU default), and ResNet-50/CIFAR step time (images/s) — the
   per-trial cost behind BASELINE.md's trials/hour north star.
3. The REAL Pallas flash kernel compiled and run against the chunked twin
   (status/step_ms/numerics under ``flash_pallas``), plus the trivial
   Mosaic compile probe.

A CPU fallback run (relay unreachable after 3 probes) is TPE-only and
embeds the newest committed ``benchmarks/results/bench_tpu_*.json`` under
``last_good_tpu`` so the driver's record always carries the TPU story.

Output contract (the driver keeps only a bounded TAIL of stdout, so the
LAST line must be small and self-contained):
- the full record is written to ``benchmarks/results/bench_<backend>_<date>
  .json``;
- the final stdout line is ONE compact JSON object:
    {"metric": "tpe_suggest_ms_per_point_10k_obs_pool8", "value": <ms>,
     "unit": "ms", "vs_baseline": <numpy_ms/jax_ms>, "backend": ...,
     "artifact": <relpath>, "tpu_record_from": "live"|"last_good:<file>",
     "mfu_seq256": ..., "mfu_seq512": ..., "mfu_seq1024": ...,
     "resnet50_mfu": ...}
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

from metaopt_tpu.utils.procs import run_with_deadline, setup_xla_cache


def preflight_backend(timeout_s: float = 90.0, retries: int = 1) -> bool:
    """Fall back to CPU if the TPU backend is unreachable (shared doctrine
    in metaopt_tpu.utils.procs.preflight_backend). True = TPU live.

    The verdict is cached per process (procs._PREFLIGHT_VERDICT), so the
    many bench sections that re-check the backend pay the probe child at
    most once. ``MTPU_BENCH_BACKEND=cpu`` skips the probe entirely and
    forces the CPU path — the CI/laptop invocation that used to burn a
    relay-probe timeout before every CPU-fallback run.
    """
    from metaopt_tpu.utils.procs import preflight_backend as _pf

    return _pf(
        timeout_s, retries=retries, backoff_s=20.0,
        announce="bench preflight: TPU backend unreachable; measuring on CPU",
    )


def last_good_tpu_record() -> dict:
    """Most recent committed TPU bench json, for CPU-fallback runs.

    A wedged relay must not erase the TPU story from the driver's record:
    when bench degrades to CPU, the newest ``benchmarks/results/
    bench_tpu_*.json`` rides along under an explicit ``last_good_tpu`` key.
    """
    import glob

    results = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "benchmarks", "results")
    paths = sorted(glob.glob(os.path.join(results, "bench_tpu_*.json")))
    if not paths:
        return {}
    path = paths[-1]  # names embed the date, so lexical max = newest
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError) as exc:
        return {"last_good_tpu_file": os.path.basename(path),
                "last_good_tpu_error": str(exc)}
    return {"last_good_tpu_file": os.path.basename(path),
            "last_good_tpu": payload}


def build_tpe(n_obs: int, seed: int = 0):
    from metaopt_tpu.algo import TPE
    from metaopt_tpu.space import build_space

    space = build_space(
        {
            "lr": "loguniform(1e-5, 1e-1)",
            "wd": "loguniform(1e-6, 1e-2)",
            "width": "uniform(32, 1024, discrete=True)",
            "depth": "uniform(1, 12, discrete=True)",
            "dropout": "uniform(0.0, 0.5)",
            "momentum": "uniform(0.5, 0.999)",
            "opt": "choices(['adam', 'sgd', 'lamb'])",
            "schedule": "choices(['cosine', 'linear', 'constant'])",
        }
    )
    tpe = TPE(space, seed=seed, n_initial_points=8)
    rng = np.random.default_rng(seed)
    X = rng.random((n_obs, tpe.cube.n_dims))
    y = rng.random(n_obs).tolist()
    tpe._X = list(X)
    tpe._y = y
    tpe._observed = {str(i): y[i] for i in range(n_obs)}
    return tpe


def build_gpbo(n_obs: int, seed: int = 0, **kw):
    from metaopt_tpu.algo import GPBO
    from metaopt_tpu.space import build_space

    space = build_space(
        {
            "lr": "loguniform(1e-5, 1e-1)",
            "wd": "loguniform(1e-6, 1e-2)",
            "width": "uniform(32, 1024, discrete=True)",
            "depth": "uniform(1, 12, discrete=True)",
            "dropout": "uniform(0.0, 0.5)",
            "momentum": "uniform(0.5, 0.999)",
            "opt": "choices(['adam', 'sgd', 'lamb'])",
            "schedule": "choices(['cosine', 'linear', 'constant'])",
        }
    )
    gp = GPBO(space, seed=seed, n_initial_points=8, **kw)
    rng = np.random.default_rng(seed)
    X = rng.random((n_obs, gp.cube.n_dims))
    y = rng.random(n_obs).tolist()
    gp._X = list(X)
    gp._y = y
    gp._observed = {str(i): y[i] for i in range(n_obs)}
    return gp


def numpy_ei_reference(tpe) -> float:
    """The same split/fit/sample/score pipeline with numpy densities.

    This is what the reference-era implementation does per suggest call
    (Python/numpy KDE evaluation); timing it on the same data is the
    apples-to-apples baseline for the jitted kernel.
    """
    from scipy.special import logsumexp
    from scipy.stats import norm

    below, above = tpe._split()
    good, bad = tpe._fit_set(below), tpe._fit_set(above)
    cand = tpe._sample_from(good, tpe.n_ei_candidates)

    def np_logpdf(fit, x):
        mu, sig, logw = fit["mu"], fit["sigma"], fit["logw"]
        z = (x[:, None, :] - mu[None, :, :]) / sig[None, :, :]
        log_phi = norm.logpdf(z) - np.log(sig[None, :, :])
        mass = norm.cdf((1 - mu) / sig) - norm.cdf((0 - mu) / sig)
        log_mass = np.log(np.clip(mass, 1e-12, 1.0))
        return logsumexp(
            log_phi - log_mass[None, :, :] + logw[None, :, :], axis=1
        )

    log_l = np_logpdf(good, cand)
    log_g = np_logpdf(bad, cand)
    k = np.maximum(tpe.cube.n_choices, 1)
    cat_idx = np.minimum((cand * k[None, :]).astype(int), (k - 1)[None, :])
    d_idx = np.arange(cand.shape[1])[None, :]
    cat_mask = tpe.cube.categorical_mask
    log_l = np.where(cat_mask[None, :], good["cat_logp"][d_idx, cat_idx], log_l)
    log_g = np.where(cat_mask[None, :], bad["cat_logp"][d_idx, cat_idx], log_g)
    scores = np.sum(log_l - log_g, axis=1)
    return cand[int(np.argmax(scores))]


def time_fn(fn, repeats: int = 20) -> float:
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append((time.perf_counter() - t0) * 1000)
    return float(np.median(times))


#: peak dense bf16 FLOP/s per chip by device-kind substring
_PEAK_FLOPS = [
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
    ("v4", 275e12), ("v6", 918e12),
]


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in kind:
            return peak
    return 0.0  # unknown device / CPU: MFU reported as 0


def transformer_train_flops(b, s, d, layers, d_ff, vocab) -> float:
    """Analytic FLOPs for one train step (fwd + bwd ≈ 3× fwd matmul FLOPs).

    Per-token matmul FLOPs: encoder layer 8d² (qkv/out) + 4·d·d_ff (ffn)
    + 4·S·d (scores+values); decoder layer adds a cross-attention block;
    readout 2·d·V per target token. Embedding gathers are ignored.
    """
    enc = layers * (8 * d * d + 4 * d * d_ff + 4 * s * d)
    dec = layers * (16 * d * d + 4 * d * d_ff + 8 * s * d)
    readout = 2 * d * vocab
    return 3.0 * b * s * (enc + dec + readout)


def bench_transformer(on_tpu: bool, seq: int = 256, batch: int = 64,
                      force_xent: str = "") -> dict:
    """Train-step time + MFU for the flagship model on the current backend.

    TPU shapes are Transformer-base (BASELINE config 4) at realistic
    sequence lengths — MFU at seq 64 measured mostly fixed overhead, which
    is not the number behind BASELINE's trials/hour north star. Attention
    rides the chunked flash path (the TPU default in
    ops/attention.attention_impl) so the O(S²) logits tensor never exists.

    ``force_xent``: the A/B control — ``"materializing"`` disables the
    blocked online-softmax xent (ops/xent.py) so the f32 (B, T, V) logits
    tensor IS materialized; ``"blocked"`` forces the blocked path even when
    the logits-bytes gate would materialize. Empty = product routing.
    The 2026-08-01 v5e A/B measured materializing FASTER at bench shapes
    (58.5 vs 65.3 ms @seq256), which is why the product gate is now
    logits-bytes, not vocab — the forced stage keeps that verdict honest
    in every future record.
    """
    import jax
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from metaopt_tpu.models import transformer as transformer_mod
    from metaopt_tpu.models.data import synthetic_seq2seq
    from metaopt_tpu.models.transformer import (
        init_sharded, make_model, make_train_step,
    )
    from metaopt_tpu.parallel.mesh import trial_mesh, use_mesh
    from metaopt_tpu.parallel.sharding import shard_batch

    if force_xent == "materializing":
        # runs in a dedicated --stage child, so the module-global poke
        # cannot leak into any other measurement
        transformer_mod._BLOCKED_XENT_MIN_LOGITS_BYTES = 1 << 62
    elif force_xent == "blocked":
        transformer_mod._BLOCKED_XENT_MIN_LOGITS_BYTES = 1
    elif force_xent:
        # a typo must not record a product-routed run under a forced tag
        raise ValueError(
            f"force_xent={force_xent!r}: expected materializing/blocked")

    if on_tpu:  # Transformer-base (BASELINE config 4 trial workload)
        cfg = {"d_model": 512, "n_heads": 8, "n_layers": 6, "d_ff": 2048,
               "vocab": 32000, "dropout": 0.1, "max_len": max(512, seq)}
    else:  # tiny stand-in so a CPU fallback run still emits the fields
        cfg = {"d_model": 64, "n_heads": 4, "n_layers": 2, "d_ff": 256,
               "vocab": 1000, "dropout": 0.1}
        batch, seq = 8, 16

    import jax.numpy as jnp

    model = make_model(cfg)
    tx = optax.adamw(1e-3)
    mesh = trial_mesh(tp=1)
    key = jax.random.PRNGKey(0)
    n_steps = 20 if on_tpu else 5
    with use_mesh(mesh):
        params, opt_state, shardings = init_sharded(
            model, mesh, tx, (batch, seq)
        )
        inner = make_train_step(model, tx)

        # the whole timed window is ONE device program (lax.scan over the
        # steps): through a tunneled runtime, a python step loop pays the
        # relay round-trip per step, which at small step times measures
        # the network, not the chip — MFU is about the chip
        def run_steps(params, opt_state, batch, key):
            def body(carry, i):
                params, opt_state = carry
                params, opt_state, loss = inner(
                    params, opt_state, batch, jax.random.fold_in(key, i)
                )
                return (params, opt_state), loss

            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state), jnp.arange(n_steps)
            )
            return params, opt_state, losses

        scanned = jax.jit(
            run_steps,
            in_shardings=(shardings[0], shardings[1],
                          NamedSharding(mesh, P("dp")), None),
            out_shardings=(shardings[0], shardings[1], None),
            donate_argnums=(0, 1),
        )
        src, tgt = synthetic_seq2seq(key, batch, seq, model.vocab)
        sharded = shard_batch(mesh, (src, tgt))
        # warm-up/compile
        params, opt_state, losses = scanned(params, opt_state, sharded, key)
        jax.block_until_ready(losses)
        t0 = time.perf_counter()
        params, opt_state, losses = scanned(
            params, opt_state, sharded, jax.random.fold_in(key, 1)
        )
        jax.block_until_ready(losses)
        dt_ms = (time.perf_counter() - t0) * 1000 / n_steps

    flops = transformer_train_flops(
        batch, seq, cfg["d_model"], cfg["n_layers"], cfg["d_ff"], cfg["vocab"]
    )
    # the step runs data-parallel over the whole mesh: peak scales with it
    peak = peak_flops(jax.devices()[0]) * mesh.size
    mfu = (flops / (dt_ms / 1000)) / peak if peak else 0.0
    from metaopt_tpu.ops.attention import attention_impl

    # one predicate, shared with loss_fn: copying the formula here is how
    # the label and the measured routing would silently desync. Forced
    # stages skip it — the gate global is poked, so it would not report
    # product routing anyway
    if force_xent:
        xent = force_xent
    else:
        with use_mesh(mesh):
            xent = ("blocked"
                    if transformer_mod.blocked_xent_enabled(
                        batch, seq, cfg["vocab"])
                    else "materializing")
    tag = f"_seq{seq}" if on_tpu else ""
    if force_xent:
        tag += "_matxent" if force_xent == "materializing" else "_blockedxent"
    return {
        f"transformer_step_ms{tag}": round(dt_ms, 3),
        f"transformer_tokens_per_s{tag}": round(batch * seq / (dt_ms / 1000)),
        f"mfu{tag}" if on_tpu else "mfu": round(mfu, 4),
        f"transformer_config{tag}": {
            **cfg, "batch": batch, "seq": seq,
            "attention": attention_impl() or "reference",
            "xent": xent,
        },
    }


def bench_resnet(on_tpu: bool) -> dict:
    """ResNet-50/CIFAR train-step time (BASELINE config 3 trial workload)."""
    import jax
    import jax.numpy as jnp
    import optax

    from metaopt_tpu.models.data import synthetic_images
    from metaopt_tpu.models.resnet import ResNet

    depth, batch = (50, 256) if on_tpu else (18, 32)
    model = ResNet(depth=depth)
    key = jax.random.PRNGKey(0)
    x, y = synthetic_images(key, batch, hw=32, channels=3)
    variables = model.init(jax.random.PRNGKey(1), x[:1], train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)
    opt_state = tx.init(params)

    def loss_fn(p, bs):
        logits, new_state = model.apply(
            {"params": p, "batch_stats": bs}, x, train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
        return loss, new_state["batch_stats"]

    # NOTE: unlike bench_transformer, this stays a python step loop — the
    # same body wrapped in lax.scan wedges the XLA:CPU compile (>400 s vs
    # 11 s for the single step; conv-heavy scan bodies are a known CPU
    # pathology), and the CPU fallback must never hang the driver. At
    # ResNet-50's ~36 ms/step the per-step dispatch RTT is a minor term.
    @jax.jit
    def step(p, bs, o):
        (loss, bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, bs)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), bs, o, loss

    params, batch_stats, opt_state, loss = step(params, batch_stats, opt_state)
    jax.block_until_ready(loss)
    n_steps = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(n_steps):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state
        )
    jax.block_until_ready(loss)
    dt_ms = (time.perf_counter() - t0) * 1000 / n_steps
    out = {
        f"resnet{depth}_step_ms": round(dt_ms, 3),
        f"resnet{depth}_images_per_s": round(batch / (dt_ms / 1000)),
    }
    # conv FLOPs come from XLA's own cost model (no hand-derived formula
    # for the CIFAR-stem ResNet variant) → an explicit resnet MFU field,
    # so nobody misreads the transformer MFU as covering this model
    try:
        cost = step.lower(params, batch_stats, opt_state).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
        peak = peak_flops(jax.devices()[0])
        if flops > 0 and peak:
            out[f"resnet{depth}_mfu"] = round(
                (flops / (dt_ms / 1000)) / peak, 4
            )
    except Exception:  # cost analysis is best-effort, never sinks the bench
        pass
    return out


def bench_profile_transformer(on_tpu: bool, seq: int = 256) -> dict:
    """A jax.profiler trace of the flagship train step, for MFU forensics.

    VERDICT r4 #3's contingency: if the blocked xent doesn't lift
    mfu_seq256 past 0.50, the record must carry the profiler evidence of
    where the remaining step time goes. The trace directory is written
    under benchmarks/results/ (left out of git — binary, tens of MB) and
    its path rides in the bench record.
    """
    import glob as _glob

    import jax

    results = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "results")
    # prune older traces first (tens of MB each; unattended watcher runs
    # must not grow disk unboundedly) — keep the newest one, plus this run
    old = sorted(
        d for d in _glob.glob(os.path.join(results, f"trace_seq{seq}_*"))
        if os.path.isdir(d)
    )
    for d in old[:-1]:
        import shutil

        shutil.rmtree(d, ignore_errors=True)
    # unique dir per run: a shared per-day dir would let a run that
    # captured nothing inherit an earlier run's files as "its" trace
    stamp = time.strftime("%Y-%m-%dT%H%M%S", time.gmtime())
    out_dir = os.path.join(results, f"trace_seq{seq}_{stamp}")
    os.makedirs(out_dir, exist_ok=True)
    jax.profiler.start_trace(out_dir)
    try:
        stats = bench_transformer(on_tpu, seq=seq, batch=16384 // seq)
    finally:
        jax.profiler.stop_trace()
    traced = _glob.glob(os.path.join(out_dir, "**", "*.trace.json.gz"),
                        recursive=True) + _glob.glob(
        os.path.join(out_dir, "**", "*.xplane.pb"), recursive=True)
    rel = os.path.relpath(out_dir, os.path.dirname(os.path.abspath(__file__)))
    return {
        f"profile_seq{seq}_trace": rel if traced else "no trace captured",
        f"profile_seq{seq}_step_ms": stats.get(
            f"transformer_step_ms_seq{seq}",
            stats.get("transformer_step_ms")),
    }


def bench_flash_pallas() -> dict:
    """Compile-and-run the REAL Pallas flash kernel (not a trivial probe).

    Runs ``ops/attention._pallas_forward`` through ``flash_attention(
    impl='pallas', interpret=False)`` at Transformer-base attention shapes,
    checks numerics against the chunked twin, and times the forward. This
    is the record ``attention_impl()``'s docstring points at before anyone
    flips the Pallas path to default-on.
    """
    import jax
    import jax.numpy as jnp

    from metaopt_tpu.ops.attention import flash_attention

    if jax.default_backend() != "tpu":
        return {"flash_pallas": {"status": "skipped-cpu"}}
    b, s, h, d = 4, 256, 8, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.bfloat16) / (d ** 0.5)
    k = jax.random.normal(ks[1], (b, s, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, s, h, d), jnp.bfloat16)

    pallas_fn = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, impl="pallas", interpret=False))
    chunked_fn = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, impl="chunked"))
    out_p = jax.block_until_ready(pallas_fn(q, k, v))    # Mosaic compile+run
    out_c = jax.block_until_ready(chunked_fn(q, k, v))
    err = float(jnp.max(jnp.abs(out_p.astype(jnp.float32)
                                - out_c.astype(jnp.float32))))
    step_ms = time_fn(lambda: jax.block_until_ready(pallas_fn(q, k, v)),
                      repeats=20)
    chunked_ms = time_fn(lambda: jax.block_until_ready(chunked_fn(q, k, v)),
                         repeats=20)

    # the two-pass Pallas BACKWARD (dKV + dQ kernels): compile via Mosaic,
    # check grads against the chunked blockwise backward, time the full
    # grad step
    def grads(impl):
        def loss(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, impl=impl, interpret=False) ** 2)
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    gp_fn, gc_fn = grads("pallas"), grads("chunked")
    gp = jax.block_until_ready(gp_fn(q, k, v))
    gc = jax.block_until_ready(gc_fn(q, k, v))
    gerr = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32)
                              - b.astype(jnp.float32))))
        for a, b in zip(gp, gc)
    )
    gstep_ms = time_fn(lambda: jax.block_until_ready(gp_fn(q, k, v)),
                       repeats=20)
    gchunked_ms = time_fn(lambda: jax.block_until_ready(gc_fn(q, k, v)),
                          repeats=20)
    return {"flash_pallas": {
        "status": "ok",
        "step_ms": round(step_ms, 3),
        "chunked_step_ms": round(chunked_ms, 3),
        "max_abs_err_vs_chunked": err,
        "bwd_step_ms": round(gstep_ms, 3),
        "bwd_chunked_step_ms": round(gchunked_ms, 3),
        "bwd_max_abs_err_vs_chunked": gerr,
        "shape": [b, s, h, d],
    }}


def probe_mosaic(timeout_s: float = 90.0) -> str:
    """Can this backend compile a Pallas (Mosaic) program? child + timeout.

    The axon relay historically hangs compiling any Mosaic program — probing
    in a disposable child turns "would wedge forever" into a recorded
    "timeout", and a future fixed relay flips this to "ok" so the Pallas
    flash path can be enabled on real TPU runs.
    """
    code = (
        "import jax, jax.numpy as jnp\n"
        "from jax.experimental import pallas as pl\n"
        "def k(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...] * 2\n"
        "x = jnp.ones((8, 128), jnp.float32)\n"
        "y = pl.pallas_call("
        "k, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)\n"
        "assert float(y[0, 0]) == 2.0\n"
    )
    rc, _ = run_with_deadline(
        [sys.executable, "-c", code], timeout_s=timeout_s, poll_s=1.0
    )
    if rc is None:
        return "timeout"
    return "ok" if rc == 0 else "error"


def main() -> None:
    # persistent XLA cache, shared with the dryrun and inherited by the
    # model-stage children: repeat bench runs skip the remote compiles
    # (r2 measured executable serialization working through the relay).
    # Set BEFORE the preflight: its CPU-fallback path imports jax, and jax
    # binds these env vars at import time
    setup_xla_cache()
    # 3 probes over ~3.5 min: the relay wedge is sometimes transient, and a
    # TPU number in the driver's record is worth the wait — but a CPU
    # fallback run must then stay slim (TPE-only, under a minute)
    preflight_backend(timeout_s=60.0, retries=3)
    import jax

    on_tpu = jax.default_backend() == "tpu"
    # CPU fallback runs exist only to prove liveness — keep them under a
    # minute; the TPU path keeps the full sample counts
    r = (lambda n: n) if on_tpu else (lambda n: max(n // 3, 2))

    n_obs = 10_000
    pool = 8  # a producer pool: one fused kernel launch + one readback
    tpe = build_tpe(n_obs)

    # warm-up: compile the kernels for these padded shapes
    tpe.suggest(pool)
    tpe._suggest_one_ei()
    pool_ms = time_fn(lambda: tpe.suggest(pool), repeats=r(20))
    jax_ms = pool_ms / pool
    # amortized single-suggest: a full prefetch cycle (one launch +
    # pool_prefetch-1 cache pops) divided by the points served — the cost a
    # worker asking for one point at a time actually pays per point — vs
    # the raw one-launch-per-point path
    pp = tpe.pool_prefetch
    single_ms = time_fn(
        lambda: [tpe._suggest_one_ei() for _ in range(pp)], repeats=r(10)
    ) / pp
    single_uncached_ms = time_fn(lambda: tpe._launch_ei(1), repeats=r(10))

    # the worker-visible "uncached" cost: observe() fires a speculative
    # pool refill, the worker spends ≥100 ms on ledger RPCs + subprocess
    # teardown before its next ask, and suggest(1) blocks only on whatever
    # of the launch+readback is still in flight
    from metaopt_tpu.ledger.trial import Trial

    def _completed(params, objective):
        t = Trial(params=params, experiment="bench")
        t.lineage = tpe.space.hash_point(params)
        t.transition("reserved")
        t.attach_results(
            [{"name": "o", "type": "objective", "value": objective}]
        )
        t.transition("completed")
        return t

    def _observe_gap_suggest(i):
        pt = tpe.space.sample(1, seed=100_000 + i)[0]
        tpe.observe([_completed(pt, float(i))])
        time.sleep(0.1)
        t0 = time.perf_counter()
        tpe.suggest(1)
        return (time.perf_counter() - t0) * 1000

    after_observe_ms = float(np.median(
        [_observe_gap_suggest(i) for i in range(r(10))]
    ))

    # transfer/launch telemetry: steady-state device traffic of one
    # observe→suggest cycle. Before the incremental buffers every fit
    # re-uploaded the whole padded (N, d) matrix — O(N·d) ≈ 440 KB per
    # suggest at 10k obs on this space; the device-resident buffer appends
    # one donated row per observe, O(d) bytes
    tel0 = tpe.telemetry()
    tel_cycles = r(10)
    for i in range(tel_cycles):
        pt = tpe.space.sample(1, seed=200_000 + i)[0]
        tpe.observe([_completed(pt, float(1000 + i))])
        tpe.suggest(pool)
    t = tpe._refill_thread
    if t is not None:
        t.join(timeout=60)  # settle in-flight speculative launches
    tel1 = tpe.telemetry()
    h2d_per_suggest = (tel1["h2d_bytes"] - tel0["h2d_bytes"]) / tel_cycles
    launches_per_suggest = (
        tel1["kernel_launches"] - tel0["kernel_launches"]) / tel_cycles
    # speculative suggest-ahead effectiveness over the whole TPE run:
    # fraction of suggest() calls answered from a banked pool
    tpe_hits = tel1.get("prefetch_hits", 0)
    tpe_served = tpe_hits + tel1.get("prefetch_misses", 0)
    tpe_hit_rate = (round(tpe_hits / tpe_served, 3) if tpe_served else None)
    from metaopt_tpu.ops.tpe_math import pad_pow2 as _pad_pow2

    d_dims = tpe.cube.n_dims
    rebuild_bytes = _pad_pow2(len(tpe._y) + 1) * (d_dims + 1) * 4

    # the reference substrate refits + rescores per suggestion (host numpy)
    numpy_ms = time_fn(lambda: numpy_ei_reference(tpe), repeats=r(5))

    # flatness check: per-suggestion latency at 1k vs 10k observations
    tpe1k = build_tpe(1_000)
    tpe1k.suggest(pool)
    jax_1k_ms = time_fn(lambda: tpe1k.suggest(pool), repeats=r(20)) / pool
    flat_16k = {}
    if on_tpu:
        # the north star claims per-suggestion cost flat PAST 10k — put
        # 16k AND 32k points on the record (TPU only: a CPU fallback run
        # must stay slim, and the claim is about the chip)
        for n in (16_000, 32_000):
            tpe_n = build_tpe(n)
            tpe_n.suggest(pool)
            jax_n_ms = time_fn(lambda: tpe_n.suggest(pool),
                               repeats=r(10)) / pool
            k = f"{n // 1000}k"
            flat_16k[f"jax_{k}_obs_ms_per_point"] = round(jax_n_ms, 3)
            flat_16k[f"flatness_{k}_over_1k"] = round(
                jax_n_ms / max(jax_1k_ms, 1e-9), 2)
        # the headline 10k window runs FIRST, possibly minutes after the
        # relay recovered from an hours-long wedge — 2026-08-01 its median
        # read 18.2 ms while the larger 16k/32k fits measured ~10 ms later
        # in the same run. Re-measure BOTH ratio legs on the now-warm relay
        # and report the re-warmed steady-state medians UNCONDITIONALLY —
        # min-of-two would let a lucky first window survive as the headline
        # while a wedge-inflated one is replaced, a one-sided filter. The
        # first-window medians stay on the record under side keys so the
        # relay's warm-up behaviour remains observable across rounds.
        flat_16k["tpe_10k_first_window_ms_per_point"] = round(jax_ms, 3)
        jax_ms = time_fn(lambda: tpe.suggest(pool), repeats=r(20)) / pool
        flat_16k["tpe_1k_first_window_ms_per_point"] = round(jax_1k_ms, 3)
        jax_1k_ms = time_fn(lambda: tpe1k.suggest(pool),
                            repeats=r(20)) / pool
        for n in (16_000, 32_000):
            k = f"{n // 1000}k"
            flat_16k[f"flatness_{k}_over_1k"] = round(
                flat_16k[f"jax_{k}_obs_ms_per_point"]
                / max(jax_1k_ms, 1e-9), 2)
    # -- GP-BO: incremental-Cholesky fast path vs the legacy cold refit --
    # per-suggest cost of the worker cycle (observe one, ask one) with the
    # device-resident factor extended rank-1 per append, against
    # incremental=False (full MLL refit + full factorization per launch —
    # the pre-fast-path behaviour). Speculation is DISABLED on both so the
    # timed suggest pays its launch inline; the prefetch win is measured
    # separately below as a hit rate. CPU fallback sizes down to 1k obs
    # (side keys carry the reduced-n name); BENCH_GP_FULL=1 forces 10k
    gp_stats = {}
    try:
        gp_full = on_tpu or bool(os.environ.get("BENCH_GP_FULL"))
        gp_n = 10_000 if gp_full else 1_000
        key_n = f"{gp_n // 1000}k"

        def _completed_on(algo, params, objective):
            t = Trial(params=params, experiment="bench-gp")
            t.lineage = algo.space.hash_point(params)
            t.transition("reserved")
            t.attach_results(
                [{"name": "o", "type": "objective", "value": objective}]
            )
            t.transition("completed")
            return t

        def _gp_cycle(gp, i, base):
            pt = gp.space.sample(1, seed=base + i)[0]
            gp.observe([_completed_on(gp, pt, float(i))])
            t0 = time.perf_counter()
            gp.suggest(1)
            return (time.perf_counter() - t0) * 1000.0

        gp_inc = build_gpbo(gp_n)
        gp_cold = build_gpbo(gp_n, incremental=False)
        for gp in (gp_inc, gp_cold):
            gp._suggest_ahead_async = lambda: None
            gp.suggest(1)  # compile + first factor at this padded shape
        inc_ms = float(np.median(
            [_gp_cycle(gp_inc, i, 300_000) for i in range(r(12))]))
        cold_ms = float(np.median(
            [_gp_cycle(gp_cold, i, 400_000)
             for i in range(max(r(12) // 3, 2))]))
        gp_stats[f"gp_suggest_ms_per_point_{key_n}_obs"] = round(inc_ms, 3)
        gp_stats[f"gp_full_refit_ms_per_point_{key_n}_obs"] = round(
            cold_ms, 3)
        gp_stats["gp_incremental_speedup_vs_full_refit"] = round(
            cold_ms / max(inc_ms, 1e-9), 2)
        gp_stats.update({f"gp_{k}": v
                         for k, v in gp_inc._factor.telemetry().items()})

        # prefetch effectiveness: speculation ON, the worker-gap cycle —
        # observe() banks the next pool while the worker is away, so
        # suggest(1) blocks only on whatever launch is still in flight
        gp_hot = build_gpbo(gp_n, suggest_prefetch_depth=2)
        gp_hot.suggest(1)

        def _gp_hot_cycle(i):
            pt = gp_hot.space.sample(1, seed=500_000 + i)[0]
            gp_hot.observe([_completed_on(gp_hot, pt, float(i))])
            time.sleep(0.1)
            t0 = time.perf_counter()
            gp_hot.suggest(1)
            return (time.perf_counter() - t0) * 1000.0

        hot_ms = float(np.median([_gp_hot_cycle(i) for i in range(r(10))]))
        gp_hot.drain_suggest_ahead()
        ahead = gp_hot.suggest_ahead_telemetry()
        served = ahead["prefetch_hits"] + ahead["prefetch_misses"]
        gp_stats["gp_suggest_after_observe_100ms_gap_ms"] = round(hot_ms, 3)
        if served:
            gp_stats["gp_prefetch_hit_rate"] = round(
                ahead["prefetch_hits"] / served, 3)
    except Exception as err:  # the TPE headline must survive a GP break
        gp_stats["gp_bench_error"] = f"{type(err).__name__}: {err}"

    model_stats = {}
    # CPU fallback = TPE-only: model steps on CPU produce mfu 0.0 noise and
    # burn minutes of driver budget nobody wants; the TPU story rides along
    # from the last committed TPU run instead
    stages = (
        ("transformer-256", "transformer-512", "transformer-1024",
         "xent-256", "xent-512", "xent-1024",
         "resnet", "flash", "profile-256")
        if on_tpu else ()
    )
    for name in stages:
        # each model bench runs in a child with a deadline: a wedged
        # remote-compile must degrade to a recorded timeout, not sink the
        # TPE metric (or hang the driver). The forensic profile stage
        # gets a tighter budget — it runs last and must never be the
        # stage that pushes the whole bench past an outer deadline
        # 600 s: a COLD remote compile through the relay runs minutes, and
        # the xent-gate change makes the default-routing stages fresh
        # programs on their first post-change run; worst case stays inside
        # the watcher's 7200 s bench deadline (8×600 + 240 + TPE section)
        rc, out = run_with_deadline(
            [sys.executable, os.path.abspath(__file__), "--stage", name],
            timeout_s=240.0 if name.startswith("profile-") else 600.0,
            capture=True,
        )
        parsed = None
        if rc == 0:
            # stderr is merged into the capture and runtime teardown may
            # chatter after the JSON line — scan for the line that parses
            for line in reversed(out.strip().splitlines()):
                try:
                    candidate = json.loads(line)
                except ValueError:
                    continue
                if isinstance(candidate, dict):  # not a stray scalar line
                    parsed = candidate
                    break
        if isinstance(parsed, dict):
            # a stage child whose OWN preflight degraded to CPU exits 0
            # with CPU-shaped keys — that is a failed capture, not data
            # (the relay can die between our init and the child's)
            if parsed.pop("stage_backend", "tpu") != "tpu":
                model_stats[f"{name}_bench_error"] = "stage degraded to cpu"
                continue
            model_stats.update(parsed)
            continue
        model_stats[f"{name}_bench_error"] = (
            "stage timeout (compile wedged?)" if rc is None
            else f"rc={rc}: {out[-200:]}"
        )
    if on_tpu:
        mosaic = probe_mosaic()
    else:
        mosaic = "skipped-cpu"
        model_stats.update(last_good_tpu_record())

    # coordinator control-plane throughput: fused worker_cycle path at 32
    # threaded workers (benchmarks/coord_scale.py). Host-CPU-bound, so it
    # is measured live on every run regardless of accelerator substrate;
    # median of 3 to ride out one-core scheduler jitter
    coord_stats = {}
    try:
        from benchmarks.coord_scale import run_scale as coord_run_scale

        # the binary-vs-JSON pair is interleaved WITHIN each repeat with
        # alternating order (a long-lived process speeds up run over run,
        # so sequential batches would hand the later codec a systematic
        # advantage — the same discipline coord_scale.py's own repeat
        # loop applies); the speedup is the median of per-repeat ratios
        coord_pairs = []
        for r in range(3):
            rep = {}
            for w in (("auto", "v1") if r % 2 == 0 else ("v1", "auto")):
                rep[w] = coord_run_scale(32, "fused", trials_per_worker=16,
                                         wire=w)
            coord_pairs.append((rep["auto"], rep["v1"]))
        coord_reps = sorted((f for f, _ in coord_pairs),
                            key=lambda row: row["trials_per_s"] or 0)
        coord_row = coord_reps[1]
        coord_stats["coord_trials_per_s_32w"] = coord_row["trials_per_s"]
        coord_stats["coord_rpcs_per_trial_32w"] = coord_row["rpcs_per_trial"]
        coord_stats["coord_wire_bytes_per_trial"] = (
            coord_row.get("wire_bytes_per_trial"))
        if coord_row.get("wire") == "v2":
            ratios = sorted(
                f["trials_per_s"] / j["trials_per_s"]
                for f, j in coord_pairs
                if f["trials_per_s"] and j["trials_per_s"])
            if ratios:
                coord_stats["coord_wire_speedup_32w"] = round(
                    ratios[len(ratios) // 2], 2)

        # durability tax + recovery: same fused path with the WAL under
        # it (group-commit fsync before every ack), then a cold restart
        # replaying a 2000-record WAL. Same median-of-3 discipline; the
        # overhead pct pairs this run's OWN fused median so one-core
        # scheduler drift between sessions cancels out
        wal_reps = sorted(
            (coord_run_scale(32, "fused+wal", trials_per_worker=16)
             for _ in range(3)),
            key=lambda row: row["trials_per_s"] or 0,
        )
        wal_tps = wal_reps[1]["trials_per_s"]
        if coord_row["trials_per_s"] and wal_tps:
            coord_stats["coord_wal_overhead_pct"] = round(
                100.0 * (1.0 - wal_tps / coord_row["trials_per_s"]), 1)

        from benchmarks.coord_scale import run_recovery as coord_run_recovery

        coord_stats["coord_recovery_time_s"] = coord_run_recovery(
            trials=2000)["recovery_s"]

        # live hand-off + failover latency on a 2-shard pod (lower is
        # better; informational until a committed baseline carries them)
        from benchmarks.coord_scale import run_handoff as coord_run_handoff

        handoff_row = coord_run_handoff()
        coord_stats["coord_handoff_ms"] = handoff_row["coord_handoff_ms"]
        coord_stats["coord_failover_time_s"] = (
            handoff_row["coord_failover_time_s"])

        # race-detector tax (informational, never gated): the same fused
        # path under full dynrace instrumentation — what `mtpu race
        # --suite coord` costs, paired against this run's OWN fused
        # median like the WAL overhead above
        from metaopt_tpu.analysis import dynrace
        from metaopt_tpu.analysis.registry import (default_config,
                                                   default_race_config)

        monitor = dynrace.monitored_classes(default_config(),
                                            default_race_config())

        def _raced_run():
            rt = dynrace.RaceRuntime(monitor)
            with dynrace.instrument(rt):
                return coord_run_scale(32, "fused", trials_per_worker=16)

        race_reps = sorted((_raced_run() for _ in range(3)),
                           key=lambda row: row["trials_per_s"] or 0)
        race_tps = race_reps[1]["trials_per_s"]
        if coord_row["trials_per_s"] and race_tps:
            coord_stats["coord_race_overhead_pct"] = round(
                100.0 * (1.0 - race_tps / coord_row["trials_per_s"]), 1)

        # sharded deployment: subprocess shards (one WAL each) behind the
        # consistent-hash map. The workload spreads 4 experiments across
        # the shards; the overhead pct pairs the 1-shard figure against
        # this run's OWN in-process fused+wal at the SAME multi-experiment
        # workload (same durability, same run — ratio doctrine). On the
        # one-core CI box shard2/shard4 time-slice a single core, so their
        # absolute numbers are informational; the gated figure is the
        # 1-shard process tax
        shard_base_reps = sorted(
            (coord_run_scale(32, "fused+wal", trials_per_worker=16,
                             experiments=4)
             for _ in range(3)),
            key=lambda row: row["trials_per_s"] or 0,
        )
        shard_base_tps = shard_base_reps[1]["trials_per_s"]
        for n_shards in (1, 2, 4):
            shard_reps = sorted(
                (coord_run_scale(32, "sharded", trials_per_worker=16,
                                 shards=n_shards, experiments=4)
                 for _ in range(3)),
                key=lambda row: row["trials_per_s"] or 0,
            )
            shard_tps = shard_reps[1]["trials_per_s"]
            coord_stats[f"coord_trials_per_s_shard{n_shards}"] = shard_tps
            if n_shards == 1 and shard_base_tps and shard_tps:
                coord_stats["coord_shard_overhead_pct"] = round(
                    100.0 * (1.0 - shard_tps / shard_base_tps), 1)

        # multi-tenant service plane at the full 1k-experiment fleet
        # (benchmarks/coord_scale.py run_multitenant): fairness under a
        # hot tenant, evicted-vs-resident RSS (fresh subprocesses), and
        # the warm-vs-cold transfer-prior study. Single shot — the
        # fairness/residency/transfer figures are acceptance bars with
        # wide margins, not drift-sensitive medians
        from benchmarks.coord_scale import run_multitenant

        mt_row = run_multitenant(experiments=1000)
        for mt_key in ("coord_trials_per_s_1k_exp", "coord_fairness_jain_1k",
                       "coord_evict_rss_mb", "coord_resident_rss_mb",
                       "coord_evict_rss_ratio", "coord_evictions_1k",
                       "coord_hydrations_1k", "status_scan_ms_1k",
                       "transfer_warm_trials_ratio",
                       "transfer_time_to_good_s", "transfer_cold_time_s"):
            if mt_row.get(mt_key) is not None:
                coord_stats[mt_key] = mt_row[mt_key]

        # fleet-fused suggest plane: same-run fused-vs-serial at the
        # 256-resident TPE fleet (benchmarks/coord_scale.py
        # run_fused_suggest). Both legs share one process and one fit
        # state, alternating order round to round, so the speedup is a
        # paired ratio — the gated figure plus the launch-count
        # telemetry that proves the O(buckets) claim
        from benchmarks.coord_scale import run_fused_suggest

        fs_row = run_fused_suggest(residents=256, bucket_max=32)
        for fs_key in ("fleet_suggest_speedup", "suggest_launches_per_tick",
                       "serial_launches_per_tick", "buckets_per_tick",
                       "bucket_occupancy"):
            if fs_row.get(fs_key) is not None:
                coord_stats[fs_key] = fs_row[fs_key]
    except Exception as err:  # the TPE headline must survive a coord break
        coord_stats["coord_bench_error"] = f"{type(err).__name__}: {err}"

    # batched trial evaluation: a pool of k trials as ONE jitted vmap
    # program vs k per-trial launches of the same math through
    # InProcessExecutor (benchmarks/batch_eval.py). The speedup pairs
    # both sides from THIS run (same-run ratio doctrine), and the
    # launch-count telemetry under it confirms the pooled side really is
    # one device program per pool. Dispatch-bound, so it is measured live
    # on every run like the coord stats
    batch_stats = {}
    try:
        from benchmarks.batch_eval import run_batch_eval

        for bpool in (8, 64):
            brow = run_batch_eval(bpool, reps=5)
            batch_stats[f"batch_eval_trials_per_s_pool{bpool}"] = (
                brow["batched_trials_per_s"])
            if bpool == 64:
                batch_stats["batch_eval_serial_trials_per_s"] = (
                    brow["serial_trials_per_s"])
                batch_stats["batch_eval_speedup"] = brow["speedup"]
                batch_stats["batch_eval_launches_per_pool"] = (
                    brow["launches_per_pool"])
    except Exception as err:  # and survive a batch-eval break too
        batch_stats["batch_eval_bench_error"] = f"{type(err).__name__}: {err}"

    # the xent A/B verdict: blocked-loss step-time win per seq (>1 = the
    # blocked online-softmax xent is faster than materializing (B, T, V)).
    # The default stage measures product routing (materializing at bench
    # shapes, per the logits-bytes gate); the xent- stage forces blocked
    for s in (256, 512, 1024):
        mat_ms = model_stats.get(f"transformer_step_ms_seq{s}")
        blocked_ms = model_stats.get(f"transformer_step_ms_seq{s}_blockedxent")
        routed = model_stats.get(f"transformer_config_seq{s}", {})
        if mat_ms and blocked_ms and routed.get("xent") == "materializing":
            model_stats[f"xent_blocked_step_speedup_seq{s}"] = round(
                mat_ms / blocked_ms, 3)

    from metaopt_tpu.utils.provenance import provenance

    result = {
        "metric": "tpe_suggest_ms_per_point_10k_obs_pool8",
        "value": round(jax_ms, 3),
        "unit": "ms",
        "vs_baseline": round(numpy_ms / jax_ms, 2),
        **provenance(),
        "extra": {
            "numpy_reference_ms_per_point": round(numpy_ms, 3),
            "single_suggest_ms": round(single_ms, 3),
            "single_suggest_uncached_ms": round(single_uncached_ms, 3),
            "suggest_after_observe_100ms_gap_ms": round(after_observe_ms, 3),
            "h2d_bytes_per_suggest": round(h2d_per_suggest, 1),
            "kernel_launches_per_suggest": round(launches_per_suggest, 2),
            "h2d_bytes_full_rebuild_equiv": rebuild_bytes,
            "jax_1k_obs_ms_per_point": round(jax_1k_ms, 3),
            "flatness_10k_over_1k": round(jax_ms / max(jax_1k_ms, 1e-9), 2),
            **({"tpe_prefetch_hit_rate": tpe_hit_rate}
               if tpe_hit_rate is not None else {}),
            **flat_16k,
            **gp_stats,
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
            "mosaic_compile_probe": mosaic,
            **model_stats,
            **coord_stats,
            **batch_stats,
        },
    }
    # Full record goes to a file; stdout gets ONE compact line. The driver
    # keeps only a bounded TAIL of output, so a giant single-line record
    # gets its head (the "{"metric": ..." part) truncated and parses as
    # nothing — exactly what r3's record died of.
    backend = result["extra"]["backend"]
    stamp = time.strftime("%Y-%m-%d", time.gmtime())
    results_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "benchmarks", "results")
    os.makedirs(results_dir, exist_ok=True)
    artifact = os.path.join(results_dir, f"bench_{backend}_{stamp}.json")
    with open(artifact, "w") as f:
        json.dump(result, f, indent=1)
    print(f"full record: {artifact}", flush=True)

    # headline fields ride in the compact line; on a CPU-fallback run they
    # come from the newest committed TPU artifact instead of the live run
    src = result["extra"]
    tpu_record_from = "live"
    value_tpu_last_good = None
    if backend != "tpu" and isinstance(src.get("last_good_tpu"), dict):
        # the cross-round `value` series must not silently flip substrate:
        # a CPU-fallback run says so (stale) and carries the TPU value it
        # would have refreshed, so drivers comparing `value` across rounds
        # compare like with like (VERDICT r4 weak #3)
        value_tpu_last_good = src["last_good_tpu"].get("value")
        src = src["last_good_tpu"].get("extra", src["last_good_tpu"])
        tpu_record_from = "last_good:" + str(
            result["extra"].get("last_good_tpu_file"))
    compact = {
        "metric": result["metric"],
        "value": result["value"],
        "unit": result["unit"],
        "vs_baseline": result["vs_baseline"],
        "backend": backend,
        # a TPU run whose model stages all deadlined still exits 0 — the
        # stage-error count lets consumers (watch_tpu.py) reject a gutted
        # capture instead of checkpointing it as done. The profile stage
        # is forensic garnish, not measurement: its failure must not void
        # an otherwise-complete capture
        "stage_errors": sum(1 for k in result["extra"]
                            if k.endswith("_bench_error")
                            and not k.startswith("profile-")),
        "commit": result.get("commit"),
        "artifact": os.path.relpath(
            artifact, os.path.dirname(os.path.abspath(__file__))),
        "tpu_record_from": tpu_record_from,
    }
    if value_tpu_last_good is not None:
        compact["value_tpu_last_good"] = value_tpu_last_good
    # staleness is PER METRIC, not global: on a CPU-fallback run only the
    # rows carried from the last-good TPU artifact are stale — everything
    # measured live this run (the headline `value`, the control-plane
    # coord_* keys below) is fresh, it just says backend=cpu. The old
    # global `stale: backend != "tpu"` flag branded live CPU measurements
    # (e.g. tpe_suggest_ms_per_point_10k_obs_pool8 in r05) as stale.
    stale_keys = []
    if value_tpu_last_good is not None:
        stale_keys.append("value_tpu_last_good")
    for key in ("mfu_seq256", "mfu_seq512", "mfu_seq1024", "resnet50_mfu",
                # pre-gate-change records (xent routing measured 2026-08-01)
                # carry the product-routing MFU under the _matxent A/B tag;
                # surface it so a carried last_good still shows the number
                # the current code would produce
                "mfu_seq256_matxent", "mfu_seq512_matxent",
                "mfu_seq1024_matxent",
                "xent_blocked_step_speedup_seq256",
                "xent_blocked_step_speedup_seq512",
                "xent_blocked_step_speedup_seq1024",
                "flatness_16k_over_1k", "flatness_32k_over_1k",
                "h2d_bytes_per_suggest", "kernel_launches_per_suggest",
                "gp_suggest_ms_per_point_10k_obs",
                "tpe_prefetch_hit_rate",
                "transformer_tokens_per_s_seq512", "resnet50_images_per_s",
                "flash_vs_chunked_crossover"):
        if key in src:
            compact[key] = src[key]
            if tpu_record_from != "live":
                stale_keys.append(key)
    # control-plane keys come from the LIVE extra, not the last-good TPU
    # record: they are host-CPU metrics, fresh on every run. The GP ratio
    # keys ride here too — the incremental-vs-full-refit speedup and hit
    # rate are measured live on whatever substrate this run has (a CPU
    # fallback carries them under the reduced-n side keys)
    for key in ("coord_trials_per_s_32w", "coord_rpcs_per_trial_32w",
                "coord_wal_overhead_pct", "coord_race_overhead_pct",
                "coord_recovery_time_s",
                "coord_handoff_ms", "coord_failover_time_s",
                "coord_trials_per_s_shard1", "coord_trials_per_s_shard2",
                "coord_trials_per_s_shard4", "coord_shard_overhead_pct",
                "gp_suggest_ms_per_point_1k_obs",
                "gp_full_refit_ms_per_point_1k_obs",
                "gp_incremental_speedup_vs_full_refit",
                "gp_prefetch_hit_rate",
                "batch_eval_trials_per_s_pool8",
                "batch_eval_trials_per_s_pool64",
                "batch_eval_speedup", "batch_eval_launches_per_pool",
                "coord_trials_per_s_1k_exp", "coord_fairness_jain_1k",
                "coord_evict_rss_ratio", "transfer_warm_trials_ratio",
                "fleet_suggest_speedup", "suggest_launches_per_tick"):
        if key in result["extra"]:
            compact[key] = result["extra"][key]
    # `stale` keeps its warn-never-fail contract for consumers that only
    # look at the flag; `stale_keys` names exactly which rows it covers
    compact["stale"] = bool(stale_keys)
    compact["stale_keys"] = sorted(stale_keys)
    print(json.dumps(compact))


def stage_main(name: str) -> None:
    """Child entry: run one model bench, print its stats as one JSON line."""
    preflight_backend()
    import jax

    on_tpu = jax.default_backend() == "tpu"
    if name.startswith("transformer"):
        seq = int(name.split("-")[1]) if "-" in name else 256
        # equal token count per step (16k): batch trades off against seq
        stats = bench_transformer(on_tpu, seq=seq, batch=16384 // seq)
    elif name.startswith("xent-"):
        # the A/B control: same shapes, blocked xent FORCED — product
        # routing materializes at these shapes (the measured-faster path),
        # so the forced stage is what keeps the blocked kernel measured
        seq = int(name.split("-")[1])
        stats = bench_transformer(on_tpu, seq=seq, batch=16384 // seq,
                                  force_xent="blocked")
    elif name.startswith("profile-"):
        stats = bench_profile_transformer(on_tpu, seq=int(name.split("-")[1]))
    elif name == "resnet":
        stats = bench_resnet(on_tpu)
    elif name == "flash":
        stats = bench_flash_pallas()
    else:
        raise SystemExit(f"unknown stage {name!r}")
    # the parent checks this observed stamp: its own preflight passing
    # says nothing about THIS child's (the relay can wedge in between)
    stats["stage_backend"] = jax.default_backend()
    print(json.dumps(stats))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--stage":
        stage_main(sys.argv[2])
    else:
        main()
