#!/usr/bin/env python
"""Benchmark: the BASELINE north-star hot path.

Measures TPE ``suggest()`` latency with 10 000 observations on an 8-dim mixed
space — the operation BASELINE.md requires to stay flat past 10k trials — with
the density kernel XLA-compiled on the real TPU chip, and compares against a
faithful numpy implementation of the exact same Parzen/EI math (the
reference's implementation substrate: pure Python/numpy, SURVEY.md §2.9).

Prints ONE JSON line:
    {"metric": "tpe_suggest_p50_ms_10k_obs", "value": <ms>, "unit": "ms",
     "vs_baseline": <numpy_ms / jax_ms speedup>}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np


def preflight_backend(timeout_s: float = 90.0) -> None:
    """Fall back to CPU if the TPU backend is unreachable.

    The axon relay is single-slot and can wedge (a stuck claim makes ANY
    ``import jax`` with PALLAS_AXON_POOL_IPS set hang indefinitely). Probe
    it in a disposable subprocess first; on failure, scrub the axon env so
    this process measures on CPU instead of hanging the driver.
    """
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        return
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return
    # Popen + poll, NOT subprocess.run(timeout=...): run()'s post-timeout
    # cleanup waits on the child, and a child wedged inside the relay claim
    # can be unwaitable — the guard itself would hang. Kill and move on.
    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()[0]"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        rc = proc.poll()
        if rc is not None:
            if rc == 0:
                return
            break
        time.sleep(1.0)
    else:
        proc.kill()
        try:  # non-blocking reap; a relay-wedged child may be unwaitable
            proc.wait(timeout=2.0)
        except subprocess.TimeoutExpired:
            pass
    print("bench preflight: TPU backend unreachable; measuring on CPU",
          file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    # the axon sitecustomize imports jax at interpreter startup, so the env
    # var above is snapshotted too late — re-apply via the live config
    # (safe: no backend has been initialized yet in this process)
    import jax

    jax.config.update("jax_platforms", "cpu")


def build_tpe(n_obs: int, seed: int = 0):
    from metaopt_tpu.algo import TPE
    from metaopt_tpu.space import build_space

    space = build_space(
        {
            "lr": "loguniform(1e-5, 1e-1)",
            "wd": "loguniform(1e-6, 1e-2)",
            "width": "uniform(32, 1024, discrete=True)",
            "depth": "uniform(1, 12, discrete=True)",
            "dropout": "uniform(0.0, 0.5)",
            "momentum": "uniform(0.5, 0.999)",
            "opt": "choices(['adam', 'sgd', 'lamb'])",
            "schedule": "choices(['cosine', 'linear', 'constant'])",
        }
    )
    tpe = TPE(space, seed=seed, n_initial_points=8)
    rng = np.random.default_rng(seed)
    X = rng.random((n_obs, tpe.cube.n_dims))
    y = rng.random(n_obs).tolist()
    tpe._X = list(X)
    tpe._y = y
    tpe._observed = {str(i): y[i] for i in range(n_obs)}
    return tpe


def numpy_ei_reference(tpe) -> float:
    """The same split/fit/sample/score pipeline with numpy densities.

    This is what the reference-era implementation does per suggest call
    (Python/numpy KDE evaluation); timing it on the same data is the
    apples-to-apples baseline for the jitted kernel.
    """
    from scipy.special import logsumexp
    from scipy.stats import norm

    below, above = tpe._split()
    good, bad = tpe._fit_set(below), tpe._fit_set(above)
    cand = tpe._sample_from(good, tpe.n_ei_candidates)

    def np_logpdf(fit, x):
        mu, sig, logw = fit["mu"], fit["sigma"], fit["logw"]
        z = (x[:, None, :] - mu[None, :, :]) / sig[None, :, :]
        log_phi = norm.logpdf(z) - np.log(sig[None, :, :])
        mass = norm.cdf((1 - mu) / sig) - norm.cdf((0 - mu) / sig)
        log_mass = np.log(np.clip(mass, 1e-12, 1.0))
        return logsumexp(
            log_phi - log_mass[None, :, :] + logw[None, :, :], axis=1
        )

    log_l = np_logpdf(good, cand)
    log_g = np_logpdf(bad, cand)
    k = np.maximum(tpe.cube.n_choices, 1)
    cat_idx = np.minimum((cand * k[None, :]).astype(int), (k - 1)[None, :])
    d_idx = np.arange(cand.shape[1])[None, :]
    cat_mask = tpe.cube.categorical_mask
    log_l = np.where(cat_mask[None, :], good["cat_logp"][d_idx, cat_idx], log_l)
    log_g = np.where(cat_mask[None, :], bad["cat_logp"][d_idx, cat_idx], log_g)
    scores = np.sum(log_l - log_g, axis=1)
    return cand[int(np.argmax(scores))]


def time_fn(fn, repeats: int = 20) -> float:
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append((time.perf_counter() - t0) * 1000)
    return float(np.median(times))


def main() -> None:
    preflight_backend()
    import jax

    n_obs = 10_000
    pool = 8  # a producer pool: one fused kernel launch + one readback
    tpe = build_tpe(n_obs)

    # warm-up: compile the kernels for these padded shapes
    tpe.suggest(pool)
    tpe._suggest_one_ei()
    pool_ms = time_fn(lambda: tpe.suggest(pool), repeats=20)
    jax_ms = pool_ms / pool
    single_ms = time_fn(tpe._suggest_one_ei, repeats=20)

    # the reference substrate refits + rescores per suggestion (host numpy)
    numpy_ms = time_fn(lambda: numpy_ei_reference(tpe), repeats=5)

    # flatness check: per-suggestion latency at 1k vs 10k observations
    tpe1k = build_tpe(1_000)
    tpe1k.suggest(pool)
    jax_1k_ms = time_fn(lambda: tpe1k.suggest(pool), repeats=20) / pool

    result = {
        "metric": "tpe_suggest_ms_per_point_10k_obs_pool8",
        "value": round(jax_ms, 3),
        "unit": "ms",
        "vs_baseline": round(numpy_ms / jax_ms, 2),
        "extra": {
            "numpy_reference_ms_per_point": round(numpy_ms, 3),
            "single_suggest_ms": round(single_ms, 3),
            "jax_1k_obs_ms_per_point": round(jax_1k_ms, 3),
            "flatness_10k_over_1k": round(jax_ms / max(jax_1k_ms, 1e-9), 2),
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
