"""workon: the worker main loop.

ref: src/metaopt/core/worker/__init__.py (SURVEY.md §2.1): produce → reserve
→ consume until the experiment is done; KeyboardInterrupt marks the in-flight
trial interrupted. Additions over the reference: throttled stale-reservation
release (pacemaker doctrine — every ``stale_sweep_interval_s``, and always
on the first cycle), per-worker trial caps (``worker_trials``), idle backoff
when the algorithm is barrier-blocked (Hyperband rung waits), and the
judge/early-stop wiring into the executor.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from metaopt_tpu.algo.base import BaseAlgorithm, make_algorithm
from metaopt_tpu.executor.base import Executor
from metaopt_tpu.ledger.experiment import Experiment
from metaopt_tpu.ledger.trial import Trial
from metaopt_tpu.worker.producer import Producer, RemoteProducer

log = logging.getLogger(__name__)


@dataclass
class WorkerStats:
    reserved: int = 0
    completed: int = 0
    broken: int = 0
    interrupted: int = 0
    pruned: int = 0
    suspended: int = 0
    #: trials bounced back to 'new' after an infrastructure failure
    #: (executor set ExecutionResult.requeue) — retried, not lost
    requeued: int = 0
    idle_cycles: int = 0
    events: List[Dict[str, Any]] = field(default_factory=list)
    #: producer timing aggregates (observe/suggest latency, SURVEY.md §5)
    producer_timings: Dict[str, float] = field(default_factory=dict)


def workon(
    experiment: Experiment,
    executor: Executor,
    worker_id: str = "worker-0",
    algorithm: Optional[BaseAlgorithm] = None,
    worker_trials: Optional[int] = None,
    max_broken: Optional[int] = 10,
    heartbeat_timeout_s: float = 60.0,
    idle_sleep_s: float = 0.05,
    max_idle_cycles: int = 200,
    producer_mode: str = "local",
    stop_event: Optional[Any] = None,
    stale_sweep_interval_s: float = 2.0,
    batch_size: Any = 1,
) -> WorkerStats:
    """Run trials until the experiment finishes (or this worker's cap hits).

    ``max_broken`` (the reference's worker guard) stops this worker once that
    many trials have broken — a persistently-crashing user script must not
    spin the produce→break loop forever.

    ``producer_mode="coord"`` delegates suggestion (and the judge hook) to
    the coordinator's single hosted algorithm instance instead of fitting a
    local copy — requires the ``coord://`` ledger backend.

    ``stop_event`` (a ``threading.Event``-like): checked between trials —
    how `hunt --n-workers` winds its worker threads down cleanly on Ctrl-C
    (the in-flight trial finishes, the executor closes).

    ``stale_sweep_interval_s``: how often this worker sweeps lapsed
    reservations back to ``new``. A stale reservation is already
    ``heartbeat_timeout_s`` old by definition, so per-cycle sweeping buys
    nothing and costs an RPC/lock round-trip per cycle; the first cycle
    always sweeps (a restart must free its dead predecessor's holds).

    ``batch_size > 1`` switches to the batched hunt: up to that many
    reserved trials evaluate as ONE call into the executor's
    ``execute_batch`` (a single device program on a
    :class:`~metaopt_tpu.executor.BatchedExecutor`), with completions
    pushed back in one fused-cycle leg. ``"auto"`` sizes the batch from
    the algorithm's population cohort (``BaseAlgorithm.cohort_size``)
    when it has one.
    """
    algo: Optional[BaseAlgorithm]
    if producer_mode == "coord":
        producer: Any = RemoteProducer(experiment, worker=worker_id)
        algo = None
    elif producer_mode == "local":
        algo = algorithm or make_algorithm(experiment.space, experiment.algorithm)
        producer = Producer(experiment, algo)
    else:
        raise ValueError(f"unknown producer_mode {producer_mode!r}")
    if batch_size == "auto":
        # population algorithms emit same-fidelity generations — the natural
        # pool; non-cohort algorithms (or the remote producer, whose algo
        # lives server-side) fall back to the experiment's suggest pool
        cohort = algo.cohort_size if algo is not None else None
        batch_size = cohort or max(int(experiment.pool_size or 1), 8)
    batch_size = int(batch_size)
    if batch_size > 1:
        if not hasattr(executor, "execute_batch"):
            raise ValueError(
                f"batch_size={batch_size} needs an executor with "
                f"execute_batch (got {type(executor).__name__})"
            )
        return _workon_batched(
            experiment, executor, worker_id, producer, algo,
            worker_trials, max_broken, heartbeat_timeout_s, idle_sleep_s,
            max_idle_cycles, stop_event, stale_sweep_interval_s, batch_size,
        )
    stats = WorkerStats()
    # per-trial requeue budget: a wedge-attributed infrastructure failure
    # releases the trial (ExecutionResult.requeue), but only this many
    # times — a permanently dead backend must converge to interrupted.
    # The count persists on the trial document (resources), so N workers
    # (or a restarted worker) share ONE budget instead of multiplying it.
    max_requeues = 3
    # first loop iteration always sweeps (resuming after a crash must
    # free the dead predecessor's reservations before producing)
    last_sweep = 0.0
    last_broken_note = ""

    # fused coord path: one worker_cycle RPC per loop iteration replaces
    # the serial release_stale → produce → reserve → count → should_suspend
    # wire sequence (~5 round-trips → 1). The client degrades to the serial
    # composition against a pre-worker_cycle coordinator, so this stays the
    # ONLY coord-mode path either way.
    fused = producer_mode == "coord" and hasattr(
        experiment.ledger, "worker_cycle"
    )
    #: the latest fused-cycle reply — carries the counts/doneness snapshot
    #: the next is_done check reads locally instead of re-RPCing
    last_cycle: Optional[Dict[str, Any]] = None
    #: fused path: a finished trial whose terminal update rides the NEXT
    #: worker_cycle instead of costing its own RPC — (trial, was_pruned);
    #: flushed with a plain update_trial if the loop exits first
    pending_push: Optional[tuple] = None

    def _resolve_push(ok: bool) -> None:
        nonlocal pending_push
        t_done, was_pruned = pending_push  # type: ignore[misc]
        pending_push = None
        if ok:
            stats.completed += 1
            stats.pruned += was_pruned
        else:
            log.warning(
                "%s lost reservation of %s before result push",
                worker_id, t_done.id,
            )

    def _flush_pending() -> None:
        if pending_push is None:
            return
        _resolve_push(experiment.ledger.update_trial(
            pending_push[0], expected_status="reserved",
            expected_worker=worker_id,
        ))

    def heartbeat_for(trial: Trial, primed: bool = False):
        # ``primed``: the fused reply just showed no pending signal for a
        # reservation microseconds old, so the executor's FIRST beat (which
        # it fires immediately on start) is answered locally; every later
        # beat goes to the wire and catches real signals/lost reservations
        state = {"primed": primed}

        def beat() -> bool:
            if state["primed"]:
                state["primed"] = False
                return True
            return experiment.ledger.heartbeat(experiment.name, trial.id, worker_id)
        return beat

    def judge_fn(trial: Trial, partial: List[Dict[str, Any]]):
        return producer.judge(trial, partial)

    def _cycle_done(r: Dict[str, Any]) -> bool:
        """``Experiment.is_done`` evaluated from the fused reply's snapshot
        (doc fields + status counts) instead of 3 fresh RPCs. The snapshot
        is as fresh as serial re-counting w.r.t. THIS worker — _settle()
        folds our own transitions in — and one cycle stale w.r.t. other
        workers, which only costs one extra (budget-guarded) cycle."""
        if r.get("max_trials") is not None:
            # keep the live `mtpu db set max_trials=N` override behavior
            experiment.max_trials = r["max_trials"]
        c = r["counts"]
        if c["completed"] >= experiment.max_trials:
            return True
        if not r.get("exp_algo_done"):
            return False
        return c["new"] + c["reserved"] == 0

    def _settle(to_status: str) -> None:
        """Fold this worker's own reserved→terminal transition into the
        cached cycle counts so the next done-check doesn't miss it."""
        if last_cycle is None:
            return
        c = last_cycle["counts"]
        c["reserved"] = max(0, c["reserved"] - 1)
        if to_status in c:
            c[to_status] += 1

    try:
        while True:
            if last_cycle is not None:
                if _cycle_done(last_cycle):
                    break
            elif experiment.is_done:
                break
            if stop_event is not None and stop_event.is_set():
                log.info("%s: stop requested — winding down", worker_id)
                break
            if worker_trials is not None and stats.reserved >= worker_trials:
                log.info("%s: worker_trials cap (%d) reached", worker_id, worker_trials)
                break
            if max_broken is not None and stats.broken >= max_broken:
                log.error(
                    "%s: %d trials broke (max_broken=%d) — is the user script "
                    "runnable? Stopping. Last failure: %s", worker_id,
                    stats.broken, max_broken, last_broken_note or "(no detail)",
                )
                break

            # pacemaker duty, throttled: a stale reservation is minutes old by
            # definition (heartbeat_timeout_s), so sweeping every cycle buys
            # nothing and costs an RPC/lock round-trip per cycle — on the
            # coord backend that was one of ~5 RPCs per trial
            now = time.time()
            sweep = now - last_sweep >= stale_sweep_interval_s
            if fused:
                # skip the produce leg when the registration budget is provably
                # exhausted: completed+new+reserved only grows (requeues move
                # within the sum), so a one-cycle-stale sum >= max_trials still
                # proves no suggest can register — the produce would be a pure
                # no-op observe. Only when the server says the algorithm is
                # passive (``algo_passive``: no judge/suspend verdicts consult
                # the fit between produces), so observe timing is unobservable
                # and the suggestion stream provably identical. Trials leaving
                # the sum (broken/interrupted) reopen budget; the next reply's
                # fresh counts catch that one cycle later.
                produce_cycle = True
                if (last_cycle is not None
                        and last_cycle.get("algo_passive")
                        and experiment.max_trials is not None):
                    c = last_cycle["counts"]
                    produce_cycle = (
                        c["new"] + c["reserved"] + c["completed"]
                        < experiment.max_trials
                    )
                complete = None
                if pending_push is not None:
                    complete = {
                        "trial": pending_push[0].to_dict(),
                        "expected_status": "reserved",
                        "expected_worker": worker_id,
                    }
                last_cycle = producer.cycle(
                    stale_timeout_s=heartbeat_timeout_s if sweep else None,
                    produce=produce_cycle,
                    complete=complete,
                )
                if complete is not None:
                    _resolve_push(bool(last_cycle.get("completed_ok")))
                produced = last_cycle["registered"]
                trial = last_cycle["trial"]
            else:
                if sweep:
                    experiment.ledger.release_stale(
                        experiment.name, heartbeat_timeout_s
                    )
                produced = producer.produce()
                trial = experiment.reserve_trial(worker_id)
            if sweep:
                last_sweep = now

            if trial is None:
                # nothing to run: either in-flight trials elsewhere, an algorithm
                # barrier (sync rungs / generation waits), or true exhaustion
                in_flight = (
                    last_cycle["counts"]["reserved"]
                    if last_cycle is not None
                    else experiment.count("reserved")
                )
                if produced == 0 and in_flight == 0:
                    stats.idle_cycles += 1
                    if producer.algo_done or stats.idle_cycles > max_idle_cycles:
                        log.info("%s: no work producible; stopping", worker_id)
                        break
                else:
                    stats.idle_cycles = 0
                time.sleep(idle_sleep_s)
                continue

            stats.idle_cycles = 0
            stats.reserved += 1
            suspend = (
                last_cycle["suspend"]  # verdict rode the fused reply
                if last_cycle is not None
                else producer.should_suspend(trial)
            )
            if suspend:
                # the algorithm wants this trial parked (e.g. a bracket wants
                # its budget elsewhere first): suspended, not executed;
                # ``mtpu resume`` flips suspended trials back to new
                trial.transition("suspended")
                experiment.ledger.update_trial(
                    trial, expected_status="reserved", expected_worker=worker_id
                )
                stats.suspended += 1
                _settle("suspended")
                continue
            log.debug("%s running trial %s %s", worker_id, trial.id[:8], trial.params)
            t0 = time.time()
            try:
                res = executor.execute(
                    trial,
                    heartbeat=heartbeat_for(
                        trial,
                        # safe to answer the executor's immediate first beat
                        # locally: the fused reply just told us this fresh
                        # reservation has no pending signal
                        primed=(last_cycle is not None
                                and last_cycle.get("fused", False)
                                and last_cycle.get("signal") is None),
                    ),
                    judge=judge_fn,
                )
            except KeyboardInterrupt:
                trial.transition("interrupted")
                experiment.ledger.update_trial(
                    trial, expected_status="reserved", expected_worker=worker_id
                )
                stats.interrupted += 1
                raise

            trial.exit_code = res.exit_code
            requeue_budget_spent = False
            if res.status == "completed":
                if fused:
                    # defer the terminal update: it rides the next worker_cycle
                    # (the cycle is due immediately anyway), so the steady-state
                    # coord cost is ~1 RPC per trial instead of 2. The server
                    # applies it before its produce/reserve legs — same order
                    # as push-then-cycle — and the reply's counts/doneness
                    # already include it, so no _settle here.
                    trial.attach_results(res.results)
                    trial.transition("completed")
                    pending_push = (trial, int("pruned" in res.note))
                else:
                    ok = experiment.push_results(trial, res.results)
                    if ok:
                        stats.completed += 1
                        _settle("completed")
                        if "pruned" in res.note:
                            stats.pruned += 1
                    else:
                        log.warning(
                            "%s lost reservation of %s before result push",
                            worker_id, trial.id,
                        )
            elif (res.requeue
                  and int(trial.resources.get("requeues", 0)) < max_requeues):
                # infrastructure failure (device wedge/park budget): release
                # the trial back to 'new' so this or another worker retries it
                # once the device recovers; bounded per trial so a permanently
                # dead backend still converges to interrupted
                n_req = int(trial.resources.get("requeues", 0)) + 1
                trial.reset_to_new()
                # AFTER reset_to_new, which clears resources — the counter
                # must survive into the ledger or the budget never binds
                trial.resources["requeues"] = n_req
                ok = experiment.ledger.update_trial(
                    trial, expected_status="reserved", expected_worker=worker_id
                )
                if ok:
                    stats.requeued += 1
                    _settle("new")
                    log.warning(
                        "%s requeued trial %s (%d/%d): %s", worker_id,
                        trial.id[:8], n_req, max_requeues, res.note,
                    )
                else:
                    log.warning(
                        "%s lost reservation of %s before requeue write-back",
                        worker_id, trial.id,
                    )
            else:
                if res.requeue:
                    # the executor flagged a retry, but the shared budget is
                    # spent — the stored outcome must say what actually
                    # happens (nothing, until a human resumes it)
                    res.note += (" (requeue budget exhausted — "
                                 "see `mtpu resume`)")
                    requeue_budget_spent = True
                trial.transition(res.status)
                experiment.ledger.update_trial(
                    trial, expected_status="reserved", expected_worker=worker_id
                )
                _settle(res.status)
                stats.broken += res.status == "broken"
                stats.interrupted += res.status == "interrupted"
                if res.status == "broken":
                    # the note carries the evidence (exit code + stderr tail);
                    # at INFO it is invisible under the default CLI level and
                    # the eventual max_broken ERROR reads as evidence-free
                    last_broken_note = res.note
                    if res.note:
                        log.warning(
                            "%s: trial %s broken: %s",
                            worker_id, trial.id[:8], res.note)
                elif res.note:
                    log.info("trial %s %s: %s", trial.id[:8], res.status, res.note)
            stats.events.append(
                {
                    "trial": trial.id,
                    "status": res.status,
                    "runtime_s": round(time.time() - t0, 4),
                    "note": res.note,
                }
            )
            if requeue_budget_spent:
                # the backend stayed dead through every park + retry this
                # trial was entitled to (~3 park budgets of wall clock) and
                # the final attempt just went terminal — continuing would
                # have the producer mint replacement trials forever, each
                # doomed to the same grind. Stop THIS worker; the interrupted
                # trials resume with `mtpu resume` once the device returns.
                # (A terminal-interrupted trial satisfies no stop condition:
                # it is neither completed nor broken.) NOTE: this must key on
                # the budget-exhausted branch having actually run, not on the
                # stored counter — right after the LAST successful requeue
                # the counter already reads max_requeues, and breaking there
                # would strand the trial in 'new' instead of interrupted.
                log.error(
                    "%s: TPU backend did not recover within trial %s's requeue "
                    "budget — stopping worker (state preserved; `mtpu resume` "
                    "when the device returns)", worker_id, trial.id[:8],
                )
                break

    except BaseException:
        # error exits (coordinator unavailable, executor blow-ups, the
        # KeyboardInterrupt re-raise) still attempt the deferred push,
        # best-effort: the flush must not mask the original failure
        try:
            _flush_pending()
        except Exception:
            log.warning(
                "%s: deferred result push failed during error unwind "
                "(the stale sweep will re-free the trial)", worker_id,
            )
        raise
    # a result the next cycle never got to carry (the loop exited first)
    # still must reach the ledger — the deferred push is an optimization,
    # never a correctness trade
    _flush_pending()
    # final observe so the algorithm state is current for callers (the
    # coordinator-hosted algorithm observes inside its own produce cycles)
    if algo is not None:
        algo.observe(experiment.fetch_completed_trials())
    stats.producer_timings = dict(producer.timings)
    return stats


def _workon_batched(
    experiment: Experiment,
    executor: Executor,
    worker_id: str,
    producer: Any,
    algo: Optional[BaseAlgorithm],
    worker_trials: Optional[int],
    max_broken: Optional[int],
    heartbeat_timeout_s: float,
    idle_sleep_s: float,
    max_idle_cycles: int,
    stop_event: Optional[Any],
    stale_sweep_interval_s: float,
    batch_size: int,
) -> WorkerStats:
    """The batched hunt: pools of trials through ``executor.execute_batch``.

    Each outer iteration reserves up to ``batch_size`` trials — on the
    coord backend through repeated fused ``worker_cycle`` calls (the first
    carries the produce leg and the previous pool's multi-trial result
    push; the rest are reserve-only) — and evaluates them in ONE executor
    call, so a population generation or ASHA rung cohort is a single
    device program. Status handling per trial mirrors the serial loop;
    completions ride the next cycle's ``complete.trials`` leg so the
    steady-state coord cost stays ~1 RPC per trial.
    """
    stats = WorkerStats()
    fused = isinstance(producer, RemoteProducer) and hasattr(
        experiment.ledger, "worker_cycle"
    )
    last_cycle: Optional[Dict[str, Any]] = None
    last_sweep = 0.0
    last_broken_note = ""
    #: completed trials awaiting the next cycle's multi-trial complete
    #: leg — (trial, was_pruned), flushed directly if the loop exits first
    pending: List[tuple] = []

    def _resolve(flushed: List[tuple], oks: List[bool]) -> None:
        for (t_done, was_pruned), ok in zip(flushed, oks):
            if ok:
                stats.completed += 1
                stats.pruned += was_pruned
            else:
                log.warning(
                    "%s lost reservation of %s before result push",
                    worker_id, t_done.id,
                )

    def _flush_pending() -> None:
        nonlocal pending
        flushed, pending = pending, []
        if flushed:
            _resolve(flushed, [
                experiment.ledger.update_trial(
                    t, expected_status="reserved", expected_worker=worker_id
                )
                for t, _ in flushed
            ])

    def _cycle_done(r: Dict[str, Any]) -> bool:
        # same snapshot evaluation as the serial loop; our own pool's
        # completions are at most one cycle behind (they ride the next
        # cycle's push leg, whose reply refreshes these counts)
        if r.get("max_trials") is not None:
            experiment.max_trials = r["max_trials"]
        c = r["counts"]
        if c["completed"] >= experiment.max_trials:
            return True
        if not r.get("exp_algo_done"):
            return False
        return c["new"] + c["reserved"] == 0

    def heartbeat_for(trial: Trial, primed: bool = False):
        state = {"primed": primed}

        def beat() -> bool:
            if state["primed"]:
                state["primed"] = False
                return True
            return experiment.ledger.heartbeat(
                experiment.name, trial.id, worker_id
            )
        return beat

    def _park_suspended(trial: Trial) -> None:
        trial.transition("suspended")
        experiment.ledger.update_trial(
            trial, expected_status="reserved", expected_worker=worker_id
        )
        stats.suspended += 1

    try:
        while True:
            if last_cycle is not None:
                if _cycle_done(last_cycle):
                    break
            elif experiment.is_done:
                break
            if stop_event is not None and stop_event.is_set():
                log.info("%s: stop requested — winding down", worker_id)
                break
            if worker_trials is not None and stats.reserved >= worker_trials:
                log.info(
                    "%s: worker_trials cap (%d) reached", worker_id,
                    worker_trials,
                )
                break
            if max_broken is not None and stats.broken >= max_broken:
                log.error(
                    "%s: %d trials broke (max_broken=%d) — is the objective "
                    "runnable? Stopping. Last failure: %s", worker_id,
                    stats.broken, max_broken, last_broken_note or "(no detail)",
                )
                break

            want = batch_size
            if worker_trials is not None:
                want = min(want, worker_trials - stats.reserved)
            now = time.time()
            sweep = now - last_sweep >= stale_sweep_interval_s
            batch: List[Trial] = []
            primed: List[bool] = []
            produced = 0
            if fused:
                first = True
                while len(batch) < want:
                    complete = None
                    if first and pending:
                        complete = {
                            "trials": [t.to_dict() for t, _ in pending],
                            "expected_status": "reserved",
                            "expected_worker": worker_id,
                        }
                    r = producer.cycle(
                        pool_size=want,
                        stale_timeout_s=(
                            heartbeat_timeout_s if sweep and first else None
                        ),
                        produce=first,
                        complete=complete,
                    )
                    last_cycle = r
                    if complete is not None:
                        flushed, pending = pending, []
                        oks = r.get("completed_oks")
                        if oks is None:
                            # push leg didn't apply (degraded reply): the
                            # trials are still reserved — flush directly
                            oks = [
                                experiment.ledger.update_trial(
                                    t, expected_status="reserved",
                                    expected_worker=worker_id,
                                )
                                for t, _ in flushed
                            ]
                        _resolve(flushed, oks)
                    if first:
                        produced = r["registered"]
                    first = False
                    t = r["trial"]
                    if t is None:
                        break
                    if r["suspend"]:
                        _park_suspended(t)
                        continue
                    batch.append(t)
                    primed.append(
                        bool(r.get("fused")) and r.get("signal") is None
                    )
            else:
                if sweep:
                    experiment.ledger.release_stale(
                        experiment.name, heartbeat_timeout_s
                    )
                produced = producer.produce(pool_size=want)
                while len(batch) < want:
                    t = experiment.reserve_trial(worker_id)
                    if t is None:
                        break
                    if producer.should_suspend(t):
                        _park_suspended(t)
                        continue
                    batch.append(t)
                    primed.append(False)
            if sweep:
                last_sweep = now

            if not batch:
                in_flight = (
                    last_cycle["counts"]["reserved"]
                    if last_cycle is not None
                    else experiment.count("reserved")
                )
                if produced == 0 and in_flight == 0:
                    stats.idle_cycles += 1
                    if producer.algo_done or stats.idle_cycles > max_idle_cycles:
                        log.info("%s: no work producible; stopping", worker_id)
                        break
                else:
                    stats.idle_cycles = 0
                time.sleep(idle_sleep_s)
                continue

            stats.idle_cycles = 0
            stats.reserved += len(batch)
            log.debug(
                "%s running pool of %d trials", worker_id, len(batch)
            )
            t0 = time.time()
            try:
                results = executor.execute_batch(
                    batch,
                    heartbeats=[
                        heartbeat_for(t, primed=p)
                        for t, p in zip(batch, primed)
                    ],
                )
            except KeyboardInterrupt:
                for t in batch:
                    t.transition("interrupted")
                    experiment.ledger.update_trial(
                        t, expected_status="reserved",
                        expected_worker=worker_id,
                    )
                    stats.interrupted += 1
                raise
            runtime_s = round(time.time() - t0, 4)

            for trial, res in zip(batch, results):
                trial.exit_code = res.exit_code
                if res.status == "completed":
                    if fused:
                        trial.attach_results(res.results)
                        trial.transition("completed")
                        pending.append((trial, int("pruned" in res.note)))
                    else:
                        if experiment.push_results(trial, res.results):
                            stats.completed += 1
                            stats.pruned += int("pruned" in res.note)
                        else:
                            log.warning(
                                "%s lost reservation of %s before result "
                                "push", worker_id, trial.id,
                            )
                else:
                    # broken / interrupted (the batched executor never
                    # requeues: a pool-level infrastructure failure surfaces
                    # as broken notes, the worker guard handles persistence)
                    trial.transition(res.status)
                    experiment.ledger.update_trial(
                        trial, expected_status="reserved",
                        expected_worker=worker_id,
                    )
                    stats.broken += res.status == "broken"
                    stats.interrupted += res.status == "interrupted"
                    if res.status == "broken":
                        last_broken_note = res.note
                        if res.note:
                            log.warning(
                                "%s: trial %s broken: %s",
                                worker_id, trial.id[:8], res.note,
                            )
                stats.events.append({
                    "trial": trial.id,
                    "status": res.status,
                    "runtime_s": runtime_s,
                    "note": res.note,
                    "pool": len(batch),
                })
    except BaseException:
        try:
            _flush_pending()
        except Exception:
            log.warning(
                "%s: deferred pool push failed during error unwind "
                "(the stale sweep will re-free the trials)", worker_id,
            )
        raise
    _flush_pending()
    if algo is not None:
        algo.observe(experiment.fetch_completed_trials())
    stats.producer_timings = dict(producer.timings)
    return stats
