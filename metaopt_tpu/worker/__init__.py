"""The worker runtime: Producer + the workon loop.

ref: src/metaopt/core/worker/ (SURVEY.md §2.1, §3.1) — the hot loop:

    while not experiment.is_done:
        producer.produce()          # observe -> suggest -> register
        trial = reserve_trial()     # atomic CAS on the ledger
        consume(trial)              # executor runs it; results pushed back

Any number of workon loops (threads, processes, hosts) may run against one
ledger; the reserve CAS is the only synchronization point, exactly like the
reference's Mongo ``find_one_and_update`` story.
"""

from metaopt_tpu.worker.producer import Producer
from metaopt_tpu.worker.loop import WorkerStats, workon

__all__ = ["Producer", "workon", "WorkerStats"]
