"""Producer: the algorithm-facing pump.

ref: src/metaopt/core/worker/producer.py (SURVEY.md §2.1): fetch completed
trials → ``algo.observe()`` → ``algo.suggest(pool_size)`` → register (the
ledger's duplicate detection absorbs suggestion races between workers).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, Optional

from metaopt_tpu.algo.base import BaseAlgorithm
from metaopt_tpu.ledger.experiment import Experiment

log = logging.getLogger(__name__)


class Producer:
    def __init__(self, experiment: Experiment, algorithm: BaseAlgorithm):
        self.experiment = experiment
        self.algorithm = algorithm
        #: rolling timing aggregates (SURVEY.md §5: suggest-latency events)
        self.timings: Dict[str, float] = {
            "observe_s": 0.0, "suggest_s": 0.0, "cycles": 0, "suggested": 0,
        }
        #: mirrored by RemoteProducer so workon need not touch the algorithm
        self.algo_done = False
        self._warm_started = False
        #: incremental-observe cursor (fetch_completed_since); None both
        #: before the first cycle and on backends without incremental
        #: support (their default returns the full set with cursor=None)
        self._completed_cursor = None

    def produce(self, pool_size: Optional[int] = None) -> int:
        """One observe→suggest→register cycle; returns #trials registered."""
        exp = self.experiment
        t0 = time.perf_counter()
        if not self._warm_started:
            # warm start (lineage EVC role): replay another experiment's
            # completions into the algorithm once, before first suggest —
            # the surrogate starts informed, trial identity stays local
            self._warm_started = True
            meta = exp.metadata or {}
            # transfer priors seed FIRST: they must occupy the oldest
            # observation rows so the algorithm's n_prior discount (TPE
            # weights / GP subsample) addresses exactly them
            transfer = meta.get("transfer_from")
            if transfer:
                self._seed_transfer_priors(transfer, meta)
            branch = meta.get("branch")
            # both can be set at once: the branch parent replays through the
            # space adapter, an additional warm-start source through the
            # plain in-space filter — neither may shadow the other
            sources = []
            if branch and branch.get("parent") and branch["parent"] != exp.name:
                sources.append((branch["parent"], branch))
            warm = meta.get("warm_start")
            if warm and warm != exp.name and warm != (branch or {}).get("parent"):
                sources.append((warm, None))
            for src, src_branch in sources:
                fetched = exp.ledger.fetch(src, "completed")
                usable = self._adapt_foreign(fetched, src, src_branch)
                if usable:
                    self.algorithm.observe(usable)
                log.info(
                    "warm start: observed %d/%d completed trials from %r",
                    len(usable), len(fetched), src,
                )
        # incremental observe: only the trials completed since the last
        # cycle (re-fetching the whole completed set every cycle is O(n²)
        # JSON decode over an experiment — the 4096-trial sweep measured
        # the coordination plane at 1/5th throughput from exactly that).
        # Cursor invalidation (backend compaction, restart) degrades to a
        # full fetch, which observe's per-id dedup absorbs.
        new_done, next_cursor = exp.fetch_completed_since(
            self._completed_cursor
        )
        self.algorithm.observe(new_done)
        # commit the cursor ONLY after observe succeeded: a raise above
        # (hosted producers survive it and are retried) must re-fetch the
        # same delta next cycle, not drop it from the surrogate forever
        self._completed_cursor = next_cursor
        if getattr(self.algorithm, "supports_pending", False):
            # parallel strategy (lineage "liar"): in-flight trials join
            # the fit with a lie objective so N racing workers don't pile
            # suggestions onto points already being evaluated
            self.algorithm.set_pending(exp.fetch_trials("reserved"))
        self.timings["observe_s"] += time.perf_counter() - t0
        self.timings["cycles"] += 1

        if self.algorithm.is_done:
            self.algo_done = True
            exp.mark_algo_done()
            return 0

        # don't flood the ledger past max_trials with pending work
        pending = exp.count(("new", "reserved"))
        completed = exp.count("completed")
        budget_left = exp.max_trials - completed - pending
        want = min(pool_size or exp.pool_size, max(0, budget_left))
        if want <= 0:
            return 0

        t1 = time.perf_counter()
        points = self.algorithm.suggest(want)
        self.timings["suggest_s"] += time.perf_counter() - t1
        self.timings["suggested"] += len(points)
        if not points:
            return 0
        # PBT-style algorithms mark continuations with the reserved
        # ``_parent`` key: the trial whose checkpoint the new one resumes
        trials = [
            exp.make_trial(
                {k: v for k, v in p.items() if k != "_parent"},
                parent=p.get("_parent"),
            )
            for p in points
        ]
        kept = exp.register_trials(trials)
        if len(kept) < len(trials):
            log.debug(
                "producer: %d/%d suggestions were duplicates",
                len(trials) - len(kept), len(trials),
            )
        return len(kept)

    def _seed_transfer_priors(self, transfer, meta) -> None:
        """Seed the algorithm from EVC-admissible ancestors (ISSUE 16c).

        ``metadata.transfer_from`` names ancestor experiments directly
        (a string or list of names), or the sentinel ``"evc"`` which
        resolves the branch-parent chain via
        :func:`metaopt_tpu.ledger.evc.branch_parent`. Each ancestor's
        completed trials are space-remapped through the same
        :class:`TrialAdapter` path as branch warm-start (an inadmissible
        ancestor degrades to the in-space filter, never poisons the fit)
        and fed to ``observe_prior`` — tagged prior rows the acquisition
        discounts against locally-measured evidence.
        """
        exp = self.experiment
        items = [transfer] if isinstance(transfer, str) else list(transfer)
        names = []
        for item in items:
            if item == "evc":
                from metaopt_tpu.ledger.evc import branch_parent

                seen = {exp.name}
                parent = branch_parent(
                    {"name": exp.name, "metadata": meta})
                while parent and parent not in seen and len(names) < 8:
                    names.append(parent)
                    seen.add(parent)
                    doc = exp.ledger.load_experiment(parent)
                    parent = branch_parent(doc) if doc else None
            elif item != exp.name and item not in names:
                names.append(item)
        for src in names:
            try:
                fetched = exp.ledger.fetch(src, "completed")
            except Exception as err:
                log.warning("transfer ancestor %r unreadable: %s", src, err)
                continue
            usable = self._adapt_foreign(
                fetched, src, {"defaults": None, "renames": None})
            usable = [t for t in usable if t.objective is not None]
            if usable:
                self.algorithm.observe_prior(usable)
            log.info(
                "transfer priors: seeded %d/%d completed trials from %r",
                len(usable), len(fetched), src,
            )

    def _adapt_foreign(self, fetched, src, branch):
        """Fit another experiment's trials to this space (EVC branch path)."""
        exp = self.experiment
        if branch and exp.space is not None:
            from metaopt_tpu.ledger.evc import BranchConflictError, TrialAdapter
            from metaopt_tpu.space import build_space

            parent_doc = exp.ledger.load_experiment(src)
            if parent_doc is not None:
                try:
                    adapter = TrialAdapter(
                        build_space(parent_doc["space"]),
                        exp.space,
                        branch.get("defaults"),
                        branch.get("renames"),
                    )
                    return [a for a in map(adapter.adapt, fetched) if a]
                except BranchConflictError as err:
                    log.warning("branch adapter rejected: %s; filtering", err)
        return [t for t in fetched
                if exp.space is None or t.params in exp.space]

    def judge(self, trial, partial):
        return self.algorithm.judge(trial, partial)

    def should_suspend(self, trial) -> bool:
        return self.algorithm.should_suspend(trial)


class RemoteProducer:
    """Producer facade that delegates the cycle to the coordinator.

    The BASELINE north star's "KDE fit on a coordinator chip": the
    coordinator owns ONE algorithm instance per experiment (see
    ``CoordServer._hosted_producer``); workers just ask it to produce and
    then reserve as usual. N workers therefore share one fitted surrogate —
    no redundant per-worker re-fits, no divergent suggestion streams — while
    the decentralized :class:`Producer` remains the fallback for ledger
    backends with no coordinator (memory/file/native).

    Concurrent produce RPCs from different workers may be COALESCED by the
    server into one combined cycle (one fused suggest launch serves every
    request in the window). The reply's ``registered`` is then the combined
    cycle's total — correct for this facade's only consumer, the workon
    loop, which reads it purely as a progress/idle signal; the
    ``coalesced`` reply field is surfaced in ``timings["coalesced"]`` (how
    many of this worker's cycles shared a launch with at least one other
    request).
    """

    def __init__(self, experiment: Experiment, worker: Optional[str] = None):
        ledger = experiment.ledger
        if not hasattr(ledger, "produce"):
            raise ValueError(
                "coordinator-hosted suggestion needs the coord:// ledger "
                f"backend (got {type(ledger).__name__})"
            )
        self.experiment = experiment
        self.worker = worker
        self.timings: Dict[str, float] = {
            "produce_rpc_s": 0.0, "cycles": 0, "suggested": 0, "remote": 1,
            "coalesced": 0,
        }
        self.algo_done = False

    def produce(self, pool_size: Optional[int] = None) -> int:
        t0 = time.perf_counter()
        out = self.experiment.ledger.produce(
            self.experiment.name,
            pool_size or self.experiment.pool_size,
            worker=self.worker,
        )
        self.timings["produce_rpc_s"] += time.perf_counter() - t0
        self.timings["cycles"] += 1
        self.timings["suggested"] += out["registered"]
        if int(out.get("coalesced", 1)) > 1:
            self.timings["coalesced"] += 1
        self.algo_done = bool(out.get("algo_done"))
        return out["registered"]

    def cycle(
        self,
        pool_size: Optional[int] = None,
        stale_timeout_s: Optional[float] = None,
        produce: bool = True,
        complete: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """One fused worker cycle (push→sweep→produce→reserve→counts) in a
        single round-trip — see ``CoordLedgerClient.worker_cycle``. The
        produce leg rides the server's shared coalescer, so the registered
        suggestion stream is bit-identical to :meth:`produce` + reserve
        served serially; against a pre-``worker_cycle`` coordinator the
        client composes the same reply from the serial RPCs.

        ``produce=False`` skips the produce leg (the workon loop sends it
        when the registration budget is provably exhausted — a no-op cycle
        not worth a fit-lock round-trip); ``complete`` carries the
        previous trial's deferred terminal update."""
        t0 = time.perf_counter()
        out = self.experiment.ledger.worker_cycle(
            self.experiment.name,
            self.worker or "worker",
            pool_size=pool_size or self.experiment.pool_size,
            stale_timeout_s=stale_timeout_s,
            produce=produce,
            complete=complete,
        )
        self.timings["produce_rpc_s"] += time.perf_counter() - t0
        self.timings["cycles"] += 1
        self.timings["suggested"] += out["registered"]
        if int(out.get("coalesced", 1)) > 1:
            self.timings["coalesced"] += 1
        if out.get("fused"):
            self.timings["fused_cycles"] = (
                self.timings.get("fused_cycles", 0) + 1
            )
        if produce:
            self.algo_done = bool(out.get("algo_done"))
        return out

    def judge(self, trial, partial):
        return self.experiment.ledger.judge(self.experiment.name, trial, partial)

    def should_suspend(self, trial) -> bool:
        return bool(self.experiment.ledger.should_suspend(
            self.experiment.name, trial
        ))
