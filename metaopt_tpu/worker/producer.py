"""Producer: the algorithm-facing pump.

ref: src/metaopt/core/worker/producer.py (SURVEY.md §2.1): fetch completed
trials → ``algo.observe()`` → ``algo.suggest(pool_size)`` → register (the
ledger's duplicate detection absorbs suggestion races between workers).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional

from metaopt_tpu.algo.base import BaseAlgorithm
from metaopt_tpu.ledger.experiment import Experiment

log = logging.getLogger(__name__)


class Producer:
    def __init__(self, experiment: Experiment, algorithm: BaseAlgorithm):
        self.experiment = experiment
        self.algorithm = algorithm
        #: rolling timing aggregates (SURVEY.md §5: suggest-latency events)
        self.timings: Dict[str, float] = {
            "observe_s": 0.0, "suggest_s": 0.0, "cycles": 0, "suggested": 0,
        }

    def produce(self, pool_size: Optional[int] = None) -> int:
        """One observe→suggest→register cycle; returns #trials registered."""
        exp = self.experiment
        t0 = time.perf_counter()
        self.algorithm.observe(exp.fetch_completed_trials())
        self.timings["observe_s"] += time.perf_counter() - t0
        self.timings["cycles"] += 1

        if self.algorithm.is_done:
            exp.mark_algo_done()
            return 0

        # don't flood the ledger past max_trials with pending work
        pending = exp.count(("new", "reserved"))
        completed = exp.count("completed")
        budget_left = exp.max_trials - completed - pending
        want = min(pool_size or exp.pool_size, max(0, budget_left))
        if want <= 0:
            return 0

        t1 = time.perf_counter()
        points = self.algorithm.suggest(want)
        self.timings["suggest_s"] += time.perf_counter() - t1
        self.timings["suggested"] += len(points)
        if not points:
            return 0
        trials = [exp.make_trial(p) for p in points]
        kept = exp.register_trials(trials)
        if len(kept) < len(trials):
            log.debug(
                "producer: %d/%d suggestions were duplicates",
                len(trials) - len(kept), len(trials),
            )
        return len(kept)
