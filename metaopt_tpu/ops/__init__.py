"""Device-side numerical kernels (jit/vmap JAX, Pallas where it pays).

The reference has no native/kernel layer at all (SURVEY.md §2.9 — pure
Python over numpy/scipy); on TPU the performant-native role is played by
XLA-compiled JAX. Hot paths live here so algorithm modules stay host-side
control plane:

- :mod:`tpe_math` — truncated-Gaussian Parzen mixtures + EI scoring for TPE
  (the BASELINE north star: flat suggest() latency past 10k observations via
  power-of-two padding, so XLA compiles O(log n) kernel variants total).
"""

from metaopt_tpu.ops.tpe_math import adaptive_bandwidths, ei_scores, pad_pow2

__all__ = ["adaptive_bandwidths", "ei_scores", "pad_pow2"]
