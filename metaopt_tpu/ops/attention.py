"""Flash attention as a Pallas TPU kernel.

The demo-zoo Transformer (BASELINE config 4) is the framework's flagship
trial workload; its attention is the one genuinely hot op we own end-to-end.
The plain XLA path materializes the (B, H, Sq, Sk) logits tensor in HBM —
O(S²) memory traffic, the classic attention bottleneck. This kernel is the
TPU-native fix: blocked **online-softmax** attention (Flash Attention
forward) that keeps Q·Kᵀ tiles in VMEM, carries running (max, denominator,
accumulator) statistics across K blocks, and never writes the quadratic
logits to HBM. MXU does the two matmuls per tile; the VPU handles the
rescaling.

Backward uses a custom VJP that recomputes attention in plain XLA from the
saved (q, k, v, mask) residuals — the standard recompute strategy: the
forward's O(S²) HBM saving is kept, the backward trades FLOPs for memory.

The kernel runs in Pallas interpret mode off-TPU (tests exercise numerics +
grads without TPU hardware); on a TPU backend it compiles via Mosaic.
``MHA`` in metaopt_tpu.models.transformer routes here ONLY when
``METAOPT_TPU_FLASH=1`` is set (see :func:`use_flash_attention` for why the
kernel is opt-in rather than backend-default) and no tp>1 mesh is active.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_BIG = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, block_k: int):
    """One (batch·head, q-block) program: online softmax over K blocks.

    Shapes in VMEM: q (1, Bq, D); k/v (1, Sk, D); mask (1, Bq, Sk) bool or
    None; o (1, Bq, D).
    """
    q = q_ref[0].astype(jnp.float32)                      # (Bq, D)
    bq, d = q.shape
    sk = k_ref.shape[1]
    n_blocks = sk // block_k

    m0 = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(                           # (Bq, Bk) on MXU
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if mask_ref is not None:
            # int8 (not i1): Mosaic's sub-byte bool tiling is a pitfall
            mb = mask_ref[0, :, pl.ds(i * block_k, block_k)]
            s = jnp.where(mb != 0, s, _NEG_BIG)
        # floor the running max above the mask fill: a fully-masked block
        # would otherwise get exp(s - m) = exp(0) = 1 (uniform attention)
        m_new = jnp.maximum(
            jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True)), 0.5 * _NEG_BIG
        )
        alpha = jnp.exp(m - m_new)                         # rescale old stats
        p = jnp.exp(s - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    _, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    # fully-masked rows have l == 0; emit zeros rather than NaNs
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _pick_block(size: int, target: int) -> int:
    if size % target == 0:
        return target
    return size  # irregular lengths: single block (demo seqs are short)


def _flash_forward(
    q: jnp.ndarray,                 # (B, Sq, H, D)
    k: jnp.ndarray,                 # (B, Sk, H, D)
    v: jnp.ndarray,                 # (B, Sk, H, D)
    mask: Optional[jnp.ndarray],    # (B, Sq, Sk) bool or None
    block_q: int,
    block_k: int,
    interpret: bool,
) -> jnp.ndarray:
    b, sq, h, d = q.shape
    sk = k.shape[1]
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)

    # head-major flattening: one grid row per (batch, head)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    grid = (b * h, sq // bq)
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0)),
    ]
    operands = [qf, kf, vf]
    if mask is not None:
        in_specs.append(
            # mask is per-batch (heads share it): index by bh // h
            pl.BlockSpec((1, bq, sk), lambda bh, qi, h=h: (bh // h, qi, 0))
        )
        operands.append(mask.astype(jnp.int8))
        kernel = functools.partial(_flash_fwd_kernel, block_k=bk)
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref):
            _flash_fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, block_k=bk)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
        interpret=interpret,
    )(*operands)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def _reference_attention(q, k, v, mask):
    """Plain XLA attention (f32 softmax) — backward path + fallbacks."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    if mask is not None:
        s = jnp.where(mask[:, None], s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    # match the kernel: fully-masked rows produce zeros, not uniform garbage
    if mask is not None:
        any_valid = jnp.any(mask[:, None], axis=-1, keepdims=True)
        p = jnp.where(any_valid, p, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, mask, block_q, block_k, interpret):
    return _flash_forward(q, k, v, mask, block_q, block_k, interpret)


def _flash_fwd_rule(q, k, v, mask, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, mask, block_q, block_k, interpret)
    return out, (q, k, v, mask)


def _flash_bwd_rule(block_q, block_k, interpret, residuals, g):
    q, k, v, mask = residuals
    # recompute-backward: differentiate the reference formulation
    def f(q_, k_, v_):
        return _reference_attention(q_, k_, v_, mask)

    _, vjp = jax.vjp(f, q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    *,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Blocked online-softmax attention.

    q: (B, Sq, H, D) — pre-scaled (multiply by 1/sqrt(D) before calling);
    k, v: (B, Sk, H, D); mask: optional (B, Sq, Sk) bool, True = attend
    (shared across heads). Returns (B, Sq, H, D) in q's dtype.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, mask, block_q, block_k, interpret)


def use_flash_attention() -> bool:
    """Route MHA through the kernel? Opt-in via METAOPT_TPU_FLASH=1.

    Deliberately NOT default-on for the TPU backend: the axon tunnel's
    remote-compile path cannot currently build Mosaic (Pallas) programs —
    even a trivial pallas_call hangs — so silently routing every
    Transformer trial through the kernel would wedge on that setup. On a
    directly-attached TPU runtime, set METAOPT_TPU_FLASH=1 (the executor
    forwards the env to trials).
    """
    env = os.environ.get("METAOPT_TPU_FLASH")
    if env is not None:
        return env.strip().lower() not in ("0", "false", "no", "off", "")
    return False
