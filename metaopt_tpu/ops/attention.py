"""Flash attention for the demo-zoo Transformer: Pallas + chunked-XLA twins.

The demo-zoo Transformer (BASELINE config 4) is the framework's flagship
trial workload; its attention is the one genuinely hot op we own end-to-end.
The plain XLA path materializes the (B, H, Sq, Sk) logits tensor in HBM —
O(S²) memory traffic, the classic attention bottleneck. Two memory-efficient
implementations share one custom-VJP wrapper:

- ``impl="pallas"`` — a Pallas TPU kernel: blocked **online-softmax**
  attention that keeps Q·Kᵀ tiles in VMEM, carries running (max,
  denominator, accumulator) statistics across K blocks, and never writes the
  quadratic logits to HBM. MXU does the two matmuls per tile; the VPU
  handles the rescaling. Runs in interpret mode off-TPU; compiles via
  Mosaic on a directly-attached TPU runtime.
- ``impl="chunked"`` — the same blocked online-softmax as a ``lax.scan``
  over K blocks in plain XLA. Live tiles are O(Sq·block_k), never
  O(Sq·Sk). This twin compiles on ANY backend — including TPU runtimes
  whose Mosaic path is unavailable (the axon relay) — and supports
  attention-probability dropout, reproduced bit-exactly in the backward
  from the same ``fold_in`` counter stream.

Backward is blockwise recompute from the saved (q, k, v, mask, lse) — the
forward emits the per-row logsumexp for exactly this — so peak memory
stays O(Sq·block_k) per step and the forward's HBM saving is preserved
through training. Two formulations: ``impl="pallas"`` (dropout-free)
runs the two-pass Pallas kernels (``_flash_bwd_dkv_kernel`` parallel
over K blocks + ``_flash_bwd_dq_kernel`` parallel over Q blocks — TPU
has no cross-program atomics, so each pass owns its outputs exclusively);
everything else uses the chunked ``lax.scan`` formulation, which also
replays dropout bit-exactly from the same ``fold_in`` counter stream.

Irregular sequence lengths are padded up to block multiples with masked
tails (``_block_and_pad``); block sizes never exceed the requested
block_q/block_k.

``MHA`` in metaopt_tpu.models.transformer routes here by default on TPU
backends (chunked twin; see :func:`attention_impl` for the selection table
and why the Pallas kernel stays opt-in), and wraps
the call in ``shard_map`` over the trial mesh (batch on "dp", heads on
"tp") via :func:`sharded_flash_attention` — attention is embarrassingly
parallel over (batch, head), so each shard runs the kernel locally and the
Megatron head split survives instead of GSPMD all-gathering q/k/v.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_BIG = -1e30
_SUBLANE = 8  # pad granularity for sequences shorter than a block


# ---------------------------------------------------------------------------
# blocking / padding


def _block_and_pad(size: int, target: int) -> tuple:
    """(block, padded_size): block ≤ target, padded_size % block == 0."""
    if size % target == 0:
        return target, size
    if size < target:
        p = -(-size // _SUBLANE) * _SUBLANE
        return p, p
    return target, -(-size // target) * target


# ---------------------------------------------------------------------------
# Pallas forward kernel


def _flash_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                      *, block_k: int):
    """One (batch·head, q-block) program: online softmax over K blocks.

    Shapes in VMEM: q (1, Bq, D); k/v (1, Sk, D); mask (1, Bq, Sk) int8 or
    None; o (1, Bq, D); lse (1, Bq, 1) — the trailing singleton keeps the
    block's last two dims (Bq, 1) legal under Mosaic's (÷8, ÷128-or-equal)
    tiling rule; a (1, Bq) block over a (B·H, Sq) array is rejected.
    """
    q = q_ref[0].astype(jnp.float32)                      # (Bq, D)
    bq, d = q.shape
    sk = k_ref.shape[1]
    n_blocks = sk // block_k

    m0 = jnp.full((bq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(                           # (Bq, Bk) on MXU
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if mask_ref is not None:
            # int8 (not i1): Mosaic's sub-byte bool tiling is a pitfall
            mb = mask_ref[0, :, pl.ds(i * block_k, block_k)]
            s = jnp.where(mb != 0, s, _NEG_BIG)
        # floor the running max above the mask fill: a fully-masked block
        # would otherwise get exp(s - m) = exp(0) = 1 (uniform attention)
        m_new = jnp.maximum(
            jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True)), 0.5 * _NEG_BIG
        )
        alpha = jnp.exp(m - m_new)                         # rescale old stats
        p = jnp.exp(s - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    # fully-masked rows have l == 0; emit zeros rather than NaNs, and an
    # lse of +inf so the blockwise backward recomputes p == 0 for them
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[0] = jnp.where(
        l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), jnp.inf
    )


def _pallas_forward(q, k, v, mask, block_q, block_k, interpret):
    """(out, lse) via the Pallas kernel. Shapes pre-padded to block multiples."""
    b, sq, h, d = q.shape
    sk = k.shape[1]

    # head-major flattening: one grid row per (batch, head)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    grid = (b * h, sq // block_q)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0)),
    ]
    operands = [qf, kf, vf]
    if mask is not None:
        in_specs.append(
            # mask is per-batch (heads share it): index by bh // h
            pl.BlockSpec((1, block_q, sk), lambda bh, qi, h=h: (bh // h, qi, 0))
        )
        operands.append(mask.astype(jnp.int8))
        kernel = functools.partial(_flash_fwd_kernel, block_k=block_k)
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref):
            _flash_fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                              block_k=block_k)

    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
        ],
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        interpret=interpret,
    )(*operands)
    return (out.reshape(b, h, sq, d).transpose(0, 2, 1, 3),
            lse.reshape(b, h, sq))


# ---------------------------------------------------------------------------
# Pallas backward kernels (FlashAttention-2 style: two passes, no atomics)


def _flash_bwd_dkv_kernel(q_ref, g_ref, k_ref, v_ref, lse_ref, delta_ref,
                          mask_ref, dk_ref, dv_ref, *, block_q: int):
    """One (batch·head, k-block) program: dK/dV over all Q blocks.

    TPU has no cross-program atomics, so the backward splits into a dKV
    pass (this kernel, parallel over K blocks) and a dQ pass (below,
    parallel over Q blocks) — each output is owned by exactly one
    program. Shapes in VMEM: q/g (1, Sq, D) full; k/v (1, Bk, D) block;
    lse/delta (1, Sq, 1) full; mask (1, Sq, Bk) int8 block or None.
    p is recomputed from the saved lse (p = exp(s − lse)), the same
    normalized-probability recomputation the chunked twin uses; ds =
    p ⊙ (dO·Vᵀ − delta) with delta = rowsum(dO ⊙ O) precomputed in XLA.
    """
    kb = k_ref[0].astype(jnp.float32)                      # (Bk, D)
    vb = v_ref[0].astype(jnp.float32)
    bk, d = kb.shape
    sq = q_ref.shape[1]
    n_blocks = sq // block_q

    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        gb = g_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse_b = lse_ref[0, pl.ds(i * block_q, block_q), :]  # (Bq, 1) f32
        delta_b = delta_ref[0, pl.ds(i * block_q, block_q), :]
        s = jax.lax.dot_general(                            # (Bq, Bk)
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if mask_ref is not None:
            mb = mask_ref[0, pl.ds(i * block_q, block_q), :]
            s = jnp.where(mb != 0, s, _NEG_BIG)
        # fully-masked rows carry lse = +inf from the forward → p = 0
        p = jnp.exp(s - lse_b)
        gp = jax.lax.dot_general(                           # dO·Vᵀ (Bq, Bk)
            gb, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (gp - delta_b)
        dv_new = dv + jax.lax.dot_general(                  # pᵀ·dO (Bk, D)
            p, gb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_new = dk + jax.lax.dot_general(                  # dsᵀ·q (Bk, D)
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk_new, dv_new

    dk, dv = jax.lax.fori_loop(0, n_blocks, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, g_ref, k_ref, v_ref, lse_ref, delta_ref,
                         mask_ref, dq_ref, *, block_k: int):
    """One (batch·head, q-block) program: dQ over all K blocks."""
    qb = q_ref[0].astype(jnp.float32)                      # (Bq, D)
    gb = g_ref[0].astype(jnp.float32)
    lse_b = lse_ref[0]                                     # (Bq, 1) f32
    delta_b = delta_ref[0]
    bq, d = qb.shape
    sk = k_ref.shape[1]
    n_blocks = sk // block_k

    def body(i, dq):
        kb = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if mask_ref is not None:
            mb = mask_ref[0, :, pl.ds(i * block_k, block_k)]
            s = jnp.where(mb != 0, s, _NEG_BIG)
        p = jnp.exp(s - lse_b)
        gp = jax.lax.dot_general(
            gb, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (gp - delta_b)
        return dq + jax.lax.dot_general(                    # ds·K (Bq, D)
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jax.lax.fori_loop(0, n_blocks, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _pallas_backward(q, k, v, mask, out, lse, g, block_q, block_k, interpret):
    """(dq, dk, dv) via the two Pallas passes. Shapes pre-padded."""
    b, sq, h, d = q.shape
    sk = k.shape[1]

    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    gf = g.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    of = out.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    # delta = rowsum(dO ⊙ O): one fused elementwise+reduce, cheaper in XLA
    # than re-deriving O inside the kernels
    delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)                # (B·H, Sq, 1)
    lsef = lse.reshape(b * h, sq, 1)
    m8 = mask.astype(jnp.int8) if mask is not None else None

    full_q = [
        pl.BlockSpec((1, sq, d), lambda bh, i: (bh, 0, 0)),      # q
        pl.BlockSpec((1, sq, d), lambda bh, i: (bh, 0, 0)),      # g
    ]
    stats = [
        pl.BlockSpec((1, sq, 1), lambda bh, i: (bh, 0, 0)),      # lse
        pl.BlockSpec((1, sq, 1), lambda bh, i: (bh, 0, 0)),      # delta
    ]

    # pass 1: dK/dV, one program per K block
    in_specs = full_q + [
        pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),  # k
        pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),  # v
    ] + stats
    operands = [qf, gf, kf, vf, lsef, delta]
    if m8 is not None:
        in_specs.append(
            pl.BlockSpec((1, sq, block_k),
                         lambda bh, ki, h=h: (bh // h, 0, ki))
        )
        operands.append(m8)
        dkv_kernel = functools.partial(_flash_bwd_dkv_kernel,
                                       block_q=block_q)
    else:
        def dkv_kernel(q_ref, g_ref, k_ref, v_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref):
            _flash_bwd_dkv_kernel(q_ref, g_ref, k_ref, v_ref, lse_ref,
                                  delta_ref, None, dk_ref, dv_ref,
                                  block_q=block_q)
    # the dKV pass reorders q/g/k/v operands: q/g are the full arrays
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        grid=(b * h, sk // block_k),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        ],
        interpret=interpret,
    )(*operands)

    # pass 2: dQ, one program per Q block
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),  # q
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),  # g
        pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0)),        # k
        pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0)),        # v
        pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),  # lse
        pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),  # delta
    ]
    operands = [qf, gf, kf, vf, lsef, delta]
    if m8 is not None:
        in_specs.append(
            pl.BlockSpec((1, block_q, sk),
                         lambda bh, qi, h=h: (bh // h, qi, 0))
        )
        operands.append(m8)
        dq_kernel = functools.partial(_flash_bwd_dq_kernel, block_k=block_k)
    else:
        def dq_kernel(q_ref, g_ref, k_ref, v_ref, lse_ref, delta_ref,
                      dq_ref):
            _flash_bwd_dq_kernel(q_ref, g_ref, k_ref, v_ref, lse_ref,
                                 delta_ref, None, dq_ref, block_k=block_k)
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=(b * h, sq // block_q),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        interpret=interpret,
    )(*operands)

    unflat = lambda t, s: t.reshape(b, h, s, d).transpose(0, 2, 1, 3)  # noqa: E731
    return unflat(dq, sq), unflat(dk, sk), unflat(dv, sk)


# ---------------------------------------------------------------------------
# chunked (lax.scan) twin — pure XLA, any backend, dropout-capable


def _dropout_tile(key, i, keep, shape):
    """The (fwd ∩ bwd)-shared dropout mask for K-block i."""
    return jax.random.bernoulli(jax.random.fold_in(key, i), keep, shape)


def online_softmax_fold(s, v, m, l, acc, drop=None, keep=1.0):
    """Fold one masked score tile into online-softmax running statistics.

    The single source of truth for the blockwise-attention update — used by
    the chunked scan here AND by ring attention's per-hop step. s: (b, h,
    sq, bk) scores with mask already applied as ``_NEG_BIG`` fills; v: (b,
    h, bk, d); carries m/l: (b, h, sq, 1), acc: (b, h, sq, d). ``drop``
    applies attention-probability dropout with ``dropout(softmax)``
    semantics: l accumulates UNdropped mass (it is the softmax
    denominator), acc takes the dropped/rescaled tiles.
    """
    # floor the running max above the mask fill: a fully-masked tile would
    # otherwise get exp(s - m) = exp(0) = 1 (uniform attention)
    m_new = jnp.maximum(
        jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True)), 0.5 * _NEG_BIG
    )
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    if drop is not None:
        p = jnp.where(drop, p / keep, 0.0)
    acc_new = alpha * acc + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v, preferred_element_type=jnp.float32
    )
    return m_new, l_new, acc_new


def shard_map_nocheck(fn, mesh, in_specs, out_specs):
    """shard_map without the replication/vma check, on whichever JAX API.

    The blockwise-attention scans start their carries mesh-invariant and
    make them varying in the body — sound here, but the checker (named
    ``check_vma`` on newer JAX, ``check_rep`` before) rejects it.
    """
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pre-promotion JAX
        from jax.experimental.shard_map import shard_map
    kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    try:
        return shard_map(fn, check_vma=False, **kw)
    except TypeError:
        return shard_map(fn, check_rep=False, **kw)


def _chunked_forward(q, k, v, mask, block_k, dropout_rate, key):
    """(out, lse) via a lax.scan over K blocks; live tiles O(Sq·block_k).

    Dropout semantics match ``dropout(softmax(s)) @ V``: the denominator l
    accumulates undropped probabilities; the accumulator takes the dropped,
    1/keep-scaled ones.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    nk = sk // block_k
    qt = q.transpose(0, 2, 1, 3).astype(jnp.float32)      # (b,h,sq,d)
    kt = k.transpose(0, 2, 1, 3).astype(jnp.float32)      # (b,h,sk,d)
    vt = v.transpose(0, 2, 1, 3).astype(jnp.float32)
    keep = 1.0 - dropout_rate

    def body(carry, i):
        m, l, acc = carry
        kb = jax.lax.dynamic_slice_in_dim(kt, i * block_k, block_k, axis=2)
        vb = jax.lax.dynamic_slice_in_dim(vt, i * block_k, block_k, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kb,
                       preferred_element_type=jnp.float32)
        if mask is not None:
            mb = jax.lax.dynamic_slice_in_dim(mask, i * block_k, block_k,
                                              axis=2)
            s = jnp.where(mb[:, None], s, _NEG_BIG)
        drop = (_dropout_tile(key, i, keep, s.shape)
                if dropout_rate > 0.0 else None)
        return online_softmax_fold(s, vb, m, l, acc, drop, keep), None

    m0 = jnp.full((b, h, sq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(body, (m0, l0, acc0), jnp.arange(nk))
    out = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
    lse = jnp.where(
        l[..., 0] > 0, m[..., 0] + jnp.log(jnp.maximum(l[..., 0], 1e-30)),
        jnp.inf,
    )
    return out.transpose(0, 2, 1, 3), lse                 # (b,sq,h,d), (b,h,sq)


def _chunked_backward(q, k, v, mask, key, out, lse, g, block_k, dropout_rate):
    """Blockwise VJP from saved lse: p-tiles recomputed per K block.

    Softmax VJP with post-normalization dropout: with y = softmax rows and
    O = (pm/keep ⊙ y) V, the row term Σⱼ yⱼ·(dL/dyⱼ) collapses to
    rowsum(dO ⊙ O) — the standard delta trick survives dropout because the
    mask rides inside both factors.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    nk = sk // block_k
    qt = q.transpose(0, 2, 1, 3).astype(jnp.float32)
    kt = k.transpose(0, 2, 1, 3).astype(jnp.float32)
    vt = v.transpose(0, 2, 1, 3).astype(jnp.float32)
    gt = g.transpose(0, 2, 1, 3).astype(jnp.float32)
    ot = out.transpose(0, 2, 1, 3).astype(jnp.float32)
    delta = jnp.sum(gt * ot, axis=-1, keepdims=True)      # (b,h,sq,1)
    keep = 1.0 - dropout_rate

    def body(dq, i):
        kb = jax.lax.dynamic_slice_in_dim(kt, i * block_k, block_k, axis=2)
        vb = jax.lax.dynamic_slice_in_dim(vt, i * block_k, block_k, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kb,
                       preferred_element_type=jnp.float32)
        if mask is not None:
            mb = jax.lax.dynamic_slice_in_dim(mask, i * block_k, block_k,
                                              axis=2)
            s = jnp.where(mb[:, None], s, _NEG_BIG)
        p = jnp.exp(s - lse[..., None])                   # normalized probs
        gp = jnp.einsum("bhqd,bhkd->bhqk", gt, vb,
                        preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            pm = _dropout_tile(key, i, keep, p.shape)
            pd = jnp.where(pm, p / keep, 0.0)
            gp = jnp.where(pm, gp / keep, 0.0)
        else:
            pd = p
        dv_i = jnp.einsum("bhqk,bhqd->bhkd", pd, gt,
                          preferred_element_type=jnp.float32)
        ds = p * (gp - delta)
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kb,
                             preferred_element_type=jnp.float32)
        dk_i = jnp.einsum("bhqk,bhqd->bhkd", ds, qt,
                          preferred_element_type=jnp.float32)
        return dq, (dk_i, dv_i)

    dq0 = jnp.zeros_like(qt)
    dq, (dks, dvs) = jax.lax.scan(body, dq0, jnp.arange(nk))
    dk = jnp.moveaxis(dks, 0, 2).reshape(b, h, sk, d)     # (nk,b,h,bk,d)
    dv = jnp.moveaxis(dvs, 0, 2).reshape(b, h, sk, d)
    to_in = lambda t, ref: t.transpose(0, 2, 1, 3).astype(ref.dtype)  # noqa: E731
    return to_in(dq, q), to_in(dk, k), to_in(dv, v)


# ---------------------------------------------------------------------------
# custom VJP plumbing


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q, k, v, mask, key, dropout_rate, block_q, block_k, impl,
           interpret):
    out, _ = _flash_fwd_rule(
        q, k, v, mask, key, dropout_rate, block_q, block_k, impl, interpret
    )
    return out


def _flash_fwd_rule(q, k, v, mask, key, dropout_rate, block_q, block_k, impl,
                    interpret):
    if impl == "pallas":
        out, lse = _pallas_forward(q, k, v, mask, block_q, block_k, interpret)
    else:
        out, lse = _chunked_forward(q, k, v, mask, block_k, dropout_rate, key)
    return out, (q, k, v, mask, key, out, lse)


def _flash_bwd_rule(dropout_rate, block_q, block_k, impl, interpret,
                    residuals, g):
    q, k, v, mask, key, out, lse = residuals
    if impl == "pallas" and dropout_rate == 0.0:
        # the pallas forward never carries dropout (flash_attention routes
        # dropout to chunked), so the pallas backward needs no mask replay
        dq, dk, dv = _pallas_backward(
            q, k, v, mask, out, lse, g, block_q, block_k, interpret
        )
    else:
        dq, dk, dv = _chunked_backward(
            q, k, v, mask, key, out, lse, g, block_k, dropout_rate
        )
    return dq, dk, dv, None, None


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _reference_attention(q, k, v, mask, dropout_rate=0.0, dropout_key=None):
    """Plain XLA attention (f32 softmax) — the O(S²)-HBM fallback/oracle."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    if mask is not None:
        s = jnp.where(mask[:, None], s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    # match the kernel: fully-masked rows produce zeros, not uniform garbage
    if mask is not None:
        any_valid = jnp.any(mask[:, None], axis=-1, keepdims=True)
        p = jnp.where(any_valid, p, 0.0)
    if dropout_rate > 0.0:
        keep = 1.0 - dropout_rate
        pm = jax.random.bernoulli(dropout_key, keep, p.shape)
        p = jnp.where(pm, p / keep, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)


# ---------------------------------------------------------------------------
# public API


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    *,
    dropout_rate: float = 0.0,
    dropout_key: Optional[jnp.ndarray] = None,
    block_q: int = 128,
    block_k: int = 128,
    impl: Optional[str] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Blocked online-softmax attention with a blockwise backward.

    q: (B, Sq, H, D) — pre-scaled (multiply by 1/sqrt(D) before calling);
    k, v: (B, Sk, H, D); mask: optional (B, Sq, Sk) bool, True = attend
    (shared across heads); dropout_rate applies to attention probabilities
    (chunked impl only) with dropout_key. Irregular Sq/Sk are padded to
    block multiples with masked tails. Returns (B, Sq, H, D) in q's dtype.
    """
    if impl is None:
        impl = "chunked" if dropout_rate > 0.0 else "pallas"
    if dropout_rate > 0.0 and impl == "pallas":
        raise ValueError("attention dropout requires impl='chunked'")
    if dropout_rate > 0.0 and dropout_key is None:
        raise ValueError("dropout_rate > 0 needs a dropout_key")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    b, sq, h, d = q.shape
    sk = k.shape[1]
    bq, sq_p = _block_and_pad(sq, block_q)
    bk, sk_p = _block_and_pad(sk, block_k)
    if sq_p != sq or sk_p != sk:
        q = jnp.pad(q, ((0, 0), (0, sq_p - sq), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, sk_p - sk), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, sk_p - sk), (0, 0), (0, 0)))
        if mask is not None:
            mask = jnp.pad(mask, ((0, 0), (0, sq_p - sq), (0, sk_p - sk)))
        elif sk_p != sk:
            # padded K columns must not be attended; padded Q rows are
            # sliced off below and need no masking
            mask = jnp.broadcast_to(
                (jnp.arange(sk_p) < sk)[None, None, :], (b, sq_p, sk_p)
            )
    out = _flash(q, k, v, mask, dropout_key, float(dropout_rate), bq, bk,
                 impl, bool(interpret))
    return out[:, :sq]


def sharded_flash_attention(
    mesh,
    q, k, v,
    mask=None,
    *,
    dropout_rate: float = 0.0,
    dropout_key=None,
    impl: Optional[str] = None,
    batch_axis: str = "dp",
    head_axis: str = "tp",
    **kwargs,
):
    """shard_map the kernel over the trial mesh: batch on dp, heads on tp.

    Attention is embarrassingly parallel over (batch, head): each shard runs
    the kernel on its local (B/dp, S, H/tp, D) slab with zero collectives,
    so the Megatron column-split of q/k/v survives instead of GSPMD
    all-gathering the heads. The dropout key is decorrelated per shard by
    folding in the mesh coordinates.
    """
    from jax.sharding import PartitionSpec as P

    ab = batch_axis if batch_axis in mesh.shape else None
    ah = head_axis if head_axis in mesh.shape else None
    qs = P(ab, None, ah, None)
    ms = P(ab, None, None)

    def local(q, k, v, mask, key):
        if key is not None:
            for ax in (ab, ah):
                if ax is not None:
                    key = jax.random.fold_in(key, jax.lax.axis_index(ax))
        return flash_attention(
            q, k, v, mask, dropout_rate=dropout_rate, dropout_key=key,
            impl=impl, **kwargs,
        )

    wrapped = shard_map_nocheck(
        local, mesh,
        in_specs=(qs, qs, qs, ms if mask is not None else P(), P()),
        out_specs=qs,
    )
    return wrapped(q, k, v, mask, dropout_key)


def attention_impl() -> Optional[str]:
    """Which implementation MHA routes through, from ``METAOPT_TPU_FLASH``.

    - unset → backend default: **``chunked`` on TPU** (compiles on any TPU
      runtime, including relay-tunneled ones, and keeps live attention
      tiles O(Sq·block_k) instead of the reference path's O(S²) HBM logits
      tensor), ``None`` (plain XLA reference) on CPU, where the O(S²) path
      is faster at test shapes and numerically the oracle.
    - ``0``/``off`` → ``None``: force the plain XLA reference attention.
    - ``1``/``pallas`` → the Pallas kernel (Mosaic on a TPU runtime,
      including relay-tunneled ones; interpret mode on CPU). Attention
      dropout still routes those calls to the chunked twin. Compiled-run
      record (2026-07-31, v5e via relay): ``flash_pallas: {status: ok,
      step_ms: 66.6, chunked_step_ms: 67.5, max_abs_err: 1e-3}`` — the
      kernel compiles and matches the chunked twin, with step time parity
      at bench shapes, so chunked stays the TPU default (it also covers
      dropout) and pallas remains the opt-in. bench.py re-measures
      flash_pallas each TPU run.
    - ``chunked``/``scan`` → force the lax.scan twin on any backend.
    """
    env = (os.environ.get("METAOPT_TPU_FLASH") or "").strip().lower()
    if env in ("", None):
        return "chunked" if jax.default_backend() == "tpu" else None
    if env in ("0", "false", "no", "off"):
        return None
    if env in ("chunked", "scan", "2"):
        return "chunked"
    if env in ("1", "true", "yes", "on", "pallas"):
        return "pallas"
    # a typo must not silently select the Mosaic path (which can wedge on
    # relay-tunneled backends) — fail loudly instead
    raise ValueError(
        f"METAOPT_TPU_FLASH={env!r}: expected off/pallas/chunked"
    )


def use_flash_attention() -> bool:
    """Back-compat boolean view of :func:`attention_impl`."""
    return attention_impl() is not None
