"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second long-context strategy next to :mod:`ring_attention` (DeepSpeed
Ulysses; see PAPERS.md): instead of rotating K/V around the ring while
queries stay put, ONE ``all_to_all`` re-shards the activations from
sequence-sharded to head-sharded, every device runs ordinary full-sequence
attention on its subset of heads, and a second ``all_to_all`` restores the
sequence sharding. Communication is 2 all-to-alls of O(S·H·D / sp) per
device — independent of the number of ring hops — at the price of needing
``heads % sp == 0`` and one full-length sequence resident per device
(attention itself still runs through the chunked flash path, so the
O(S²) logits tensor never materializes; only O(S·d) activations do).

Trade-off vs the ring, honestly stated: the ring's peak activation memory
is O(S/sp · d) (never the full sequence) and it pipelines transfers with
compute — better for the longest contexts; Ulysses has lower collective
count and latency at moderate lengths and maps onto XLA's native
``all_to_all``. Both compose with dp/tp in one ``shard_map``. The demo
Transformer picks via ``METAOPT_TPU_SP_IMPL`` (``ring`` default,
``ulysses`` opt-in) — see :func:`sp_impl`.

ref: the reference framework has no attention code at all (SURVEY.md §5
long-context: "absent by design"); TPU-native demo-zoo surface.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from metaopt_tpu.ops.attention import flash_attention, shard_map_nocheck


def sp_impl() -> str:
    """Which sequence-parallel attention MHA uses when the mesh has sp>1.

    ``METAOPT_TPU_SP_IMPL``: ``ring`` (default — lowest per-chip memory,
    transfers overlap compute) or ``ulysses`` (2 all-to-alls, needs
    ``local heads % sp == 0``).
    """
    env = (os.environ.get("METAOPT_TPU_SP_IMPL") or "ring").strip().lower()
    if env in ("ring", "ulysses"):
        return env
    raise ValueError(f"METAOPT_TPU_SP_IMPL={env!r}: expected ring/ulysses")


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    *,
    mesh: Mesh,
    seq_axis: str = "sp",
    batch_axis: Optional[str] = "dp",
    head_axis: Optional[str] = "tp",
    dropout_rate: float = 0.0,
    dropout_key: Optional[jnp.ndarray] = None,
    impl: Optional[str] = "chunked",
) -> jnp.ndarray:
    """Sequence-parallel attention via head/sequence all-to-all exchange.

    q: (B, Sq, H, D) pre-scaled by 1/sqrt(D); k, v: (B, Sk, H, D); mask:
    optional (B, Sq, Sk) bool, True = attend (replicated over the seq
    axis — each device needs full-sequence rows for its heads). Sq/Sk must
    divide the ``seq_axis`` size, and the per-device head count (H, or
    H/tp when ``head_axis`` is in the mesh) must divide it too. Returns
    (B, Sq, H, D) in q's dtype, sequence-sharded like q.

    Differentiable end-to-end: ``all_to_all`` transposes to the inverse
    all-to-all, and the local attention is the chunked flash kernel with
    its blockwise VJP.
    """
    if seq_axis not in mesh.shape:
        raise ValueError(f"mesh has no {seq_axis!r} axis: {dict(mesh.shape)}")
    sp = mesh.shape[seq_axis]
    if q.shape[1] % sp or k.shape[1] % sp:
        raise ValueError(
            f"Sq={q.shape[1]}, Sk={k.shape[1]} must divide seq axis {sp}"
        )
    ab = batch_axis if (batch_axis and batch_axis in mesh.shape) else None
    ah = head_axis if (head_axis and head_axis in mesh.shape) else None
    h_local = q.shape[2] // (mesh.shape[ah] if ah else 1)
    if h_local % sp:
        raise ValueError(
            f"ulysses needs per-device heads ({h_local}) divisible by the "
            f"{seq_axis} axis ({sp}); use ring attention for this layout"
        )
    qs = P(ab, seq_axis, ah, None)
    ms = P(ab, None, None)  # full-sequence mask rows on every seq shard

    def local(q, k, v, mask, key):
        # seq-sharded -> head-sharded: split heads sp ways, gather the
        # full sequence (one all-to-all riding ICI)
        def fwd(x):
            return jax.lax.all_to_all(
                x, seq_axis, split_axis=2, concat_axis=1, tiled=True
            )

        qg, kg, vg = fwd(q), fwd(k), fwd(v)
        if key is not None:
            for ax in (ab, seq_axis, ah):
                if ax is not None:
                    key = jax.random.fold_in(key, jax.lax.axis_index(ax))
        out = flash_attention(
            qg, kg, vg, mask, dropout_rate=dropout_rate, dropout_key=key,
            impl=impl,
        )
        # head-sharded -> seq-sharded: the inverse exchange
        return jax.lax.all_to_all(
            out, seq_axis, split_axis=1, concat_axis=2, tiled=True
        )

    wrapped = shard_map_nocheck(
        local, mesh,
        in_specs=(qs, qs, qs, ms if mask is not None else P(), P()),
        out_specs=qs,
    )
    return wrapped(q, k, v, mask, dropout_key)
