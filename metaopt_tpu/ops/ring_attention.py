"""Ring attention: sequence-parallel attention over a mesh axis.

Long-context support for the demo-zoo Transformer beyond what fits one
chip's HBM: shard the sequence over a mesh axis ("sp"), keep each shard's
queries local, and rotate K/V shards around the ring with
``jax.lax.ppermute`` while accumulating online-softmax statistics — the
blockwise-parallel formulation (Liu et al., "Ring Attention with Blockwise
Transformers"; see PAPERS.md). Peak memory per chip is O(S/sp · d) for
activations and O(S/sp · S/sp) for score tiles; the full (S, S) logits
never exist anywhere, and the K/V transfers ride the ICI ring — each hop
overlaps one neighbor transfer with one local blockwise fold. The last
fold is peeled out of the scan so no dead final rotation is paid.

Composition with the rest of the stack:

- the per-tile update is :func:`metaopt_tpu.ops.attention
  .online_softmax_fold` — the same single-source-of-truth fold the chunked
  scan twin uses, dropout convention included;
- the collective layer is exactly ``shard_map`` + ``ppermute`` over the
  trial mesh (SURVEY.md §7's "pick a mesh, annotate shardings, let XLA
  insert collectives" doctrine) — no bespoke comm backend;
- autodiff works through ``ppermute`` natively (its transpose is the
  reverse permute), so the backward is the same ring run in reverse with
  the blockwise VJP — no custom gradient code needed here.

ref: the reference framework has no model/attention code at all
(SURVEY.md §5 long-context: "absent by design"); this module is part of
the TPU-native demo-zoo surface that BASELINE configs exercise, built so
the framework's flagship workload scales past single-chip sequence
lengths.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from metaopt_tpu.ops.attention import (
    _NEG_BIG,
    online_softmax_fold,
    shard_map_nocheck,
)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    *,
    mesh: Mesh,
    seq_axis: str = "sp",
    batch_axis: Optional[str] = "dp",
    head_axis: Optional[str] = "tp",
    dropout_rate: float = 0.0,
    dropout_key: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Sequence-parallel attention: Q stays put, K/V ride the ICI ring.

    q: (B, Sq, H, D) pre-scaled by 1/sqrt(D); k, v: (B, Sk, H, D);
    mask: optional (B, Sq, Sk) bool, True = attend. Sq and Sk must divide
    by the ``seq_axis`` size (pad upstream). Composes with batch ("dp")
    and head ("tp") sharding in the same call. Returns (B, Sq, H, D) in
    q's dtype, sequence-sharded like q.

    Differentiable end-to-end: the ring is a ``lax.scan`` of
    (local blockwise fold + ``ppermute``) plus one peeled final fold, and
    every piece transposes cleanly, so ``jax.grad`` yields the
    reverse-ring backward with blockwise memory — no quadratic logits in
    either direction.
    """
    if seq_axis not in mesh.shape:
        raise ValueError(f"mesh has no {seq_axis!r} axis: {dict(mesh.shape)}")
    sp = mesh.shape[seq_axis]
    if q.shape[1] % sp or k.shape[1] % sp:
        raise ValueError(
            f"Sq={q.shape[1]}, Sk={k.shape[1]} must divide seq axis {sp}"
        )
    if dropout_rate > 0.0 and dropout_key is None:
        raise ValueError("dropout_rate > 0 needs a dropout_key")
    ab = batch_axis if (batch_axis and batch_axis in mesh.shape) else None
    ah = head_axis if (head_axis and head_axis in mesh.shape) else None
    qs = P(ab, seq_axis, ah, None)       # (b, s, h, d): sequence-sharded
    ms = P(ab, seq_axis, None)           # mask: rows with q, cols gathered
    keep = 1.0 - dropout_rate

    def local(q_loc, k_loc, v_loc, mask_loc, key):
        # q_loc: (b, sq/sp, h, d); k/v_loc: (b, sk/sp, h, d);
        # mask_loc: (b, sq/sp, sk) — full key axis, sliced per ring step
        qt = q_loc.transpose(0, 2, 1, 3).astype(jnp.float32)
        my = jax.lax.axis_index(seq_axis)
        sk_loc = k_loc.shape[1]
        b, h, sq_loc, d = qt.shape
        if key is not None:
            # decorrelate the dropout stream per mesh coordinate
            for ax in (ab, ah, seq_axis):
                if ax is not None:
                    key = jax.random.fold_in(key, jax.lax.axis_index(ax))

        def fold(kv, m, l, acc, i):
            """Fold the currently-held K/V shard (ring position i)."""
            kt = kv[0].transpose(0, 2, 1, 3).astype(jnp.float32)
            vt = kv[1].transpose(0, 2, 1, 3).astype(jnp.float32)
            s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt,
                           preferred_element_type=jnp.float32)
            # position i holds the shard that started (my + i) hops back —
            # slice the matching key columns from the local mask
            src = (my - i) % sp
            if mask_loc is not None:
                mk = jax.lax.dynamic_slice_in_dim(
                    mask_loc, src * sk_loc, sk_loc, axis=2
                )
                s = jnp.where(mk[:, None], s, _NEG_BIG)
            drop = None
            if key is not None:
                drop = jax.random.bernoulli(
                    jax.random.fold_in(key, i), keep, s.shape
                )
            return online_softmax_fold(s, vt, m, l, acc, drop, keep)

        def step(carry, i):
            kv, m, l, acc = carry
            m, l, acc = fold(kv, m, l, acc, i)
            # rotate K/V one hop around the ring for the next fold
            kv = jax.tree.map(
                lambda x: jax.lax.ppermute(
                    x, seq_axis,
                    [(j, (j + 1) % sp) for j in range(sp)],
                ),
                kv,
            )
            return (kv, m, l, acc), None

        m0 = jnp.full((b, h, sq_loc, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, h, sq_loc, 1), jnp.float32)
        acc0 = jnp.zeros((b, h, sq_loc, d), jnp.float32)
        # sp-1 (fold + rotate) steps in the scan, then the final fold
        # peeled: the last shard needs no onward rotation, so no dead hop
        (kv, m, l, acc), _ = jax.lax.scan(
            step, ((k_loc, v_loc), m0, l0, acc0), jnp.arange(sp - 1)
        )
        m, l, acc = fold(kv, m, l, acc, jnp.asarray(sp - 1))
        out = (acc / jnp.maximum(l, 1e-30)).astype(jnp.float32)
        # fully-masked rows (l == 0) emit zeros, matching ops.attention
        out = jnp.where(l > 0, out, 0.0).astype(q_loc.dtype)
        return out.transpose(0, 2, 1, 3)

    wrapped = shard_map_nocheck(
        local, mesh,
        in_specs=(qs, qs, qs, ms if mask is not None else P(), P()),
        out_specs=qs,
    )
    return wrapped(q, k, v, mask, dropout_key)
