"""TPE surrogate math as XLA-compiled array kernels.

ref mechanism: src/metaopt/algo/tpe.py (SURVEY.md §2.3 [HIGH]): observations
split at the γ-quantile into good/bad sets; per-dimension adaptive-bandwidth
Parzen estimators l(x) and g(x); candidates drawn from l and ranked by
EI ∝ l(x)/g(x). The reference evaluates these densities in Python/numpy per
suggest call; here the density evaluation — the O(candidates × observations ×
dims) part that grows with trial count — is a single jitted kernel over
[0,1]-cube arrays, with observation counts padded to powers of two so XLA
compiles at most O(log n) variants over an experiment's lifetime (this is
what keeps suggest() latency flat past 10k trials, per BASELINE.md).

Everything here is pure and shape-explicit; host-side control plane lives in
:mod:`metaopt_tpu.algo.tpe`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_SQRT2 = 1.4142135623730951


def pad_pow2(n: int, minimum: int = 8) -> int:
    """Padded buffer size ≥ max(n, minimum): powers of two up to 4096,
    then 4096-step multiples.

    Doubling forever wastes up to ~2× FLOPs at ANY scale; stepping by 4096
    past that point bounds the waste by 4096/n (still ~2× just past the
    4096 boundary, shrinking as n grows — <20% by 20k observations) while
    keeping recompiles to O(n/4096) large-n variants (a 100k-trial sweep
    compiles ~25, each reused for 4096 observations).
    """
    p = minimum
    while p < n and p < 4096:
        p *= 2
    if p >= n:
        return p
    return ((n + 4095) // 4096) * 4096


def adaptive_bandwidths(sorted_mu: np.ndarray) -> np.ndarray:
    """Per-component sigmas for a 1-D Parzen mixture on [0, 1].

    Classic adaptive-Parzen rule: each point's sigma is the larger of the
    gaps to its sorted neighbours (edge points use the gap to the domain
    bound), clipped to [1/min(100, n+1), 1]. Host-side numpy — O(n) after the
    caller's sort, negligible next to density evaluation.
    """
    n = len(sorted_mu)
    if n == 0:
        return np.zeros(0)
    if n == 1:
        return np.ones(1)
    ext = np.concatenate([[0.0], sorted_mu, [1.0]])
    left = sorted_mu - ext[:-2]
    right = ext[2:] - sorted_mu
    sig = np.maximum(left, right)
    sig_min = 1.0 / min(100.0, n + 1.0)
    return np.clip(sig, sig_min, 1.0)


def _truncnorm_mixture_logpdf_1d(
    x: jnp.ndarray,      # (C,) evaluation points in [0,1]
    mu: jnp.ndarray,     # (N,) component means
    sigma: jnp.ndarray,  # (N,) component sigmas (>0 even for padding)
    logw: jnp.ndarray,   # (N,) log mixture weights (-inf for padding)
) -> jnp.ndarray:        # (C,)
    """log pdf of a weighted mixture of [0,1]-truncated Gaussians."""
    z = (x[:, None] - mu[None, :]) / sigma[None, :]
    log_phi = -0.5 * z * z - 0.5 * jnp.log(2 * jnp.pi) - jnp.log(sigma[None, :])
    # truncation mass on [0,1] per component
    a = jax.scipy.special.ndtr((1.0 - mu) / sigma)
    b = jax.scipy.special.ndtr((0.0 - mu) / sigma)
    log_mass = jnp.log(jnp.clip(a - b, 1e-12, 1.0))
    return jax.scipy.special.logsumexp(
        log_phi - log_mass[None, :] + logw[None, :], axis=1
    )


#: vmap over dimensions: x (C,d), mu (N,d), sigma (N,d), logw (N,d)
#: (weights are per-dim because adaptive bandwidths sort components per dim)
_mixture_logpdf = jax.vmap(
    _truncnorm_mixture_logpdf_1d, in_axes=(1, 1, 1, 1), out_axes=1
)


# mtpu: hotpath
@functools.partial(jax.jit, static_argnames=())
def ei_scores(
    cand: jnp.ndarray,          # (C, d) candidates in the unit cube
    good_mu: jnp.ndarray,       # (Ng, d)
    good_sigma: jnp.ndarray,    # (Ng, d)
    good_logw: jnp.ndarray,     # (Ng, d)
    bad_mu: jnp.ndarray,        # (Nb, d)
    bad_sigma: jnp.ndarray,     # (Nb, d)
    bad_logw: jnp.ndarray,      # (Nb, d)
    cont_mask: jnp.ndarray,     # (d,) 1.0 for continuous cols, 0.0 for categorical
    cand_cat_idx: jnp.ndarray,  # (C, d) int32 category index (0 for cont cols)
    good_cat_logp: jnp.ndarray, # (d, K) per-dim category log-probs under l
    bad_cat_logp: jnp.ndarray,  # (d, K) per-dim category log-probs under g
) -> jnp.ndarray:               # (C,) EI score = log l(x) - log g(x)
    """Expected-improvement ranking for TPE: log l(x) − log g(x).

    Continuous columns use truncated-Gaussian Parzen mixtures; categorical
    columns use re-weighted category frequency tables (the reference's
    mechanism for categorical dims). One fused kernel — XLA maps the
    (C × N × d) inner product onto the VPU and fuses the masked reduction.
    """
    log_l_cont = _mixture_logpdf(cand, good_mu, good_sigma, good_logw)   # (C, d)
    log_g_cont = _mixture_logpdf(cand, bad_mu, bad_sigma, bad_logw)     # (C, d)

    d_idx = jnp.arange(cand.shape[1])[None, :]                           # (1, d)
    log_l_cat = good_cat_logp[d_idx, cand_cat_idx]                       # (C, d)
    log_g_cat = bad_cat_logp[d_idx, cand_cat_idx]                        # (C, d)

    log_l = jnp.where(cont_mask[None, :] > 0, log_l_cont, log_l_cat)
    log_g = jnp.where(cont_mask[None, :] > 0, log_g_cont, log_g_cat)
    return jnp.sum(log_l - log_g, axis=1)


# ---------------------------------------------------------------------------
# Fully fused suggest kernel
# ---------------------------------------------------------------------------
# The reference recomputes the whole split/sort/fit/sample/score pipeline in
# Python+numpy per suggest() call. Here the entire pipeline is ONE jitted
# program over padded device-resident buffers: γ-split by rank, per-dim sort,
# adaptive bandwidths, recency weights, categorical frequency tables,
# mixture sampling, and EI ranking — no host round-trips, no per-dim Python
# loops. Padding to powers of two keeps the compile count at O(log n) over an
# experiment's lifetime.

_NEG_INF = -jnp.inf
_BIG = 1e9


def _recency_weights(n, idx, full_weight_num, equal_weight: bool):
    """Observation-order weights (lineage forgetting ramp), device-side.

    Matches the host `_weights`: newest ``full_weight_num`` points get weight
    1.0; older points ramp linearly from 1/n up (numpy ``linspace(1/n, 1,
    n - fwn)`` semantics, including the single-element case).
    """
    if equal_weight:
        return jnp.ones_like(idx, dtype=jnp.float32)
    m = n - full_weight_num                      # number of ramped (old) points
    denom = jnp.maximum(m - 1, 1).astype(jnp.float32)
    lo = 1.0 / jnp.maximum(n, 1).astype(jnp.float32)
    ramp = lo + idx.astype(jnp.float32) * (1.0 - lo) / denom
    ramp = jnp.where(m == 1, lo, ramp)           # linspace(1/n, 1, 1) == [1/n]
    w = jnp.where(idx >= m, 1.0, ramp)
    return jnp.where(n <= full_weight_num, 1.0, w)


def _fit_set_device(X, w_sel, count, prior_weight):
    """Per-dim sorted Parzen components for one (masked) observation subset.

    X: (N, d) unit-cube observations (full buffer); w_sel: (N,) recency
    weights, 0.0 outside the subset; count: subset size (traced). Returns
    mu/sigma/logw of shape (N, d) with the prior pseudo-component at row
    ``count`` and -inf log-weight padding elsewhere.
    """
    npad, d = X.shape
    row = jnp.arange(npad)[:, None]                              # (N, 1)
    in_set = w_sel > 0.0

    xg = jnp.where(in_set[:, None], X, _BIG)
    sort_idx = jnp.argsort(xg, axis=0)                           # (N, d)
    xs = jnp.take_along_axis(xg, sort_idx, axis=0)
    ws = jnp.take_along_axis(
        jnp.broadcast_to(w_sel[:, None], (npad, d)), sort_idx, axis=0
    )

    valid = row < count
    prev = jnp.concatenate([jnp.zeros((1, d)), xs[:-1]], axis=0)
    nxt = jnp.concatenate([xs[1:], jnp.full((1, d), _BIG)], axis=0)
    left = xs - prev
    right = jnp.where(row == count - 1, 1.0 - xs, nxt - xs)
    sig = jnp.maximum(left, right)
    sig_min = 1.0 / jnp.minimum(100.0, count.astype(jnp.float32) + 1.0)
    sig = jnp.clip(sig, sig_min, 1.0)
    sig = jnp.where(count == 1, 1.0, sig)        # host rule: single point → 1.0

    is_prior = row == count
    mu = jnp.where(valid, xs, 0.5)
    sigma = jnp.where(valid, sig, 1.0)
    logw = jnp.where(valid, jnp.log(jnp.clip(ws, 1e-12, None)), _NEG_INF)
    logw = jnp.where(is_prior, jnp.log(jnp.maximum(prior_weight, 1e-12)), logw)
    return mu, sigma, logw


def _categorical_cdf(key, logits, shape):
    """Categorical draws: ONE uniform per slot + an inverse-CDF sweep.

    Drop-in for ``jax.random.categorical(key, logits, shape=shape)`` on
    the suggest hot path (same distribution, different bit mapping —
    the Gumbel-max trick burns K gumbels PER draw, two transcendentals
    each, which profiled as the single largest cost of a suggest launch
    on CPU: ~90 us/experiment of a ~250 us body). Here the CDF costs one
    softmax+cumsum over the logits (constant in the draw count) and each
    draw is one uniform plus K compares.

    Selection is "first k with cdf[k] >= u": a zero-probability category
    (-inf logit) has cdf[k] == cdf[k-1] and can never satisfy
    cdf[k] >= u > cdf[k-1], so dead/padded components are never drawn
    (the clamp only guards the u ~ 1.0 rounding edge).
    """
    cdf = jnp.cumsum(jax.nn.softmax(logits, axis=-1), axis=-1)
    u = jax.random.uniform(key, shape, dtype=cdf.dtype)
    draw = jnp.sum(u[..., None] > cdf, axis=-1)
    return jnp.minimum(draw, logits.shape[-1] - 1).astype(jnp.int32)


def _cat_tables_device(X, w_sel, n_choices, prior_weight, kmax: int):
    """Re-weighted category frequency tables, (d, kmax) log-probs."""
    npad, d = X.shape
    k = jnp.maximum(n_choices, 1)                                # (d,)
    cat_idx = jnp.minimum((X * k[None, :]).astype(jnp.int32),
                          (k - 1)[None, :])                      # (N, d)
    col = jnp.arange(kmax)[None, :]                              # (1, K)
    base = jnp.where(col < k[:, None], prior_weight, 0.0)        # (d, K)

    def scatter_one(ci, base_row):
        return base_row.at[ci].add(w_sel)

    counts = jax.vmap(scatter_one, in_axes=(1, 0))(cat_idx, base)  # (d, K)
    probs = counts / jnp.clip(counts.sum(axis=1, keepdims=True), 1e-12, None)
    return jnp.log(jnp.clip(probs, 1e-12, None))


def _tpe_suggest_body(
    X,                   # (N, d) unit-cube observations, padded (N ≥ n+1)
    y,                   # (N,) objectives, +inf padding
    n,                   # scalar int32: live observation count
    count,               # scalar int32: PRNG stream position (fold_in on device)
    base_key,            # PRNG key (created once per algorithm instance)
    n_choices,           # (d,) int32: categories per dim (≤1 for continuous)
    cont_mask,           # (d,) bool: True for continuous dims
    gamma,               # scalar: good-set quantile
    prior_weight,        # scalar: prior pseudo-count / pseudo-component weight
    full_weight_num,     # scalar int32: recency ramp cutoff
    n_prior=0,           # scalar int32: rows 0..n_prior-1 are transfer priors
    transfer_discount=1.0,  # scalar: weight multiplier on those rows
    *,
    n_cand: int,
    n_out: int,
    kmax: int,
    equal_weight: bool,
    n_good_pad: int = 0,
    n_bad_pad: int = 0,
    n_pools: int = 1,
):
    """Whole suggest pools in ONE device program + ONE host readback.

    Scores ``n_cand`` candidates per output slot against a shared l/g fit
    and returns the winners, shape (n_pools * n_out, d) — ``n_pools``
    independent prefetch pools, each keyed ``fold_in(base_key, count + p)``
    so pool ``p`` draws the EXACT stream a separate launch at stream
    position ``count + p`` would (counter-based threefry: no state carries
    between pools). One call serves every pool — essential on tunneled PJRT
    backends where a blocking device→host readback costs ~70 ms regardless
    of payload size.

    The good/bad sets are COMPACTED before fitting: the γ-split selects
    ``n_below`` good rows out of n, so density evaluation runs over
    ``n_good_pad``/``n_bad_pad`` components (pads of n_below+1 and
    n−n_below+1, computed host-side from the live count with the same
    formula as the in-kernel split) instead of 2× the full buffer — at
    γ=0.25 that cuts the O(C·N·d) inner product roughly in half. Pass
    0 (default) to fit over the full buffer width.
    """
    npad, d = X.shape
    if not n_good_pad:
        n_good_pad = npad
    if not n_bad_pad:
        n_bad_pad = npad
    idx = jnp.arange(npad)

    # γ-split by objective rank (padding sorts last via +inf)
    order = jnp.argsort(jnp.where(idx < n, y, jnp.inf))
    n_below = jnp.minimum(
        jnp.maximum(1, jnp.ceil(gamma * n).astype(jnp.int32)),
        jnp.maximum(n, 1),
    )
    # safety clamp: the caller sized n_good_pad from the same formula on the
    # host; never let a rounding divergence index past the prior row
    n_below = jnp.minimum(n_below, n_good_pad - 1)
    w_obs = _recency_weights(n, idx, full_weight_num, equal_weight)
    # transfer priors (EVC warm-start) occupy the OLDEST rows; their
    # evidence is discounted so locally-measured points dominate the fit
    # as soon as they exist. Traced scalars: no new compile variants.
    w_obs = w_obs * jnp.where(idx < n_prior, transfer_discount, 1.0)
    ng = jnp.minimum(n_below, n)
    nb = jnp.maximum(n - n_below, 0)

    # compact gather: good rows are order[0:n_below], bad rows follow
    gpos = jnp.arange(n_good_pad)
    gsel = order[jnp.minimum(gpos, npad - 1)]
    w_good = jnp.where(gpos < ng, w_obs[gsel], 0.0)
    Xg = X[gsel]
    bpos = n_below + jnp.arange(n_bad_pad)
    bsel = order[jnp.minimum(bpos, npad - 1)]
    w_bad = jnp.where(bpos < n, w_obs[bsel], 0.0)
    Xb = X[bsel]

    g_mu, g_sig, g_logw = _fit_set_device(Xg, w_good, ng, prior_weight)
    b_mu, b_sig, b_logw = _fit_set_device(Xb, w_bad, nb, prior_weight)
    g_cat = _cat_tables_device(Xg, w_good, n_choices, prior_weight, kmax)
    b_cat = _cat_tables_device(Xb, w_bad, n_choices, prior_weight, kmax)

    # ---- per pool: sample n_out slots of n_cand candidates from l ----
    dim_idx = jnp.arange(d)[None, :]                             # (1, d)
    C = n_out * n_cand
    k = jnp.maximum(n_choices, 1)
    cat_logits = jnp.where(jnp.arange(kmax)[None, :] < k[:, None],
                           g_cat, _NEG_INF)                      # (d, K)

    outs = []
    for p in range(n_pools):
        key = jax.random.fold_in(base_key, count + p)
        k_comp, k_draw, k_redraw, k_cat = jax.random.split(key, 4)

        comp = _categorical_cdf(k_comp, g_logw.T, (C, d))
        mu_c = g_mu[comp, dim_idx]
        sig_c = g_sig[comp, dim_idx]
        draws = mu_c + sig_c * jax.random.normal(k_draw, (C, d))
        redraw = mu_c + sig_c * jax.random.normal(k_redraw, (C, d))
        oob = (draws < 0.0) | (draws > 1.0)
        draws = jnp.clip(jnp.where(oob, redraw, draws), 1e-6, 1.0 - 1e-6)

        cats = _categorical_cdf(k_cat, cat_logits, (C, d))
        cat_vals = (cats.astype(jnp.float32) + 0.5) / k[None, :]

        cand = jnp.where(cont_mask[None, :], draws, cat_vals)    # (C, d)
        cand_cat = jnp.minimum((cand * k[None, :]).astype(jnp.int32),
                               (k - 1)[None, :])

        # ---- EI ranking: log l(x) - log g(x) ----
        log_l = _mixture_logpdf(cand, g_mu, g_sig, g_logw)
        log_g = _mixture_logpdf(cand, b_mu, b_sig, b_logw)
        log_l = jnp.where(cont_mask[None, :], log_l,
                          g_cat[dim_idx, cand_cat])
        log_g = jnp.where(cont_mask[None, :], log_g,
                          b_cat[dim_idx, cand_cat])
        scores = jnp.sum(log_l - log_g, axis=1).reshape(n_out, n_cand)
        winners = jnp.argmax(scores, axis=1)                     # (n_out,)
        outs.append(
            cand.reshape(n_out, n_cand, d)[jnp.arange(n_out), winners]
        )
    return outs[0] if n_pools == 1 else jnp.concatenate(outs, axis=0)


#: the per-experiment entry point: ONE experiment, one jitted program.
#: The traced pipeline lives in ``_tpe_suggest_body`` so the fleet kernel
#: below vmaps the IDENTICAL computation — bit-identity of fused vs
#: per-experiment suggestions reduces to "same body, same inputs".
tpe_suggest_fused = functools.partial(
    jax.jit,
    static_argnames=(
        "n_cand", "n_out", "kmax", "equal_weight",
        "n_good_pad", "n_bad_pad", "n_pools",
    ),
)(_tpe_suggest_body)


def _stk(col):
    """Column-stack a fleet input inside the trace.

    Each column arrives either already stacked (a (B, ...) array — the
    test-friendly form) or as a TUPLE of B per-experiment leaves — the
    bucket-native form the fuser passes. Tuples are stacked HERE, inside
    the jitted program: the stack compiles into the launch (one dispatch
    for the whole bucket instead of ~2 dispatched host ops per column
    per member, which measured 14 ms of a 32 ms sweep at B=16), and
    device-resident buffers are stacked device-side, never touching the
    host. The tuple length is part of the jit cache key, which is fine:
    it equals the pow2-padded bucket size the static key already pins.
    """
    return jnp.stack(col) if isinstance(col, (tuple, list)) else col


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_cand", "n_out", "kmax", "equal_weight",
        "n_good_pad", "n_bad_pad", "n_pools",
    ),
)
def tpe_suggest_fleet(
    X,                   # (B, N, d) stacked — or a B-tuple of (N, d)
    y,                   # (B, N) objectives, +inf padding
    n,                   # (B,) int32 live counts (may differ within a pad)
    count,               # (B,) int32 PRNG stream positions
    base_key,            # (B, key) per-experiment base keys
    n_choices,           # (B, d) int32
    cont_mask,           # (B, d) bool
    gamma,               # (B,) float32
    prior_weight,        # (B,) float32
    full_weight_num,     # (B,) float32
    n_prior,             # (B,) int32
    transfer_discount,   # (B,) float32
    *,
    n_cand: int,
    n_out: int,
    kmax: int,
    equal_weight: bool,
    n_good_pad: int = 0,
    n_bad_pad: int = 0,
    n_pools: int = 1,
):
    """``tpe_suggest_fused`` for a BUCKET of experiments in ONE launch.

    vmaps ``_tpe_suggest_body`` over a leading experiment axis: every
    per-experiment quantity (buffer, live count, stream position, space
    encoding, hyperparameters) is stacked and traced, while the bucket
    key's statics (pads, candidate/pool widths, kmax, equal_weight) are
    uniform across members — that is exactly what makes two experiments
    bucket-compatible (coord/fuser.py). Every column accepts either the
    stacked (B, ...) array or a B-tuple of per-experiment leaves, which
    is stacked in-trace (see ``_stk``). Row b of the result is bitwise
    the array ``tpe_suggest_fused`` would return for experiment b alone:
    the body is the same traced code, reductions keep their per-row
    order under the batch dim, and the PRNG is counter-based per
    experiment (fold_in of b's own key — nothing crosses the stack
    axis). Returns (B, n_pools * n_out, d).
    """
    body = functools.partial(
        _tpe_suggest_body,
        n_cand=n_cand, n_out=n_out, kmax=kmax, equal_weight=equal_weight,
        n_good_pad=n_good_pad, n_bad_pad=n_bad_pad, n_pools=n_pools,
    )
    return jax.vmap(body)(
        _stk(X), _stk(y), _stk(n), _stk(count), _stk(base_key),
        _stk(n_choices), _stk(cont_mask), _stk(gamma), _stk(prior_weight),
        _stk(full_weight_num), _stk(n_prior), _stk(transfer_discount),
    )


def split_pads(n: int, gamma: float) -> tuple:
    """Static (n_good_pad, n_bad_pad) for a live count, mirroring the
    in-kernel γ-split so the compacted fit always has room for the subset
    plus its prior pseudo-component row. float32 math on purpose — it must
    round exactly like the traced ``ceil(gamma * n)`` inside the kernel."""
    n = int(n)
    n_below = int(np.ceil(np.float32(gamma) * np.float32(n)))
    n_below = min(max(1, n_below), max(n, 1))
    return pad_pow2(n_below + 1), pad_pow2(max(n - n_below, 0) + 1)
