"""TPE surrogate math as XLA-compiled array kernels.

ref mechanism: src/metaopt/algo/tpe.py (SURVEY.md §2.3 [HIGH]): observations
split at the γ-quantile into good/bad sets; per-dimension adaptive-bandwidth
Parzen estimators l(x) and g(x); candidates drawn from l and ranked by
EI ∝ l(x)/g(x). The reference evaluates these densities in Python/numpy per
suggest call; here the density evaluation — the O(candidates × observations ×
dims) part that grows with trial count — is a single jitted kernel over
[0,1]-cube arrays, with observation counts padded to powers of two so XLA
compiles at most O(log n) variants over an experiment's lifetime (this is
what keeps suggest() latency flat past 10k trials, per BASELINE.md).

Everything here is pure and shape-explicit; host-side control plane lives in
:mod:`metaopt_tpu.algo.tpe`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_SQRT2 = 1.4142135623730951


def pad_pow2(n: int, minimum: int = 8) -> int:
    """Smallest power of two ≥ max(n, minimum)."""
    p = minimum
    while p < n:
        p *= 2
    return p


def adaptive_bandwidths(sorted_mu: np.ndarray) -> np.ndarray:
    """Per-component sigmas for a 1-D Parzen mixture on [0, 1].

    Classic adaptive-Parzen rule: each point's sigma is the larger of the
    gaps to its sorted neighbours (edge points use the gap to the domain
    bound), clipped to [1/min(100, n+1), 1]. Host-side numpy — O(n) after the
    caller's sort, negligible next to density evaluation.
    """
    n = len(sorted_mu)
    if n == 0:
        return np.zeros(0)
    if n == 1:
        return np.ones(1)
    ext = np.concatenate([[0.0], sorted_mu, [1.0]])
    left = sorted_mu - ext[:-2]
    right = ext[2:] - sorted_mu
    sig = np.maximum(left, right)
    sig_min = 1.0 / min(100.0, n + 1.0)
    return np.clip(sig, sig_min, 1.0)


def _truncnorm_mixture_logpdf_1d(
    x: jnp.ndarray,      # (C,) evaluation points in [0,1]
    mu: jnp.ndarray,     # (N,) component means
    sigma: jnp.ndarray,  # (N,) component sigmas (>0 even for padding)
    logw: jnp.ndarray,   # (N,) log mixture weights (-inf for padding)
) -> jnp.ndarray:        # (C,)
    """log pdf of a weighted mixture of [0,1]-truncated Gaussians."""
    z = (x[:, None] - mu[None, :]) / sigma[None, :]
    log_phi = -0.5 * z * z - 0.5 * jnp.log(2 * jnp.pi) - jnp.log(sigma[None, :])
    # truncation mass on [0,1] per component
    a = jax.scipy.special.ndtr((1.0 - mu) / sigma)
    b = jax.scipy.special.ndtr((0.0 - mu) / sigma)
    log_mass = jnp.log(jnp.clip(a - b, 1e-12, 1.0))
    return jax.scipy.special.logsumexp(
        log_phi - log_mass[None, :] + logw[None, :], axis=1
    )


#: vmap over dimensions: x (C,d), mu (N,d), sigma (N,d), logw (N,d)
#: (weights are per-dim because adaptive bandwidths sort components per dim)
_mixture_logpdf = jax.vmap(
    _truncnorm_mixture_logpdf_1d, in_axes=(1, 1, 1, 1), out_axes=1
)


@functools.partial(jax.jit, static_argnames=())
def ei_scores(
    cand: jnp.ndarray,          # (C, d) candidates in the unit cube
    good_mu: jnp.ndarray,       # (Ng, d)
    good_sigma: jnp.ndarray,    # (Ng, d)
    good_logw: jnp.ndarray,     # (Ng, d)
    bad_mu: jnp.ndarray,        # (Nb, d)
    bad_sigma: jnp.ndarray,     # (Nb, d)
    bad_logw: jnp.ndarray,      # (Nb, d)
    cont_mask: jnp.ndarray,     # (d,) 1.0 for continuous cols, 0.0 for categorical
    cand_cat_idx: jnp.ndarray,  # (C, d) int32 category index (0 for cont cols)
    good_cat_logp: jnp.ndarray, # (d, K) per-dim category log-probs under l
    bad_cat_logp: jnp.ndarray,  # (d, K) per-dim category log-probs under g
) -> jnp.ndarray:               # (C,) EI score = log l(x) - log g(x)
    """Expected-improvement ranking for TPE: log l(x) − log g(x).

    Continuous columns use truncated-Gaussian Parzen mixtures; categorical
    columns use re-weighted category frequency tables (the reference's
    mechanism for categorical dims). One fused kernel — XLA maps the
    (C × N × d) inner product onto the VPU and fuses the masked reduction.
    """
    log_l_cont = _mixture_logpdf(cand, good_mu, good_sigma, good_logw)   # (C, d)
    log_g_cont = _mixture_logpdf(cand, bad_mu, bad_sigma, bad_logw)     # (C, d)

    d_idx = jnp.arange(cand.shape[1])[None, :]                           # (1, d)
    log_l_cat = good_cat_logp[d_idx, cand_cat_idx]                       # (C, d)
    log_g_cat = bad_cat_logp[d_idx, cand_cat_idx]                        # (C, d)

    log_l = jnp.where(cont_mask[None, :] > 0, log_l_cont, log_l_cat)
    log_g = jnp.where(cont_mask[None, :] > 0, log_g_cont, log_g_cat)
    return jnp.sum(log_l - log_g, axis=1)
