"""Blocked softmax cross-entropy against a (tied) readout table.

The flagship Transformer's loss was the MFU ceiling at short sequence: the
readout einsum materializes an f32 ``(B, T, V)`` logits tensor (2.1 GB at
batch 64 × seq 256 × vocab 32k) and ``optax.softmax_cross_entropy...``
makes several more full passes over it — all HBM traffic, no MXU work.
(ref: the lineage has no equivalent; SURVEY.md §6 MFU north star.)

This op never materializes the logits. Forward is a ``lax.scan`` over
vocab blocks: each block's logits tile ``y @ embᵀ[block]`` feeds an online
logsumexp (the flash-attention trick applied to the softmax denominator)
and the label logit is gathered blockwise; live memory is O(B·T·block_v).
Backward recomputes each block's probabilities from the saved (lse,
label_logit) and accumulates dY and dEmb per block — two more MXU matmuls
per block instead of a (B, T, V) round-trip through HBM.

FLOP cost: 2·N·D·V forward + 6·N·D·V backward (one logits recompute, dY,
dEmb) vs 2+4 for the materializing path — 33% more readout FLOPs traded
for never touching a (N, V) f32 tensor in HBM. On bandwidth-bound shapes
that trade wins by construction; bench.py measures it (mfu_seq256).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def pick_block_v(vocab: int, target: int = 4096) -> int:
    """Largest divisor of ``vocab`` ≤ target (the scan's tile width).

    Falls back to the whole vocab when no useful divisor exists (e.g. a
    prime vocab) — one big "block" degrades to the materializing path for
    that call, which is correct, just not faster.
    """
    best = vocab
    for cand in range(min(target, vocab), 0, -1):
        if vocab % cand == 0:
            best = cand
            break
    # a block much narrower than asked (worst case 1, for a prime vocab)
    # would make the scan absurdly long — degrade to one whole-vocab block
    return best if best >= max(1, target // 8) else vocab


def _block_logits(y, emb_block):
    """(N, bv) f32 logits tile for one vocab block; bf16 in, f32 accum."""
    return jax.lax.dot_general(
        y, emb_block, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _fwd_scan(y, emb, labels, n_blocks, block_v):
    """(lse, label_logit) via online logsumexp over vocab blocks."""
    n = y.shape[0]

    def body(carry, i):
        m, l, lab = carry
        eb = jax.lax.dynamic_slice_in_dim(emb, i * block_v, block_v, axis=0)
        s = _block_logits(y, eb)                          # (N, bv)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        l_new = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(s - m_new[:, None]), axis=-1
        )
        # gather this block's label logits where the label falls inside it
        loc = labels - i * block_v
        inside = (loc >= 0) & (loc < block_v)
        picked = jnp.take_along_axis(
            s, jnp.clip(loc, 0, block_v - 1)[:, None], axis=1
        )[:, 0]
        lab = jnp.where(inside, picked, lab)
        return (m_new, l_new, lab), None

    m0 = jnp.full((n,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((n,), jnp.float32)
    lab0 = jnp.zeros((n,), jnp.float32)
    (m, l, lab), _ = jax.lax.scan(body, (m0, l0, lab0), jnp.arange(n_blocks))
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return lse, lab


# mtpu: hotpath
@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def blocked_softmax_xent(y, emb, labels, block_v: int = 2048):
    """Per-token ``lse(y·embᵀ) - (y·embᵀ)[label]`` without (N, V) logits.

    y: (N, D) features (bf16 recommended); emb: (V, D) readout/embedding
    table; labels: (N,) int32 in [0, V). ``block_v`` must divide V — use
    :func:`pick_block_v` to choose one (padding the table instead would
    add spurious exp(y·pad) mass to every denominator). Returns (N,) f32
    losses. Differentiable in y and emb.
    """
    loss, _ = _xent_fwd_impl(y, emb, labels, block_v)
    return loss


def _xent_fwd_impl(y, emb, labels, block_v):
    v = emb.shape[0]
    assert v % block_v == 0, (v, block_v)
    lse, lab = _fwd_scan(y, emb, labels, v // block_v, block_v)
    return lse - lab, (lse, lab)


def _xent_fwd(y, emb, labels, block_v):
    # custom_vjp fwd keeps the primal signature; only bwd gets the
    # nondiff argnums hoisted to the front
    loss, (lse, _) = _xent_fwd_impl(y, emb, labels, block_v)
    return loss, (y, emb, labels, lse)


def _xent_bwd(block_v, res, g):
    """dY, dEmb from recomputed per-block probabilities.

    d loss / d logits = softmax(logits) − onehot(label); chain with g (N,).
    """
    y, emb, labels, lse = res
    v, _ = emb.shape
    n_blocks = v // block_v
    gf = g.astype(jnp.float32)

    def body(dy, i):
        eb = jax.lax.dynamic_slice_in_dim(emb, i * block_v, block_v, axis=0)
        s = _block_logits(y, eb)                          # (N, bv)
        p = jnp.exp(s - lse[:, None])                     # softmax tile
        loc = labels - i * block_v
        inside = (loc >= 0) & (loc < block_v)
        onehot = (
            (jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
             == jnp.clip(loc, 0, block_v - 1)[:, None])
            & inside[:, None]
        )
        ds = (p - onehot.astype(jnp.float32)) * gf[:, None]
        dy = dy + jax.lax.dot_general(                    # ds·emb (N, D)
            ds, eb.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        demb_b = jax.lax.dot_general(                     # dsᵀ·y (bv, D)
            ds, y.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dy, demb_b

    dy0 = jnp.zeros((y.shape[0], y.shape[1]), jnp.float32)
    dy, demb_blocks = jax.lax.scan(body, dy0, jnp.arange(n_blocks))
    demb = demb_blocks.reshape(v, y.shape[1])
    return dy.astype(y.dtype), demb.astype(emb.dtype), None


blocked_softmax_xent.defvjp(_xent_fwd, _xent_bwd)
