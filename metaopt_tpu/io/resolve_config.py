"""Layered configuration resolution: defaults < env vars < YAML < argv.

ref: src/metaopt/core/io/resolve_config.py. The precedence order is the
lineage's signature behavior and is preserved verbatim; the env-var prefix is
``METAOPT_TPU_``. Also collects experiment metadata (user, utc datetime, the
full user command line) the way the reference stamps experiments.
"""

from __future__ import annotations

import copy
import datetime
import getpass
import os
from typing import Any, Dict, List, Optional

import yaml

DEFAULTS: Dict[str, Any] = {
    "name": None,
    "max_trials": 100,
    "pool_size": 1,
    "worker_trials": None,        # cap on trials run by THIS worker (None = unlimited)
    "algorithm": {"random": {"seed": None}},
    "ledger": {"type": "file", "path": None},  # path defaults to ~/.metaopt_tpu/<name>
    "executor": {"type": "subprocess", "n_chips": 1},
    "coordinator": {"host": "127.0.0.1", "port": 0},
    "heartbeat_s": 30.0,
    "working_dir": None,
}

ENV_VARS: Dict[str, str] = {
    "METAOPT_TPU_NAME": "name",
    "METAOPT_TPU_MAX_TRIALS": "max_trials",
    "METAOPT_TPU_POOL_SIZE": "pool_size",
    "METAOPT_TPU_LEDGER_TYPE": "ledger.type",
    "METAOPT_TPU_LEDGER_PATH": "ledger.path",
    "METAOPT_TPU_COORD_HOST": "coordinator.host",
    "METAOPT_TPU_COORD_PORT": "coordinator.port",
}

_INT_KEYS = {"max_trials", "pool_size", "worker_trials", "coordinator.port"}


def _set_path(cfg: Dict[str, Any], dotted: str, value: Any) -> None:
    node = cfg
    *parents, leaf = dotted.split(".")
    for p in parents:
        node = node.setdefault(p, {})
    node[leaf] = value


#: keys whose dict value REPLACES the lower layer instead of deep-merging —
#: an algorithm choice is atomic ({"asha": ...} must not union with the
#: default {"random": ...} into a two-key config)
_REPLACE_KEYS = {"algorithm"}


def _merge(base: Dict[str, Any], overlay: Dict[str, Any]) -> Dict[str, Any]:
    out = copy.deepcopy(base)
    for k, v in overlay.items():
        if k in _REPLACE_KEYS and v is not None:
            out[k] = copy.deepcopy(v)
        elif isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge(out[k], v)
        elif v is not None:
            out[k] = v
    return out


def fetch_metadata(user_args: Optional[List[str]] = None) -> Dict[str, Any]:
    """Experiment metadata stamped at creation, mirroring the reference."""
    return {
        "user": os.environ.get("METAOPT_TPU_USER") or getpass.getuser(),
        "datetime": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "user_args": list(user_args or []),
        "framework_version": _version(),
    }


def _version() -> str:
    from metaopt_tpu import __version__

    return __version__


def resolve_config(
    cmdargs: Optional[Dict[str, Any]] = None,
    config_path: Optional[str] = None,
) -> Dict[str, Any]:
    """Merge defaults < environment < yaml file < explicit command args."""
    cfg = copy.deepcopy(DEFAULTS)

    env_overlay: Dict[str, Any] = {}
    for var, dotted in ENV_VARS.items():
        if var in os.environ:
            raw: Any = os.environ[var]
            if dotted in _INT_KEYS:
                raw = int(raw)
            _set_path(env_overlay, dotted, raw)
    cfg = _merge(cfg, env_overlay)

    if config_path:
        with open(config_path) as f:
            file_cfg = yaml.safe_load(f) or {}
        if not isinstance(file_cfg, dict):
            raise ValueError(f"config file {config_path!r} must contain a mapping")
        cfg = _merge(cfg, file_cfg)

    if cmdargs:
        cfg = _merge(cfg, {k: v for k, v in cmdargs.items() if v is not None})

    return cfg
