"""Read-only REST API over the ledger.

ref: the reference lineage's serving layer (a REST API over experiments and
trials; post-v0 in the lineage — SURVEY.md §5 records only `status`-style
observability for the v0 era). Re-based here as a thin stdlib HTTP server
over the ledger, so dashboards can poll a hunt without touching the
coordinator's write path:

- ``GET /``                               → route list
- ``GET /experiments``                    → summaries (mtpu list)
- ``GET /experiments/{name}``             → full document + stats (mtpu info)
- ``GET /experiments/{name}/trials``      → trial docs (``?status=`` filter)
- ``GET /experiments/{name}/regret``      → best-so-far series (mtpu plot)
- ``GET /experiments/{name}/lcurves``     → objective per fidelity budget
  per lineage (mtpu plot lcurve)
- ``GET /healthz``                        → liveness

Deliberately read-only: every write still flows through the single-writer
coordinator or the flock'd file ledger, so this server can never introduce
a new race surface.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from metaopt_tpu.ledger.backends import LedgerBackend
from metaopt_tpu.ledger.trial import STATUSES

log = logging.getLogger(__name__)


def _experiment_summary(ledger: LedgerBackend, name: str) -> Dict[str, Any]:
    """One-line experiment status; also the backing store for ``mtpu list``.

    Shared so the CLI and the REST surface can never disagree on what
    "done" means. missing/None ``max_trials`` = unbounded (never done by
    count alone).
    """
    doc = ledger.load_experiment(name) or {}
    completed = ledger.count(name, "completed")
    max_trials = doc.get("max_trials")
    return {
        "name": name,
        "version": doc.get("version", 1),
        "algorithm": next(iter(doc.get("algorithm", {})), None),
        "trials": ledger.count(name),
        "completed": completed,
        "max_trials": max_trials,
        "done": bool(doc.get("algo_done"))
        or (max_trials is not None and completed >= max_trials),
    }


def _experiment_detail(ledger: LedgerBackend, name: str) -> Optional[Dict[str, Any]]:
    from metaopt_tpu.ledger.experiment import Experiment

    doc = ledger.load_experiment(name)
    if doc is None:
        return None
    s = Experiment(name, ledger).configure().stats
    return {**doc, "stats": {"by_status": s["by_status"], "best": s["best"]}}


def regret_series(ledger: LedgerBackend, name: str) -> List[Dict[str, Any]]:
    """Best-so-far objective per completed trial (shared with `mtpu plot`)."""
    done = [t for t in ledger.fetch(name, "completed")
            if t.objective is not None]

    done.sort(key=lambda t: t.end_time or t.submit_time or 0.0)
    out, best = [], float("inf")
    for i, t in enumerate(done):
        best = min(best, t.objective)
        out.append({"trial": i, "objective": t.objective, "best": best,
                    "id": t.id})
    return out


def parallel_series(ledger: LedgerBackend, name: str):
    """(dimensions, rows) for parallel-coordinates rendering.

    Shared by `mtpu plot parallel` and GET /experiments/{name}/parallel.
    """
    doc = ledger.load_experiment(name) or {}
    dims = sorted((doc.get("space") or {}).keys())
    rows = [
        {**{d: t.params.get(d) for d in dims}, "objective": t.objective}
        for t in ledger.fetch(name, "completed")
        if t.objective is not None
    ]
    return dims, rows


def lcurve_series(ledger: LedgerBackend, name: str):
    """(fidelity_name, {lineage: [{budget, objective}...]}) or (None, {}).

    Shared by `mtpu plot lcurve` and GET /experiments/{name}/lcurves.
    """
    from metaopt_tpu.space import build_space

    doc = ledger.load_experiment(name)
    if doc is None or not doc.get("space"):
        return None, {}
    space = build_space(doc["space"])
    fid = space.fidelity
    if fid is None:
        return None, {}
    curves: Dict[str, List[Dict[str, Any]]] = {}
    for t in ledger.fetch(name, "completed"):
        if t.objective is None or fid.name not in t.params:
            continue
        lineage = t.lineage or space.hash_point(t.params)
        curves.setdefault(lineage, []).append(
            {"budget": int(t.params[fid.name]), "objective": t.objective}
        )
    for pts in curves.values():
        pts.sort(key=lambda p: p["budget"])
    return fid.name, curves


class _Handler(BaseHTTPRequestHandler):
    ledger: LedgerBackend  # set by make_server on the class

    def log_message(self, fmt, *args):  # route through logging, not stderr
        log.debug("webapi: " + fmt, *args)

    def _send(self, code: int, payload: Any) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        try:
            url = urlparse(self.path)
            parts = [p for p in url.path.split("/") if p]
            query = parse_qs(url.query)
            code, payload = self._route(parts, query)
        except Exception as err:  # a bad request must not kill the thread
            log.exception("webapi error for %s", self.path)
            code, payload = 500, {"error": str(err)}
        self._send(code, payload)

    def _route(self, parts: List[str], query) -> Tuple[int, Any]:
        ledger = self.ledger
        if not parts:
            return 200, {"routes": [
                "/experiments", "/experiments/{name}",
                "/experiments/{name}/trials", "/experiments/{name}/regret",
                "/experiments/{name}/lcurves",
                "/experiments/{name}/parallel", "/healthz",
            ]}
        if parts == ["healthz"]:
            return 200, {"ok": True}
        if parts[0] != "experiments" or len(parts) > 3:
            return 404, {"error": f"unknown route /{'/'.join(parts)}"}
        if len(parts) == 1:
            return 200, [
                _experiment_summary(ledger, n)
                for n in sorted(ledger.list_experiments())
            ]
        name = parts[1]
        if ledger.load_experiment(name) is None:
            return 404, {"error": f"no such experiment: {name}"}
        if len(parts) == 2:
            return 200, _experiment_detail(ledger, name)
        if parts[2] == "trials":
            status = (query.get("status") or [None])[0]
            if status is not None and status not in STATUSES:
                return 400, {"error": f"status must be one of {STATUSES}"}
            return 200, [t.to_dict() for t in ledger.fetch(name, status)]
        if parts[2] == "regret":
            return 200, {"experiment": name,
                         "regret": regret_series(ledger, name)}
        if parts[2] == "lcurves":
            fid_name, curves = lcurve_series(ledger, name)
            if fid_name is None:
                return 400, {"error": f"{name!r} has no fidelity dimension"}
            return 200, {"experiment": name, "fidelity": fid_name,
                         "lcurves": curves}
        if parts[2] == "parallel":
            dims, rows = parallel_series(ledger, name)
            return 200, {"experiment": name, "dimensions": dims,
                         "trials": rows}
        return 404, {"error": f"unknown route /{'/'.join(parts)}"}


def make_server(
    ledger: LedgerBackend, host: str = "127.0.0.1", port: int = 0
) -> ThreadingHTTPServer:
    """A bound (not yet serving) HTTP server; port 0 picks an ephemeral one."""
    handler = type("BoundHandler", (_Handler,), {"ledger": ledger})
    return ThreadingHTTPServer((host, port), handler)


def serve_forever(server: ThreadingHTTPServer) -> None:
    host, port = server.server_address[:2]
    print(f"webapi listening on http://{host}:{port}", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()


def start_in_thread(server: ThreadingHTTPServer) -> threading.Thread:
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return t
