"""Read-only REST API over the ledger.

ref: the reference lineage's serving layer (a REST API over experiments and
trials; post-v0 in the lineage — SURVEY.md §5 records only `status`-style
observability for the v0 era). Re-based here as a thin stdlib HTTP server
over the ledger, so dashboards can poll a hunt without touching the
coordinator's write path:

- ``GET /``                               → route list
- ``GET /dashboard``                      → self-contained HTML dashboard
  (experiments table + live regret chart; polls the JSON routes, no
  external assets, dark-mode aware)
- ``GET /experiments``                    → summaries (mtpu list)
- ``GET /experiments/{name}``             → full document + stats (mtpu info)
- ``GET /experiments/{name}/trials``      → trial docs (``?status=`` filter)
- ``GET /experiments/{name}/regret``      → best-so-far series (mtpu plot)
- ``GET /experiments/{name}/lcurves``     → objective per fidelity budget
  per lineage (mtpu plot lcurve)
- ``GET /experiments/{name}/importance``  → per-parameter importance from
  the ARD GP surrogate (mtpu plot importance)
- ``GET /experiments/{name}/pareto``      → nondominated front over the
  trials' objective vectors (mtpu plot pareto; multi-objective runs)
- ``GET /experiments/{name}/workers``     → per-worker liveness derived
  from trial ownership + heartbeats (mtpu status --workers)
- ``GET /experiments/{name}/pdp``         → 1-D partial dependence per
  parameter under the fitted ARD GP (mtpu plot pdp)
- ``GET /healthz``                        → liveness

Deliberately read-only: every write still flows through the single-writer
coordinator or the flock'd file ledger, so this server can never introduce
a new race surface.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from metaopt_tpu.ledger.backends import LedgerBackend
from metaopt_tpu.ledger.trial import STATUSES

log = logging.getLogger(__name__)


def _experiment_summary(ledger: LedgerBackend, name: str) -> Dict[str, Any]:
    """One-line experiment status; also the backing store for ``mtpu list``.

    Shared so the CLI and the REST surface can never disagree on what
    "done" means. missing/None ``max_trials`` = unbounded (never done by
    count alone).
    """
    doc = ledger.load_experiment(name) or {}
    completed = ledger.count(name, "completed")
    max_trials = doc.get("max_trials")
    from metaopt_tpu.ledger.evc import branch_parent

    return {
        "name": name,
        "version": doc.get("version", 1),
        "parent": branch_parent(doc),
        "algorithm": next(iter(doc.get("algorithm", {})), None),
        "trials": ledger.count(name),
        "completed": completed,
        "max_trials": max_trials,
        "done": bool(doc.get("algo_done"))
        or (max_trials is not None and completed >= max_trials),
    }


def _experiment_detail(ledger: LedgerBackend, name: str) -> Optional[Dict[str, Any]]:
    from metaopt_tpu.ledger.experiment import Experiment

    doc = ledger.load_experiment(name)
    if doc is None:
        return None
    s = Experiment(name, ledger).configure().stats
    return {**doc, "stats": {"by_status": s["by_status"], "best": s["best"]}}


def worker_table(ledger: LedgerBackend, name: str) -> List[Dict[str, Any]]:
    """Per-worker liveness derived from trial ownership + heartbeats.

    The reference lineage's worker visibility came from querying Mongo for
    reserved trials; here the same derivation is a first-class surface:
    every trial records its owning worker, reserved trials carry the
    heartbeat the executor pumps, finished trials keep their end time.
    No extra registry — the ledger already knows. Shared by
    ``mtpu status --workers`` and ``GET /experiments/{name}/workers``.
    """
    now = time.time()
    workers: Dict[str, Dict[str, Any]] = {}
    for t in ledger.fetch(name):
        w = t.worker
        if not w:
            continue
        rec = workers.setdefault(w, {
            "worker": w, "reserved": 0, "completed": 0, "broken": 0,
            "interrupted": 0, "suspended": 0, "current": [],
            "last_seen": None,
        })
        if t.status in rec:
            rec[t.status] += 1
        if t.status == "reserved":
            rec["current"].append(t.id)
            seen = t.heartbeat or t.start_time
        else:
            seen = t.end_time or t.heartbeat
        if seen and (rec["last_seen"] is None or seen > rec["last_seen"]):
            rec["last_seen"] = seen
    out = sorted(workers.values(),
                 key=lambda r: -(r["last_seen"] or 0.0))
    for r in out:
        r["last_seen_age_s"] = (
            round(now - r["last_seen"], 1)
            if r["last_seen"] is not None else None
        )
    return out


def completed_in_order(ledger: LedgerBackend, name: str):
    """Completed trials sorted by completion time — THE progress order.

    Single source for every progress series (regret, lcurves,
    hypervolume-so-far): if the ordering semantics ever change, every
    surface moves together.
    """
    done = list(ledger.fetch(name, "completed"))
    done.sort(key=lambda t: t.end_time or t.submit_time or 0.0)
    return done


def regret_series(ledger: LedgerBackend, name: str) -> List[Dict[str, Any]]:
    """Best-so-far objective per completed trial (shared with `mtpu plot`)."""
    done = [t for t in completed_in_order(ledger, name)
            if t.objective is not None]
    out, best = [], float("inf")
    for i, t in enumerate(done):
        best = min(best, t.objective)
        out.append({"trial": i, "objective": t.objective, "best": best,
                    "id": t.id})
    return out


def parallel_series(ledger: LedgerBackend, name: str):
    """(dimensions, rows) for parallel-coordinates rendering.

    Shared by `mtpu plot parallel` and GET /experiments/{name}/parallel.
    """
    doc = ledger.load_experiment(name) or {}
    dims = sorted((doc.get("space") or {}).keys())
    rows = [
        {**{d: t.params.get(d) for d in dims}, "objective": t.objective}
        for t in ledger.fetch(name, "completed")
        if t.objective is not None
    ]
    return dims, rows


def _surrogate_inputs(ledger: LedgerBackend, name: str):
    """Shared loader for the GP-surrogate analyses (importance, pdp).

    Returns ``((cube, X, y), None)`` or ``(None, (status, payload))``.
    Only FINITE objectives count toward the 4-trial floor — a diverged
    (NaN/inf) trial contributes nothing to either analysis, and letting
    it through would turn a user-data condition into a 500 downstream.
    Column naming comes from ``cube.names`` (fidelity dims excluded,
    shaped dims expanded), the exact layout the fitted surrogate sees —
    ``space.keys()`` would misalign on any multi-fidelity experiment.
    """
    import math

    from metaopt_tpu.space import UnitCube, build_space

    doc = ledger.load_experiment(name) or {}
    if not doc.get("space"):
        return None, (400, {"error": f"{name!r} has no stored space"})
    space = build_space(doc["space"])
    done = [t for t in ledger.fetch(name, "completed")
            if t.objective is not None and math.isfinite(t.objective)]
    if len(done) < 4:
        return None, (400, {"error": f"need at least 4 completed trials "
                                     f"with finite objectives, have "
                                     f"{len(done)}"})
    import numpy as np

    cube = UnitCube(space)
    X = np.stack([cube.transform(t.params) for t in done])
    y = np.asarray([t.objective for t in done], np.float32)
    return (cube, X, y), None


def importance_series(ledger: LedgerBackend, name: str) -> Tuple[int, Any]:
    """(status, payload) for GET /experiments/{name}/importance.

    Parameter importance from the jitted ARD GP surrogate (see
    metaopt_tpu.algo.gp_bo.ard_importance); shares the exact computation
    with `mtpu plot importance`.
    """
    from metaopt_tpu.algo.gp_bo import ard_importance

    inputs, err = _surrogate_inputs(ledger, name)
    if err is not None:
        return err
    cube, X, y = inputs
    imp = ard_importance(X, y)
    return 200, {"experiment": name, "trials": len(y),
                 "importance": dict(zip(cube.names, imp.tolist()))}


def pdp_series(ledger: LedgerBackend, name: str) -> Tuple[int, Any]:
    """(status, payload) for GET /experiments/{name}/pdp.

    1-D partial dependence of each parameter under the fitted ARD GP
    (metaopt_tpu.algo.gp_bo.partial_dependence — the lineage's
    ``plot partial_dependencies`` role); shared with `mtpu plot pdp`.
    Grid x-values are reported in each cube column's NATIVE scale
    (fidelity dims excluded, shaped dims one curve per element).
    """
    from metaopt_tpu.algo.gp_bo import partial_dependence

    inputs, err = _surrogate_inputs(ledger, name)
    if err is not None:
        return err
    cube, X, y = inputs
    grid, curves = partial_dependence(X, y)
    out = {}
    for j, pname in enumerate(cube.names):
        dim = cube.dims[j]
        xs = [cube._bwd_one(dim, float(g)) for g in grid]
        out[pname] = {"x": xs, "mean": curves[j].tolist()}
    return 200, {"experiment": name, "trials": len(y), "pdp": out}


def pareto_series(ledger: LedgerBackend, name: str) -> Tuple[int, Any]:
    """(status, payload) for GET /experiments/{name}/pareto.

    Nondominated front over the completed trials' objective VECTORS
    (multi-objective runs report several objective-typed results; see
    Trial.objectives). Shares the ranking computation with the motpe
    algorithm and `mtpu plot pareto`, so the three surfaces agree.
    """
    import numpy as np

    from metaopt_tpu.algo.motpe import nondominated_ranks

    every = [t for t in ledger.fetch(name, "completed") if t.objectives]
    if not every:
        return 400, {"error": f"{name!r} has no completed trials with "
                              "objectives"}
    # the vector length to rank in: the motpe config's n_objectives when
    # the experiment ran motpe (constructor default 2 when the key is
    # omitted — the algorithm truncates to it, so the surface must too),
    # else the MODAL reported length (ties → longer): one stray long- or
    # short-vector trial must never redefine the run's dimensionality.
    # Trials with fewer (or non-finite) objectives are then EXCLUDED,
    # exactly like motpe._observe_one — truncating everyone to the
    # shortest vector would instead drop points that are nondominated
    # only via the missing dimension.
    doc = ledger.load_experiment(name) or {}
    algo_cfg = doc.get("algorithm") or {}
    if "motpe" in algo_cfg:
        m = int((algo_cfg["motpe"] or {}).get("n_objectives", 2))
    else:
        lengths = [len(t.objectives) for t in every]
        m = max(set(lengths), key=lambda n: (lengths.count(n), n))
    if m < 2:
        return 400, {"error": f"{name!r} trials report a single objective; "
                              "the Pareto front needs at least two "
                              "(see client.report_results)"}
    done = [t for t in every
            if len(t.objectives) >= m
            and np.all(np.isfinite(t.objectives[:m]))]
    if not done:
        return 400, {"error": f"{name!r} has no completed trials with "
                              f"{m} finite objectives"}
    F = np.asarray([t.objectives[:m] for t in done], dtype=np.float64)
    ranks = nondominated_ranks(F)
    front = [
        {"id": done[i].id, "params": done[i].params,
         "objectives": F[i].tolist()}
        for i in np.where(ranks == 0)[0]
    ]
    front.sort(key=lambda r: r["objectives"])
    # dominated points ride along so renderers (the CLI scatter) get one
    # consistent snapshot instead of a second racy ledger read
    dominated = sorted(F[i].tolist() for i in np.where(ranks > 0)[0])
    return 200, {"experiment": name, "n_objectives": m,
                 "trials": len(done), "front": front,
                 "dominated": dominated}


def lcurve_series(ledger: LedgerBackend, name: str):
    """(fidelity_name, {lineage: [{budget, objective}...]}) or (None, {}).

    Shared by `mtpu plot lcurve` and GET /experiments/{name}/lcurves.
    """
    from metaopt_tpu.space import build_space

    doc = ledger.load_experiment(name)
    if doc is None or not doc.get("space"):
        return None, {}
    space = build_space(doc["space"])
    fid = space.fidelity
    if fid is None:
        return None, {}
    curves: Dict[str, List[Dict[str, Any]]] = {}
    for t in ledger.fetch(name, "completed"):
        if t.objective is None or fid.name not in t.params:
            continue
        lineage = t.lineage or space.hash_point(t.params)
        curves.setdefault(lineage, []).append(
            {"budget": int(t.params[fid.name]), "objective": t.objective}
        )
    for pts in curves.values():
        pts.sort(key=lambda p: p["budget"])
    return fid.name, curves


#: Self-contained HTML dashboard (no external assets — works air-gapped).
#: One accessible hue for the single regret series (the title names it, so
#: no legend); text in ink colors, recessive grid; the trials table is the
#: table view; per-point tooltips via SVG <title>.
_DASHBOARD = """<!doctype html>
<html><head><meta charset="utf-8"><title>metaopt-tpu</title><style>
:root { --ink:#1f2430; --muted:#667085; --grid:#e4e7ec; --accent:#2458c5;
        --bg:#ffffff; --row:#f6f7f9; }
@media (prefers-color-scheme: dark) {
  :root { --ink:#e6e9ef; --muted:#98a2b3; --grid:#363c47; --accent:#7da2e8;
          --bg:#15181e; --row:#1d2129; } }
body { font: 14px/1.5 system-ui, sans-serif; color: var(--ink);
       background: var(--bg); margin: 2rem; }
h1 { font-size: 1.2rem; } h2 { font-size: 1rem; }
table { border-collapse: collapse; min-width: 40rem; }
th, td { text-align: left; padding: .35rem .8rem; }
th { color: var(--muted); font-weight: 500;
     border-bottom: 1px solid var(--grid); }
tbody tr:nth-child(even) { background: var(--row); }
tbody tr { cursor: pointer; }
.done { color: var(--accent); font-weight: 600; }
svg text { fill: var(--muted); font-size: 11px; }
.end-label { fill: var(--ink); font-weight: 600; }
#status { color: var(--muted); margin-left: .6rem; font-size: .85rem; }
</style></head><body>
<h1>metaopt-tpu experiments<span id="status"></span></h1>
<table id="exps"><thead><tr><th>name</th><th>algo</th><th>trials</th>
<th>completed</th><th>max</th><th>state</th></tr></thead>
<tbody></tbody></table>
<h2 id="chart-title" hidden></h2>
<div id="chart"></div>
<h2 id="pareto-title" hidden></h2>
<div id="pareto"></div>
<h2 id="workers-title" hidden></h2>
<table id="workers" hidden><thead><tr><th>worker</th><th>completed</th>
<th>broken</th><th>holds</th><th>last seen</th></tr></thead>
<tbody></tbody></table>
<script>
const W=640, H=220, PAD=42;
async function j(u){ const r=await fetch(u); return r.json(); }
// experiment names are user-controlled strings headed into innerHTML —
// escape or a hostile `-n` becomes stored XSS for anyone watching
function esc(s){ return String(s).replace(/[&<>"']/g,
  c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c])); }
function fmt(v){ return Math.abs(v)>=100?v.toFixed(0)
                 : Math.abs(v)>=1?v.toFixed(2):v.toPrecision(3); }
function drawRegret(name, series){
  // best-objective-so-far vs trial index: a 2px single-hue line on a
  // recessive grid; the heading names the series (no legend needed)
  document.getElementById('chart-title').hidden=false;
  document.getElementById('chart-title').textContent=
    name+' — best objective so far';
  if(!series.length){
    document.getElementById('chart').textContent='no completed trials yet';
    return;}
  const ys=series.map(p=>p[1]), xs=series.map(p=>p[0]);
  const ymin=Math.min(...ys), ymax=Math.max(...ys);
  const yr=(ymax-ymin)||1, xr=(xs[xs.length-1]-xs[0])||1;
  const X=i=>PAD+(i-xs[0])/xr*(W-2*PAD), Y=v=>H-PAD-(v-ymin)/yr*(H-2*PAD);
  const pts=series.map(p=>X(p[0])+','+Y(p[1])).join(' ');
  let g='';
  for(const t of [ymin, ymin+yr/2, ymax]){
    g+=`<line x1="${PAD}" y1="${Y(t)}" x2="${W-PAD}" y2="${Y(t)}"
         stroke="var(--grid)" stroke-width="1"/>
        <text x="4" y="${Y(t)+4}">${fmt(t)}</text>`;}
  const dots=series.map(p=>
    `<circle cx="${X(p[0])}" cy="${Y(p[1])}" r="8" fill="transparent">
       <title>trial ${p[0]}: ${fmt(p[1])}</title></circle>`).join('');
  const last=series[series.length-1];
  document.getElementById('chart').innerHTML=
   `<svg width="${W}" height="${H}" role="img"
         aria-label="regret curve for ${esc(name)}">
      ${g}
      <polyline points="${pts}" fill="none" stroke="var(--accent)"
                stroke-width="2" stroke-linejoin="round"/>
      <circle cx="${X(last[0])}" cy="${Y(last[1])}" r="3"
              fill="var(--accent)"/>
      <text class="end-label" x="${Math.min(X(last[0])+6, W-38)}"
            y="${Y(last[1])-6}">${fmt(last[1])}</text>
      <text x="${PAD}" y="${H-6}">trial ${xs[0]}</text>
      <text x="${W-PAD-40}" y="${H-6}">trial ${last[0]}</text>
      ${dots}
    </svg>`;
}
let selected=null;
async function refresh(){
  try{
    const exps=await j('/experiments');
    const tb=document.querySelector('#exps tbody'); tb.innerHTML='';
    for(const e of exps){
      const tr=document.createElement('tr');
      tr.innerHTML=`<td>${esc(e.name)}</td><td>${esc(e.algorithm??'?')}</td>
        <td>${esc(e.trials)}</td><td>${esc(e.completed)}</td>
        <td>${esc(e.max_trials??'∞')}</td>
        <td class="${e.done?'done':''}">${e.done?'done':'running'}</td>`;
      tr.onclick=()=>{selected=e.name; show(e.name);};
      tb.appendChild(tr);
    }
    if(selected===null && exps.length){selected=exps[0].name; show(selected);}
    document.getElementById('status').textContent=
      'updated '+new Date().toLocaleTimeString();
  }catch(err){
    document.getElementById('status').textContent='unreachable: '+err;
  }
}
function drawPareto(name, front, dominated){
  // objective-1 vs objective-2 scatter: dominated points recessive,
  // front points in the accent hue joined by a step line
  const t=document.getElementById('pareto-title');
  t.hidden=false;
  t.textContent=name+' — pareto front ('+front.length+' nondominated)';
  const pts=front.map(r=>r.objectives).concat(dominated);
  const xs=pts.map(p=>p[0]), ys=pts.map(p=>p[1]);
  const xmin=Math.min(...xs), xmax=Math.max(...xs);
  const ymin=Math.min(...ys), ymax=Math.max(...ys);
  const xr=(xmax-xmin)||1, yr=(ymax-ymin)||1;
  const X=v=>PAD+(v-xmin)/xr*(W-2*PAD), Y=v=>H-PAD-(v-ymin)/yr*(H-2*PAD);
  let g='';
  for(const t2 of [ymin, ymax]){
    g+=`<line x1="${PAD}" y1="${Y(t2)}" x2="${W-PAD}" y2="${Y(t2)}"
         stroke="var(--grid)" stroke-width="1"/>
        <text x="4" y="${Y(t2)+4}">${fmt(t2)}</text>`;}
  const dots=dominated.map(p=>
    `<circle cx="${X(p[0])}" cy="${Y(p[1])}" r="2.5"
       fill="var(--muted)" opacity="0.45"><title>${fmt(p[0])}, ${fmt(p[1])}
       </title></circle>`).join('');
  const fsorted=front.map(r=>r.objectives)
    .slice().sort((a,b)=>a[0]-b[0]);
  const fline=fsorted.map(p=>X(p[0])+','+Y(p[1])).join(' ');
  const fdots=front.map(r=>
    `<circle cx="${X(r.objectives[0])}" cy="${Y(r.objectives[1])}" r="3.5"
       fill="var(--accent)"><title>${esc(JSON.stringify(r.params))} →
       ${r.objectives.map(fmt).join(', ')}</title></circle>`).join('');
  document.getElementById('pareto').innerHTML=
   `<svg width="${W}" height="${H}" role="img"
         aria-label="pareto front for ${esc(name)}">
      ${g}
      <polyline points="${fline}" fill="none" stroke="var(--accent)"
                stroke-width="1.5" stroke-dasharray="4 3"/>
      ${dots}${fdots}
      <text x="${PAD}" y="${H-6}">obj1 ${fmt(xmin)}</text>
      <text x="${W-PAD-52}" y="${H-6}">${fmt(xmax)}</text>
    </svg>`;
}
async function show(name){
  const r=await j('/experiments/'+encodeURIComponent(name)+'/regret');
  if(name!==selected) return;  // a newer click superseded this fetch
  drawRegret(name, (r.regret||[]).map(d=>[d.trial, d.best]));
  drawWorkers(name);
  // multi-objective runs additionally get the front scatter; a 400 from
  // a single-objective run just hides the section
  try{
    const p=await j('/experiments/'+encodeURIComponent(name)+'/pareto');
    if(name!==selected) return;  // stale response: don't draw A under B
    if(p.front){ drawPareto(name, p.front, p.dominated||[]); return; }
  }catch(e){}
  if(name!==selected) return;
  document.getElementById('pareto-title').hidden=true;
  document.getElementById('pareto').innerHTML='';
}
async function drawWorkers(name){
  // per-worker liveness (the status --workers table): reserved holders
  // with a fresh heartbeat read as live; long-silent rows read as gone
  try{
    const rows=await j('/experiments/'+encodeURIComponent(name)+'/workers');
    if(name!==selected) return;
    const title=document.getElementById('workers-title');
    const table=document.getElementById('workers');
    if(!rows.length){ title.hidden=true; table.hidden=true; return; }
    title.hidden=false; table.hidden=false;
    title.textContent=name+' — workers ('+rows.length+')';
    const tb=table.querySelector('tbody'); tb.innerHTML='';
    for(const w of rows){
      const age=w.last_seen_age_s;
      const seen=age==null?'never':
        age<120?fmt(age)+'s ago':fmt(age/60)+'m ago';
      const tr=document.createElement('tr');
      tr.innerHTML=`<td>${esc(w.worker)}</td><td>${esc(w.completed)}</td>
        <td>${esc(w.broken)}</td>
        <td>${esc((w.current||[]).map(t=>t.slice(0,8)).join(' ')||'—')}</td>
        <td>${esc(seen)}</td>`;
      tb.appendChild(tr);
    }
  }catch(e){
    // a failed fetch must not leave the PREVIOUS experiment's rows
    // mislabeled under the new selection
    document.getElementById('workers-title').hidden=true;
    document.getElementById('workers').hidden=true;
  }
}
refresh(); setInterval(refresh, 5000);
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    ledger: LedgerBackend  # set by make_server on the class

    def log_message(self, fmt, *args):  # route through logging, not stderr
        log.debug("webapi: " + fmt, *args)

    def _send(self, code: int, payload: Any) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_html(self, body: str) -> None:
        data = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        try:
            url = urlparse(self.path)
            parts = [p for p in url.path.split("/") if p]
            if parts == ["dashboard"]:
                self._send_html(_DASHBOARD)
                return
            query = parse_qs(url.query)
            code, payload = self._route(parts, query)
        except Exception as err:  # a bad request must not kill the thread
            log.exception("webapi error for %s", self.path)
            code, payload = 500, {"error": str(err)}
        self._send(code, payload)

    def _route(self, parts: List[str], query) -> Tuple[int, Any]:
        ledger = self.ledger
        if not parts:
            return 200, {"routes": [
                "/dashboard",
                "/experiments", "/experiments/{name}",
                "/experiments/{name}/trials", "/experiments/{name}/regret",
                "/experiments/{name}/lcurves",
                "/experiments/{name}/parallel",
                "/experiments/{name}/importance",
                "/experiments/{name}/pareto",
                "/experiments/{name}/pdp",
                "/experiments/{name}/workers", "/healthz",
            ]}
        if parts == ["healthz"]:
            return 200, {"ok": True}
        if parts[0] != "experiments" or len(parts) > 3:
            return 404, {"error": f"unknown route /{'/'.join(parts)}"}
        if len(parts) == 1:
            return 200, [
                _experiment_summary(ledger, n)
                for n in sorted(ledger.list_experiments())
            ]
        name = parts[1]
        if ledger.load_experiment(name) is None:
            return 404, {"error": f"no such experiment: {name}"}
        if len(parts) == 2:
            return 200, _experiment_detail(ledger, name)
        if parts[2] == "trials":
            status = (query.get("status") or [None])[0]
            if status is not None and status not in STATUSES:
                return 400, {"error": f"status must be one of {STATUSES}"}
            return 200, [t.to_dict() for t in ledger.fetch(name, status)]
        if parts[2] == "regret":
            return 200, {"experiment": name,
                         "regret": regret_series(ledger, name)}
        if parts[2] == "lcurves":
            fid_name, curves = lcurve_series(ledger, name)
            if fid_name is None:
                return 400, {"error": f"{name!r} has no fidelity dimension"}
            return 200, {"experiment": name, "fidelity": fid_name,
                         "lcurves": curves}
        if parts[2] == "parallel":
            dims, rows = parallel_series(ledger, name)
            return 200, {"experiment": name, "dimensions": dims,
                         "trials": rows}
        if parts[2] == "importance":
            return importance_series(ledger, name)
        if parts[2] == "pareto":
            return pareto_series(ledger, name)
        if parts[2] == "workers":
            return 200, worker_table(ledger, name)
        if parts[2] == "pdp":
            return pdp_series(ledger, name)
        return 404, {"error": f"unknown route /{'/'.join(parts)}"}


def make_server(
    ledger: LedgerBackend, host: str = "127.0.0.1", port: int = 0
) -> ThreadingHTTPServer:
    """A bound (not yet serving) HTTP server; port 0 picks an ephemeral one."""
    handler = type("BoundHandler", (_Handler,), {"ledger": ledger})
    return ThreadingHTTPServer((host, port), handler)


def serve_forever(server: ThreadingHTTPServer) -> None:
    host, port = server.server_address[:2]
    print(f"webapi listening on http://{host}:{port}", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()


def start_in_thread(server: ThreadingHTTPServer) -> threading.Thread:
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return t
