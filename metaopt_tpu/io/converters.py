"""Read/write user-script config files so priors can live in config templates.

ref: src/metaopt/core/io/converters.py — the lineage supports YAML/JSON (and a
generic fallback) so that ``~prior`` expressions can be written inside the
user's own config file; the Consumer rewrites that file with concrete values
for each trial.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

import yaml


class Converter:
    """File-format adapter: parse to a (possibly nested) dict and dump back."""

    extensions: tuple[str, ...] = ()

    def parse(self, path: str) -> Dict[str, Any]:
        raise NotImplementedError

    def generate(self, path: str, data: Dict[str, Any]) -> None:
        raise NotImplementedError


class JSONConverter(Converter):
    extensions = (".json",)

    def parse(self, path: str) -> Dict[str, Any]:
        with open(path) as f:
            return json.load(f)

    def generate(self, path: str, data: Dict[str, Any]) -> None:
        with open(path, "w") as f:
            json.dump(data, f, indent=2)


class YAMLConverter(Converter):
    extensions = (".yml", ".yaml")

    def parse(self, path: str) -> Dict[str, Any]:
        with open(path) as f:
            return yaml.safe_load(f) or {}

    def generate(self, path: str, data: Dict[str, Any]) -> None:
        with open(path, "w") as f:
            yaml.safe_dump(data, f, default_flow_style=False)


def infer_converter(path: str) -> Converter:
    ext = os.path.splitext(path)[1].lower()
    for cls in (JSONConverter, YAMLConverter):
        if ext in cls.extensions:
            return cls()
    # default to YAML, the lineage's lingua franca
    return YAMLConverter()
