"""Config IO: file converters and layered config resolution.

ref: src/metaopt/core/io/ (resolve_config.py, converters.py).
"""

from metaopt_tpu.io.converters import Converter, JSONConverter, YAMLConverter, infer_converter
from metaopt_tpu.io.resolve_config import resolve_config

__all__ = [
    "Converter",
    "JSONConverter",
    "YAMLConverter",
    "infer_converter",
    "resolve_config",
]
