"""The Python-API flow: ``build_experiment(...).workon(fn)``.

ref: the lineage's client API (``orion.client.build_experiment`` →
``ExperimentClient`` with ``workon(fn)`` and the manual
``suggest()``/``observe()`` loop) — the library-first UX next to the
``hunt`` CLI. Re-based onto this framework's machinery: the client wraps
a ledger-backed :class:`~metaopt_tpu.ledger.experiment.Experiment`, runs
``workon`` with the in-process executor, and shares the Producer
(observe → suggest → dedup → register) with the CLI path, so both UIs
exercise identical coordination code.

>>> from metaopt_tpu.client import build_experiment
>>> exp = build_experiment(
...     "demo", space={"x": "uniform(-5, 5)"},
...     algorithm={"tpe": {"seed": 1}}, max_trials=40)
>>> exp.workon(lambda params: (params["x"] - 1) ** 2)
>>> exp.best.objective  # doctest: +SKIP

The manual loop (remote/irregular evaluation — e.g. the measurement
happens outside this process):

>>> trial = exp.suggest()
>>> exp.observe(trial, 0.42)
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from metaopt_tpu.ledger.backends import LedgerBackend, ledger_from_spec
from metaopt_tpu.ledger.experiment import Experiment
from metaopt_tpu.ledger.trial import Trial


class WaitingForTrials(RuntimeError):
    """suggest(): nothing reservable right now, but the search isn't done.

    Other workers hold the in-flight trials, or the algorithm is at a
    barrier (sync rungs / a generation cohort waiting on stragglers).
    Retry after those complete — or pass ``block=True``.
    """


class CompletedExperiment(RuntimeError):
    """suggest() on an experiment that is already done."""


class ExperimentClient:
    """Library handle over one experiment: run, steer, inspect."""

    def __init__(self, experiment: Experiment, worker_id: str = "api-0"):
        self._exp = experiment
        self._worker = worker_id
        self._producer = None  # built lazily; shares one algorithm fit

    # -- the one-call flow -------------------------------------------------
    def workon(self, fn, max_trials: Optional[int] = None, **kw):
        """Evaluate ``fn(params)`` until the experiment is done.

        ``fn`` may return a scalar objective or a full results list (the
        ``report_results`` schema — several objective entries for
        multi-objective searches). Extra ``**kw`` pass through to
        :func:`metaopt_tpu.worker.workon` (``worker_trials``,
        ``max_broken``, ``producer_mode``, ...).
        """
        from metaopt_tpu.executor import InProcessExecutor
        from metaopt_tpu.worker import workon as _workon

        if max_trials is not None:
            kw.setdefault("worker_trials", max_trials)
        return _workon(self._exp, InProcessExecutor(fn),
                       worker_id=self._worker, **kw)

    # -- the manual loop ---------------------------------------------------
    def _ensure_producer(self):
        if self._producer is None:
            from metaopt_tpu.algo import make_algorithm
            from metaopt_tpu.worker.producer import Producer

            algo = make_algorithm(self._exp.space, self._exp.algorithm)
            self._producer = Producer(self._exp, algo)
        return self._producer

    def suggest(self, block: bool = False, timeout_s: float = 60.0,
                poll_s: float = 0.25,
                heartbeat_timeout_s: float = 60.0) -> Trial:
        """Reserve the next trial to evaluate (producing when needed).

        Raises :class:`CompletedExperiment` when the search is done and
        :class:`WaitingForTrials` when everything runnable is in flight
        elsewhere (unless ``block=True``, which polls up to
        ``timeout_s``). Each attempt also re-frees reservations whose
        heartbeat lapsed past ``heartbeat_timeout_s`` — the pacemaker
        sweep the worker loop runs every cycle; without it an API-only
        deployment would never recover a crashed client's trial.
        """
        deadline = time.time() + timeout_s
        while True:
            if self._exp.is_done:
                raise CompletedExperiment(
                    f"experiment {self._exp.name!r} is done"
                )
            self._exp.ledger.release_stale(self._exp.name,
                                           heartbeat_timeout_s)
            self._ensure_producer().produce()
            trial = self._exp.reserve_trial(self._worker)
            if trial is not None:
                return trial
            if not block:
                raise WaitingForTrials(
                    f"experiment {self._exp.name!r}: nothing reservable "
                    "(in-flight trials elsewhere or an algorithm barrier)"
                )
            if time.time() >= deadline:
                raise WaitingForTrials(
                    f"experiment {self._exp.name!r}: still nothing "
                    f"reservable after {timeout_s:.0f}s"
                )
            time.sleep(poll_s)

    def observe(
        self,
        trial: Trial,
        results: Union[float, int, Sequence[Dict[str, Any]]],
    ) -> None:
        """Complete a suggested trial with its measurement.

        ``results``: a scalar objective, or the ``report_results``-schema
        list (which may carry several objective entries, constraints,
        gradients, statistics). The schema's at-least-one-objective rule
        is enforced here too — an objective-less "completion" would
        silently burn max_trials budget while every algorithm skips it.

        Raises RuntimeError if the reservation was lost meanwhile (e.g.
        the evaluation outlived the heartbeat timeout and a pacemaker
        re-freed the trial) — the measurement did NOT reach the ledger.
        """
        if isinstance(results, (int, float)):
            results = [{"name": "objective", "type": "objective",
                        "value": float(results)}]
        results = [dict(r) for r in results]
        if not any(r.get("type") == "objective" for r in results):
            raise ValueError(
                "observe() needs at least one objective-typed result "
                f"(got types {[r.get('type') for r in results]})"
            )
        if not self._exp.push_results(trial, results):
            raise RuntimeError(
                f"trial {trial.id}: reservation lost before results "
                "landed (evaluation outlived the heartbeat timeout?) — "
                "the measurement was NOT recorded"
            )

    def release(self, trial: Trial, status: str = "new") -> None:
        """Give back a suggested trial without results.

        Default ``status="new"`` RE-QUEUES it (any worker can reserve it
        again — same mechanics as the stale-reservation pacemaker);
        ``"interrupted"``/``"broken"`` abandon it permanently instead.
        """
        if status == "new":
            trial.status = "new"  # reserved→new bypasses the lifecycle
            trial.worker = None   # table by design, like release_stale
            trial.start_time = None
            trial.heartbeat = None
            self._exp.ledger.update_trial(
                trial, expected_status="reserved",
                expected_worker=self._worker,
            )
            return
        trial.transition(status)
        self._exp.ledger.update_trial(
            trial, expected_status="reserved", expected_worker=self._worker
        )

    # -- inspection --------------------------------------------------------
    @property
    def name(self) -> str:
        return self._exp.name

    @property
    def space(self):
        return self._exp.space

    @property
    def is_done(self) -> bool:
        return self._exp.is_done

    @property
    def experiment(self) -> Experiment:
        """The underlying ledger-backed experiment (full API)."""
        return self._exp

    @property
    def stats(self) -> Dict[str, Any]:
        return self._exp.stats

    @property
    def best(self) -> Optional[Trial]:
        """The completed trial with the lowest (first) objective."""
        done = [t for t in self._exp.fetch_completed_trials()
                if t.objective is not None]
        return min(done, key=lambda t: t.objective) if done else None

    def fetch_trials(self, status: Optional[str] = None) -> List[Trial]:
        return self._exp.ledger.fetch(self._exp.name, status)

    def to_pandas(self, with_evc_tree: bool = False):
        """The experiment's trials as a DataFrame (lineage ``to_pandas``).

        One row per trial: id, status, timing, worker, the objective, and
        the params flattened into ``params.<name>`` columns. With
        ``with_evc_tree`` the frame also includes every ancestor/child
        version's trials (a ``experiment`` column disambiguates), walking
        ``branch_parent`` links both ways the way the lineage's EVC
        fetches do.
        """
        try:
            import pandas as pd
        except ImportError as err:  # declared in the [pandas]/[test] extras
            raise ImportError(
                "ExperimentClient.to_pandas needs pandas "
                "(pip install metaopt-tpu[pandas])"
            ) from err

        from metaopt_tpu.ledger.evc import branch_parent

        ledger = self._exp.ledger
        names = [self._exp.name]
        if with_evc_tree:
            doc = ledger.load_experiment(self._exp.name) or {}
            seen = {self._exp.name}
            parent = branch_parent(doc)
            while parent and parent not in seen:  # ancestors
                seen.add(parent)
                names.insert(0, parent)
                pdoc = ledger.load_experiment(parent) or {}
                parent = branch_parent(pdoc)
            # descendants: parent -> children map first, then expand to a
            # fixpoint — a single sorted pass would drop a grandchild
            # listed before its parent (e.g. fam-v10 < fam-v2)
            children: Dict[str, List[str]] = {}
            for other in ledger.list_experiments():
                if other in seen:
                    continue
                odoc = ledger.load_experiment(other) or {}
                p = branch_parent(odoc)
                if p:
                    children.setdefault(p, []).append(other)
            frontier = list(names)
            while frontier:
                kids = [
                    c for p in frontier for c in sorted(children.get(p, []))
                    if c not in seen
                ]
                seen.update(kids)
                names.extend(kids)
                frontier = kids
        rows = []
        for name in names:
            for t in ledger.fetch(name):
                row = {
                    "experiment": name,
                    "id": t.id,
                    "status": t.status,
                    "worker": t.worker,
                    "submit_time": t.submit_time,
                    "start_time": t.start_time,
                    "end_time": t.end_time,
                    "objective": t.objective,
                }
                for k, v in t.params.items():
                    row[f"params.{k}"] = v
                rows.append(row)
        base_cols = ["experiment", "id", "status", "worker", "submit_time",
                     "start_time", "end_time", "objective"]
        if not rows:  # keep the documented schema even when empty
            return pd.DataFrame(columns=base_cols)
        return pd.DataFrame(rows)

    def pareto_front(self) -> List[Tuple[Dict[str, Any], List[float]]]:
        """Nondominated ``(params, objective_vector)`` pairs (multi-
        objective experiments; ranking shared with motpe / plot pareto)."""
        import numpy as np

        from metaopt_tpu.algo.motpe import nondominated_ranks

        done = [t for t in self._exp.fetch_completed_trials()
                if len(t.objectives) >= 2
                and np.all(np.isfinite(t.objectives))]
        if not done:
            return []
        m = min(len(t.objectives) for t in done)
        F = np.asarray([t.objectives[:m] for t in done])
        ranks = nondominated_ranks(F)
        return [(dict(done[i].params), F[i].tolist())
                for i in np.where(ranks == 0)[0]]


def build_experiment(
    name: str,
    space: Optional[Dict[str, str]] = None,
    algorithm: Optional[Dict[str, Any]] = None,
    max_trials: Optional[int] = None,
    ledger: Union[str, LedgerBackend] = "memory",
    pool_size: int = 1,
    worker_id: str = "api-0",
    **experiment_kw: Any,
) -> ExperimentClient:
    """Create-or-load an experiment and return its client handle.

    ``space`` maps names to ``~prior`` expressions (``{"x": "uniform(-5,
    5)"}``); ``algorithm`` is the one-key config (``{"tpe": {...}}``,
    default random); ``ledger`` is a backend instance or a spec string —
    ``"memory"``, a directory path, ``"native:<dir>"``,
    ``"coord://host:port"`` (the CLI's ``--ledger`` grammar). Re-calling
    with the same name on the same ledger ADOPTS the stored
    configuration, exactly like re-running ``hunt`` (resume semantics).
    """
    from metaopt_tpu.space import build_space

    backend = (ledger if isinstance(ledger, LedgerBackend)
               else ledger_from_spec(ledger))
    if max_trials is not None:  # None = keep Experiment's default / stored
        experiment_kw["max_trials"] = max_trials
    exp = Experiment(
        name,
        backend,
        space=build_space(space) if space else None,
        algorithm=algorithm,
        pool_size=pool_size,
        **experiment_kw,
    ).configure()
    return ExperimentClient(exp, worker_id=worker_id)
