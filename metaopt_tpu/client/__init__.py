"""The in-script client: the single touchpoint inside a user's training code.

ref: src/metaopt/client/__init__.py — ``report_results(list_of_dicts)`` writes
JSON to a results path injected by the trial executor (SURVEY.md §2.6: this
file handshake IS the worker↔trial protocol; no socket, no RPC). Preserved
verbatim, with the path injected via the ``METAOPT_TPU_RESULTS_PATH`` env var.

Additions for multi-fidelity runs: ``report_partial(objective, step)`` streams
intermediate objectives (appends JSON lines to a sidecar file) so the
coordinator's ``judge``/early-stop hook can prune running trials, and
``get_trial_info()`` exposes the trial's id/params/fidelity/assigned chips to
the script.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Mapping, Optional

RESULTS_PATH_ENV = "METAOPT_TPU_RESULTS_PATH"
TRIAL_INFO_ENV = "METAOPT_TPU_TRIAL_INFO"
STOP_PATH_ENV = "METAOPT_TPU_STOP_PATH"

IS_ORCHESTRATED = RESULTS_PATH_ENV in os.environ


class ReportError(RuntimeError):
    pass


def _results_path() -> str:
    path = os.environ.get(RESULTS_PATH_ENV)
    if not path:
        raise ReportError(
            f"{RESULTS_PATH_ENV} is not set — this process was not launched by "
            "a metaopt-tpu executor. Guard the call with "
            "`if metaopt_tpu.client.IS_ORCHESTRATED:` for standalone runs."
        )
    return path


def report_results(data: List[Mapping[str, Any]]) -> None:
    """Report final trial results. Each item:

    ``{"name": ..., "type": "objective" | "constraint" | "gradient" | "statistic",
       "value": ...}``

    At least one ``objective`` entry is required. The FIRST one is the
    scalar single-objective algorithms minimize (reference contract:
    exactly one); additional objective entries, in report order, form the
    objective vector consumed by multi-objective algorithms (``motpe``).
    """
    data = [dict(d) for d in data]
    n_obj = sum(1 for d in data if d.get("type") == "objective")
    if n_obj < 1:
        raise ReportError(
            f"report_results needs at least one objective entry, got {n_obj}"
        )
    for d in data:
        if not {"name", "type", "value"} <= set(d):
            raise ReportError(f"malformed result entry {d!r}")
    path = _results_path()
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f)
    # atomic, deliberately not durable: same-host IPC with the executor
    # that spawned us — if the HOST crashes the trial is re-run anyway,
    # so atomicity (never a torn read) is the whole contract here
    os.replace(tmp, path)  # mtpu: lint-ok MTP001 same-host IPC, atomicity-only


def report_objective(value: float, name: str = "objective") -> None:
    """Shorthand for the common single-scalar case."""
    report_results([{"name": name, "type": "objective", "value": float(value)}])


def stop_requested() -> bool:
    """Has the executor asked this trial to stop (judge pruned it)?

    The cooperative half of early stopping: the executor touches a stop
    sentinel, waits a grace period, then SIGTERMs. A script that polls
    this (or passes it as ``should_stop`` to
    :func:`metaopt_tpu.parallel.control.run_signaled` — which agrees the
    verdict over the trial's mesh so a gang-scheduled trial exits
    coherently) can report its partial results and exit cleanly instead
    of dying mid-step. Always False outside an orchestrated trial.
    """
    path = os.environ.get(STOP_PATH_ENV)
    return bool(path) and os.path.exists(path)


def report_partial(objective: float, step: int) -> None:
    """Stream an intermediate objective (for early stopping / rung judging).

    Appends a JSON line to ``<results path>.partial``; the executor polls it
    and feeds ``algo.judge()``.
    """
    path = _results_path() + ".partial"
    with open(path, "a") as f:
        f.write(json.dumps({"objective": float(objective), "step": int(step)}) + "\n")
        f.flush()


def get_trial_info() -> Optional[Dict[str, Any]]:
    """Trial id / params / fidelity / assigned chips, or None standalone."""
    raw = os.environ.get(TRIAL_INFO_ENV)
    return json.loads(raw) if raw else None


CKPT_ROOT_ENV = "METAOPT_TPU_CKPT_ROOT"


def checkpoint_paths(root: Optional[str] = None):
    """(own_dir, parent_dir_or_None) for PBT-style weight handoff.

    PBT continuations carry the donor trial's id in ``Trial.parent``; a
    script that saves its weights under ``own_dir`` every step and restores
    from ``parent_dir`` when present inherits the exploited member's
    training state exactly as the algorithm intends. ``root`` defaults to
    ``$METAOPT_TPU_CKPT_ROOT`` (injected via ``hunt --ckpt-root``), else a
    per-experiment directory under the system temp dir. ``parent_dir`` is
    None when there is no parent or its checkpoint never materialized
    (broken donor) — scripts must treat that as cold start.

    Usage::

        own, parent = client.checkpoint_paths()
        if parent: restore(parent)
        ... train, save(own) ...
    """
    import tempfile

    info = get_trial_info() or {}
    root = root or os.environ.get(CKPT_ROOT_ENV) or os.path.join(
        tempfile.gettempdir(), "metaopt_tpu_ckpt",
        str(info.get("experiment") or "standalone"),
    )
    own = os.path.join(root, str(info.get("id", os.getpid())))
    os.makedirs(own, exist_ok=True)
    parent = info.get("parent")
    parent_dir = os.path.join(root, str(parent)) if parent else None
    if parent_dir is not None:
        # an existing-but-EMPTY dir means the donor called us too and then
        # died before saving anything — that's a cold start, not a restore
        try:
            if not os.listdir(parent_dir):
                parent_dir = None
        except OSError:
            parent_dir = None
    return own, parent_dir


PROFILE_DIR_ENV = "METAOPT_TPU_PROFILE_DIR"


class profiled:
    """Context manager: capture a ``jax.profiler`` trace of this trial.

    No-op unless the executor injected ``METAOPT_TPU_PROFILE_DIR`` (set
    ``profile_dir=`` on the executor / ``--profile-dir`` on the CLI). Traces
    land in ``<profile_dir>/<trial_id>/`` for TensorBoard's profile plugin —
    the per-trial on-chip observability SURVEY.md §5 calls for.

    Usage inside a user script::

        with client.profiled():
            for step in range(n):
                train_step(...)
    """

    def __init__(self) -> None:
        base = os.environ.get(PROFILE_DIR_ENV)
        self._dir: Optional[str] = None
        if base:
            info = get_trial_info() or {}
            self._dir = os.path.join(base, str(info.get("id", os.getpid())))

    def __enter__(self) -> "profiled":
        if self._dir:
            import jax

            os.makedirs(self._dir, exist_ok=True)
            jax.profiler.start_trace(self._dir)
        return self

    def __exit__(self, *exc) -> None:
        if self._dir:
            import jax

            jax.profiler.stop_trace()


#: the library-first flow (ref: the lineage's client API —
#: build_experiment(...).workon(fn) / suggest() / observe()). Lazy (PEP
#: 562): every trial subprocess imports this package for report_results,
#: and must not pay the ledger/algo import chain.
_LAZY_API = ("build_experiment", "ExperimentClient", "WaitingForTrials",
             "CompletedExperiment")


def __getattr__(name):
    if name in _LAZY_API:
        from metaopt_tpu.client import api

        return getattr(api, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "report_results",
    "report_objective",
    "report_partial",
    "stop_requested",
    "STOP_PATH_ENV",
    "get_trial_info",
    "checkpoint_paths",
    "profiled",
    "IS_ORCHESTRATED",
    "RESULTS_PATH_ENV",
    "TRIAL_INFO_ENV",
    "PROFILE_DIR_ENV",
    "CKPT_ROOT_ENV",
    "ReportError",
    *_LAZY_API,
]
