"""Mesh / sharding helpers for trial workloads.

The reference has no parallelism layer (SURVEY.md §2.8 — the user script owns
all model sharding); what the TPU build owes instead is *sub-slice* support:
a trial is handed an ICI-contiguous block of chips (``MTPU_ASSIGNED_CHIPS``)
and whatever model runs inside shards over exactly those chips with plain
``jax.sharding``. These helpers are that contract:

- :func:`trial_devices` — the JAX devices this trial may touch,
- :func:`make_mesh` — dp/tp (or custom) meshes over those devices,
- :func:`shard_batch` / :func:`replicate` — canonical data/param placement,
- :func:`logical_axis_rules` style param specs for the demo model zoo.
"""

from metaopt_tpu.parallel.mesh import (
    make_mesh,
    trial_devices,
    trial_mesh,
)
from metaopt_tpu.parallel.sharding import (
    batch_spec,
    replicate,
    shard_batch,
    shard_params,
)
from metaopt_tpu.parallel.control import (
    pod_agree,
    run_signaled,
)

__all__ = [
    "trial_devices",
    "make_mesh",
    "trial_mesh",
    "shard_batch",
    "replicate",
    "batch_spec",
    "shard_params",
    "pod_agree",
    "run_signaled",
]
