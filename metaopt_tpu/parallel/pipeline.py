"""Pipeline parallelism: a GPipe schedule over a "pp" mesh axis.

Each device along the axis holds ONE stage's parameters (a pytree with a
leading stage dimension, sharded over "pp"). Microbatches flow stage to
stage over the ICI ring: every tick, each stage applies its function to
the activation it holds and passes the result one hop with
``lax.ppermute``. A batch of M microbatches through P stages takes
M + P − 1 ticks (the usual GPipe bubble); activations live one microbatch
per stage, so per-chip activation memory is O(microbatch), not O(batch).

Everything is ``lax.scan`` + ``ppermute`` + one final masked ``psum``, so
``jax.grad`` differentiates it into the reverse pipeline schedule
automatically — no bespoke backward.

ref: the reference framework has no parallelism layers at all (SURVEY.md
§2.8); this is TPU-native demo-zoo surface so trials can shard deep
stacks across gang-scheduled sub-slices.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from metaopt_tpu.ops.attention import shard_map_nocheck


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "pp",
    batch_axis: Optional[str] = "dp",
    n_microbatches: Optional[int] = None,
) -> jnp.ndarray:
    """y = stage_{P-1}(…stage_1(stage_0(x))) with stages sharded over pp.

    ``stage_params``: pytree whose leaves have a leading dimension of size
    P (one slice per stage), sharded over ``axis``. ``stage_fn(params_p,
    h) -> h`` must be shape-preserving (same activation shape in and out).
    ``x``: (B, ...) batch, optionally sharded over ``batch_axis``; the
    per-shard batch must be a multiple of ``n_microbatches`` (default P).
    Returns y shaped like x.
    """
    if axis not in mesh.shape:
        raise ValueError(f"mesh has no {axis!r} axis: {dict(mesh.shape)}")
    n_stages = mesh.shape[axis]
    leads = {jnp.shape(leaf)[0] if jnp.ndim(leaf) else None
             for leaf in jax.tree.leaves(stage_params)}
    if leads != {n_stages}:
        # a[0] below keeps exactly one stage per device; any other leading
        # dim would silently drop stages and return wrong numbers
        raise ValueError(
            f"stage_params leading dims {sorted(leads, key=str)} must all "
            f"equal the {axis} mesh size {n_stages} (None = scalar leaf)"
        )
    ab = batch_axis if (batch_axis and batch_axis in mesh.shape) else None
    m = n_microbatches or n_stages
    b_local = x.shape[0] // (mesh.shape[ab] if ab else 1)
    if b_local % m:
        raise ValueError(
            f"per-shard batch {b_local} must be a multiple of "
            f"n_microbatches {m}"
        )

    param_specs = jax.tree.map(lambda _: P(axis), stage_params)
    xs = P(ab, *([None] * (x.ndim - 1)))

    def local(params, x_loc):
        # params leaves: (1, ...) — this device's stage slice
        params_p = jax.tree.map(lambda a: a[0], params)
        p_idx = jax.lax.axis_index(axis)
        micro = x_loc.reshape(m, x_loc.shape[0] // m, *x_loc.shape[1:])
        ticks = m + n_stages - 1
        fwd = [(j, j + 1) for j in range(n_stages - 1)]  # no wraparound

        def tick(carry, t):
            held = carry  # activation this stage holds entering tick t
            # stage 0 feeds itself from the microbatch queue (zeros once
            # the queue is drained — those bubbles are masked out below)
            feed = jax.lax.dynamic_index_in_dim(
                micro, jnp.minimum(t, m - 1), keepdims=False
            ) * (t < m)
            inp = jnp.where(p_idx == 0, feed, held)
            out = stage_fn(params_p, inp)
            # hand the result one hop down the pipe; stage 0 receives
            # nothing (zeros), the last stage's send is its output
            nxt = jax.lax.ppermute(out, axis, fwd)
            return nxt, out

        h0 = jnp.zeros_like(micro[0])
        _, outs = jax.lax.scan(tick, h0, jnp.arange(ticks))
        # the last stage emitted microbatch (t - P + 1) at tick t: ticks
        # P-1 .. P-1+M-1 hold the M results, in order
        y_loc = jax.lax.dynamic_slice_in_dim(outs, n_stages - 1, m, axis=0)
        y_loc = y_loc.reshape(x_loc.shape)
        # only the last stage holds real outputs; broadcast them across
        # the pp axis so every shard returns the same (replicated) y
        y_loc = jnp.where(p_idx == n_stages - 1, y_loc, 0.0)
        return jax.lax.psum(y_loc, axis)

    wrapped = shard_map_nocheck(
        local, mesh, in_specs=(param_specs, xs), out_specs=xs
    )
    return wrapped(stage_params, x)
