"""Pipeline parallelism: GPipe and interleaved virtual-stage schedules.

Each device along the "pp" mesh axis holds ``virtual_stages`` stage slices
(a pytree with a leading logical-stage dimension of size P·V, laid out so
device d owns stages d, P+d, 2P+d, …). Microbatches flow stage to stage
over the ICI ring: every tick, each device applies ONE stage slice to the
activation it holds and hands the result one hop with ``lax.ppermute``
(wraparound ring — the hop from device P−1 back to device 0 carries the
activation into its next virtual round, and the timing works out to
single-tick hops with no buffering).

Schedules, for M microbatches over P devices:

- ``virtual_stages=1`` — plain GPipe: M + P − 1 ticks, bubble fraction
  (P−1)/(M+P−1).
- ``virtual_stages=V>1`` — the interleaved schedule (Megatron-style
  virtual pipeline): each tick does 1/V of a device's work, M·V + P − 1
  ticks total, bubble fraction **(P−1)/(M·V+P−1)** — strictly smaller
  than GPipe's at the same M. See :func:`bubble_fraction`.

Non-shape-preserving ends ride along: ``pre_fn`` (e.g. token embedding)
runs as part of logical stage 0 on each fed microbatch, ``post_fn`` (e.g.
the logits readout) on the last stage's collected outputs — so a real
embed → blocks → readout transformer maps onto the pipe even though its
end shapes differ from the trunk activations.

Everything is ``lax.scan`` + ``ppermute`` + one final masked ``psum``, so
``jax.grad`` differentiates it into the reverse pipeline schedule
automatically — no bespoke backward. (The backward therefore runs after
the full forward, GPipe-style: this buys the interleaved schedule's
bubble, not 1F1B's O(P) activation memory; activations are O(M·V) per
device as in any scan-VJP pipeline.)

ref: the reference framework has no parallelism layers at all (SURVEY.md
§2.8); this is TPU-native demo-zoo surface so trials can shard deep
stacks across gang-scheduled sub-slices.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from metaopt_tpu.ops.attention import shard_map_nocheck


def bubble_fraction(n_stages: int, n_microbatches: int,
                    virtual_stages: int = 1) -> float:
    """Idle fraction of the schedule: (P−1)/(M·V + P − 1)."""
    return (n_stages - 1) / (
        n_microbatches * virtual_stages + n_stages - 1
    )


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "pp",
    batch_axis: Optional[str] = "dp",
    n_microbatches: Optional[int] = None,
    virtual_stages: int = 1,
    pre_fn: Optional[Callable[[Any, jnp.ndarray], jnp.ndarray]] = None,
    pre_params: Any = None,
    post_fn: Optional[Callable[[Any, jnp.ndarray], jnp.ndarray]] = None,
    post_params: Any = None,
) -> jnp.ndarray:
    """y = post(stage_{PV-1}(…stage_0(pre(x)))) with stages sharded over pp.

    ``stage_params``: pytree whose leaves have a leading dimension of size
    P·``virtual_stages`` (one slice per logical stage, device d owning
    logical stages v·P+d), sharded over ``axis``. ``stage_fn(params_s, h)
    -> h`` must be shape-preserving on the trunk activation; ``pre_fn``
    /``post_fn`` map into/out of that shape at the pipe's ends (their
    params are replicated). ``x``: (B, ...) batch, optionally sharded over
    ``batch_axis``; the per-shard batch must be a multiple of
    ``n_microbatches`` (default P), and ``n_microbatches`` a multiple of P
    when ``virtual_stages > 1`` (the interleaved schedule feeds in groups
    of P). Returns y shaped like ``post_fn``'s output (or like x).
    """
    if axis not in mesh.shape:
        raise ValueError(f"mesh has no {axis!r} axis: {dict(mesh.shape)}")
    n_stages = mesh.shape[axis]
    v_stages = int(virtual_stages)
    n_logical = n_stages * v_stages
    leads = {jnp.shape(leaf)[0] if jnp.ndim(leaf) else None
             for leaf in jax.tree.leaves(stage_params)}
    if leads != {n_logical}:
        # reshaping below assumes exactly one slice per logical stage; any
        # other leading dim would silently drop stages and return wrong
        # numbers
        raise ValueError(
            f"stage_params leading dims {sorted(leads, key=str)} must all "
            f"equal {axis} mesh size × virtual_stages = {n_logical} "
            "(None = scalar leaf)"
        )
    ab = batch_axis if (batch_axis and batch_axis in mesh.shape) else None
    m = n_microbatches or n_stages
    if v_stages > 1 and m % n_stages:
        raise ValueError(
            f"interleaved schedule feeds microbatches in groups of "
            f"{n_stages}: n_microbatches {m} must be a multiple of {n_stages}"
        )
    b_local = x.shape[0] // (mesh.shape[ab] if ab else 1)
    if b_local % m:
        raise ValueError(
            f"per-shard batch {b_local} must be a multiple of "
            f"n_microbatches {m}"
        )

    # device d owns logical stages v·P + d: reshape (PV, ...) -> (V, P, ...)
    # and shard the SECOND axis over pp, so the local slice is (V, ...)
    stacked = jax.tree.map(
        lambda a: jnp.reshape(a, (v_stages, n_stages) + a.shape[1:]),
        stage_params,
    )
    param_specs = jax.tree.map(lambda _: P(None, axis), stacked)
    xs = P(ab, *([None] * (x.ndim - 1)))
    rep = jax.tree.map(lambda _: P(), (pre_params, post_params))

    ticks = m * v_stages + n_stages - 1
    ring = [(j, (j + 1) % n_stages) for j in range(n_stages)]
    # the last device finishes chunk (g, V−1, j) of microbatch g·P+j at
    # tick (P−1) + g·V·P + (V−1)·P + j — static schedule, so the gather
    # indices are host-side numpy
    g_idx = np.arange(m // n_stages if v_stages > 1 else m)
    if v_stages > 1:
        t_out = (n_stages - 1 + (v_stages - 1) * n_stages
                 + g_idx[:, None] * v_stages * n_stages
                 + np.arange(n_stages)[None, :]).reshape(-1)
    else:
        t_out = n_stages - 1 + np.arange(m)

    def local(params, x_loc, pre_p, post_p):
        # params leaves: (V, 1, ...) — this device's V stage slices
        params_v = jax.tree.map(lambda a: a[:, 0], params)
        p_idx = jax.lax.axis_index(axis)
        micro = x_loc.reshape(m, x_loc.shape[0] // m, *x_loc.shape[1:])

        def embed(mb):
            return pre_fn(pre_p, mb) if pre_fn is not None else mb

        h_shape = jax.eval_shape(embed, micro[0])

        def tick(carry, t):
            held = carry  # activation this device holds entering tick t
            # static interleaved schedule: device d works on chunk
            # (g, v, j) = microbatch g·P+j at virtual round v, where
            # t = d + g·V·P + v·P + j — invert per tick
            lt = jnp.clip(t - p_idx, 0, m * v_stages - 1)
            r = lt % (v_stages * n_stages)
            v = r // n_stages
            g = lt // (v_stages * n_stages)
            micro_idx = g * n_stages + (r % n_stages)
            feed = embed(jax.lax.dynamic_index_in_dim(
                micro, micro_idx, keepdims=False
            ))
            inp = jnp.where((p_idx == 0) & (v == 0), feed, held)
            p_v = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, v, keepdims=False
                ),
                params_v,
            )
            out = stage_fn(p_v, inp)
            # one hop down the wraparound ring: the (P−1)→0 edge carries
            # the chunk into its next virtual round, arriving exactly one
            # tick later — no buffering
            nxt = jax.lax.ppermute(out, axis, ring)
            return nxt, out

        h0 = jnp.zeros(h_shape.shape, h_shape.dtype)
        _, outs = jax.lax.scan(tick, h0, jnp.arange(ticks))
        y_loc = outs[jnp.asarray(t_out)]        # (M, mb, ...) in micro order
        y_loc = y_loc.reshape((x_loc.shape[0],) + y_loc.shape[2:])
        if post_fn is not None:
            y_loc = post_fn(post_p, y_loc)
        # only the last device holds real outputs; broadcast them across
        # the pp axis so every shard returns the same (replicated) y
        y_loc = jnp.where(p_idx == n_stages - 1, y_loc, 0.0)
        return jax.lax.psum(y_loc, axis)

    wrapped = shard_map_nocheck(
        local, mesh,
        in_specs=(param_specs, xs, rep[0], rep[1]),
        out_specs=xs,
    )
    return wrapped(stacked, x, pre_params, post_params)
