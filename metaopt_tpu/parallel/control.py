"""Pod-global control signals as mesh collectives (the ICI path).

ref: BASELINE.json north star — "ASHA/Hyperband rung bookkeeping
pod-global via ICI broadcast of promotions / early-stop signals". The
coordinator's control plane (``set_signal`` → heartbeat piggyback) is a
DCN channel: only a trial's host-0 process polls it. But a
gang-scheduled trial executing collectives over a multi-chip mesh cannot
act on that signal unilaterally — if one process leaves the step loop
while the others enter the next ``psum``, the pod hangs. This module
closes that loop the TPU way: the stop decision is agreed ON THE MESH
(one tiny all-reduce riding ICI within a slice, DCN across slices), so
every participating process leaves the loop at the same step.

Usage inside a distributed trial::

    from metaopt_tpu.parallel.control import run_signaled

    def should_stop():           # host 0 polls the coordinator; other
        ...                      # hosts just return False

    carry, steps, stopped = run_signaled(
        step, carry, mesh=mesh, should_stop=should_stop,
        max_steps=1000, check_every=50,
    )
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@functools.lru_cache(maxsize=8)
def _pod_reducer(mesh: Mesh):
    """(input sharding, jitted all-reduce) for a mesh — built ONCE.

    pod_agree sits on the trial's step loop (every ``check_every``
    steps); rebuilding the Mesh/shardings/jit wrapper per call would pay
    a fresh trace + dispatch-cache miss each time instead of the
    intended tiny all-reduce.
    """
    devs = mesh.devices.reshape(-1)
    flat = Mesh(devs, ("_pod",))
    sharding = NamedSharding(flat, P("_pod"))
    reduce = jax.jit(jnp.max, out_shardings=NamedSharding(flat, P()))
    return sharding, reduce


def pod_agree(mesh: Mesh, local_flag: bool) -> bool:
    """Global OR of a per-process flag over every device in ``mesh``.

    One 8-byte-per-device all-reduce: each process contributes its flag
    on its addressable devices; the jitted ``max`` reduces across the
    whole mesh (XLA inserts the cross-host collective) and the result is
    replicated, so every process reads the identical verdict. Safe to
    call under multi-controller SPMD — all processes MUST call it
    together (it is itself a collective program).
    """
    sharding, reduce = _pod_reducer(mesh)
    val = np.int32(1 if local_flag else 0)
    arr = jax.make_array_from_callback(
        (mesh.devices.size,), sharding,
        lambda idx: np.full((1,), val, np.int32),
    )
    out = reduce(arr)
    # fully replicated: the local shard holds the global verdict
    return bool(np.asarray(out.addressable_shards[0].data))


def run_signaled(
    step_fn: Callable[[Any], Any],
    carry: Any,
    *,
    mesh: Mesh,
    should_stop: Callable[[], bool],
    max_steps: int,
    check_every: int = 50,
) -> Tuple[Any, int, bool]:
    """Drive ``carry = step_fn(carry)`` with pod-coherent early stop.

    Every ``check_every`` steps, each process contributes
    ``should_stop()`` (host 0 typically polls the coordinator's signal
    channel; other hosts return False) and the pod takes the global OR
    via :func:`pod_agree` — so either every process keeps stepping or
    every process stops, at the same step count. Returns
    ``(carry, steps_run, stopped_early)``.
    """
    if check_every < 1:
        raise ValueError(f"check_every must be >= 1, got {check_every}")
    steps = 0
    while steps < max_steps:
        chunk = min(check_every, max_steps - steps)
        for _ in range(chunk):
            carry = step_fn(carry)
        steps += chunk
        if pod_agree(mesh, bool(should_stop())):
            return carry, steps, True
    return carry, steps, False
