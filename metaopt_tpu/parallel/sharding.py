"""Sharding placement helpers used by the demo model zoo."""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_spec(mesh: Mesh) -> P:
    """Batch-sharded over the dp axis (leading dim), replicated elsewhere."""
    return P("dp") if "dp" in mesh.axis_names else P()


def shard_batch(mesh: Mesh, batch: Any) -> Any:
    """Place a host batch pytree onto the mesh, batch dim over dp."""
    sharding = NamedSharding(mesh, batch_spec(mesh))
    return jax.device_put(batch, sharding)


def replicate(mesh: Mesh, tree: Any) -> Any:
    """Fully replicate a pytree (params/opt state for pure-dp demos)."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def shard_params(
    mesh: Mesh,
    params: Any,
    rule: Optional[Callable[[tuple, jax.Array], P]] = None,
) -> Any:
    """Place params by rule(path, leaf) → PartitionSpec; default replicate.

    Model files provide tp-aware rules (e.g. attention heads over "tp");
    anything the rule declines (returns None) is replicated.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    placed = []
    for path, leaf in flat:
        spec = rule(path, leaf) if rule else None
        placed.append(
            jax.device_put(leaf, NamedSharding(mesh, spec if spec is not None else P()))
        )
    return jax.tree_util.tree_unflatten(treedef, placed)
