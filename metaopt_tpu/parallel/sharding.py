"""Sharding placement helpers used by the demo model zoo."""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class MeshTolerantPartitioned(nn.Partitioned):
    """``nn.Partitioned`` that survives partial meshes and flattened inits.

    Model code annotates the FULL parallel surface (tp/ep/...), but a trial
    mesh may carve out only some axes — the stock box then raises
    "resource axis not found" instead of replicating. And flax's
    ``DenseGeneral.kernel_init_wrap`` calls the boxed init with a flattened
    rank-2 shape and unboxes it BEFORE reshaping back to the rank-3 kernel,
    which under an active mesh applies a rank-3 constraint to a rank-2
    value. Both paths are handled here: skip the constraint while value
    rank and names rank disagree, and prune axis names the active mesh
    does not have (those dims stay replicated, matching ``_prune_spec``
    in the jit-init path).
    """

    def unbox(self, apply_constraint=True):
        if jnp.ndim(self.value) != len(self.names):
            return self.value
        if apply_constraint:
            from metaopt_tpu.parallel.mesh import active_mesh

            mesh = active_mesh()
            if mesh is not None:
                axes = set(mesh.axis_names)
                pruned, changed = [], False
                for entry in self.names:
                    if entry is None:
                        pruned.append(None)
                    elif isinstance(entry, (tuple, list)):
                        kept = tuple(a for a in entry if a in axes)
                        pruned.append(kept if kept else None)
                        changed |= kept != tuple(entry)
                    elif entry not in axes:
                        pruned.append(None)
                        changed = True
                    else:
                        pruned.append(entry)
                if changed:
                    if not any(pruned):
                        return self.value
                    return jax.lax.with_sharding_constraint(
                        self.value, P(*pruned)
                    )
        return super().unbox(apply_constraint=apply_constraint)


def with_mesh_partitioning(init: Callable, names) -> Callable:
    """``nn.with_partitioning`` built on :class:`MeshTolerantPartitioned`."""

    def boxed_init(rng, shape, dtype=jnp.float32):
        return MeshTolerantPartitioned(init(rng, shape, dtype), tuple(names))

    return boxed_init


def batch_spec(mesh: Mesh) -> P:
    """Batch-sharded over the dp axis (leading dim), replicated elsewhere."""
    return P("dp") if "dp" in mesh.axis_names else P()


def pin_batch_layout(x: jax.Array) -> jax.Array:
    """Constrain a batch-DERIVED tensor to the canonical batch layout.

    Token tensors produced by shifts/concats (the decoder-input BOS shift,
    the LM next-token slice) leave GSPMD free to re-partition the embedding
    gather that consumes them. On composed tp×sp meshes the CPU backend
    routes that freedom into an unevenly padded reshard whose padding rows
    poison the lookup with NaN (uninitialized pad × zero mask → NaN under
    the gather-combine). Pinning the derived tensor to the same
    ``P("dp", None, ...)`` layout as the batch it came from removes the
    freedom — and costs nothing, since that is where the data already
    lives. No-op outside a concrete mesh.
    """
    from metaopt_tpu.parallel.mesh import active_mesh

    mesh = active_mesh()
    if isinstance(mesh, Mesh) and "dp" in mesh.axis_names:
        spec = P(*(["dp"] + [None] * (x.ndim - 1)))
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    return x


def shard_batch(mesh: Mesh, batch: Any) -> Any:
    """Place a host batch pytree onto the mesh, batch dim over dp."""
    sharding = NamedSharding(mesh, batch_spec(mesh))
    return jax.device_put(batch, sharding)


def replicate(mesh: Mesh, tree: Any) -> Any:
    """Fully replicate a pytree (params/opt state for pure-dp demos)."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def shard_params(
    mesh: Mesh,
    params: Any,
    rule: Optional[Callable[[tuple, jax.Array], P]] = None,
) -> Any:
    """Place params by rule(path, leaf) → PartitionSpec; default replicate.

    Model files provide tp-aware rules (e.g. attention heads over "tp");
    anything the rule declines (returns None) is replicated.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    placed = []
    for path, leaf in flat:
        spec = rule(path, leaf) if rule else None
        placed.append(
            jax.device_put(leaf, NamedSharding(mesh, spec if spec is not None else P()))
        )
    return jax.tree_util.tree_unflatten(treedef, placed)
