"""Device meshes over a trial's assigned sub-slice."""

from __future__ import annotations

import contextlib
import contextvars
import os
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

_ACTIVE_MESH: contextvars.ContextVar = contextvars.ContextVar(
    "mtpu_active_mesh", default=None
)


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Activate a mesh: legacy ``with mesh:`` semantics + model-layer access.

    The model layer (MHA's shard_map routing) reads the active mesh via
    :func:`active_mesh` rather than probing the deprecated
    ``jax.interpreters.pxla.thread_resources`` — this context is the
    supported registration point, and it works for both activation styles
    (the legacy context manager is entered here; new-style
    ``jax.sharding.use_mesh`` callers are caught by the abstract-mesh
    probe in :func:`active_mesh`).
    """
    token = _ACTIVE_MESH.set(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        _ACTIVE_MESH.reset(token)


def active_mesh() -> Optional[Mesh]:
    """The mesh the current trial runs under, or None outside any mesh."""
    mesh = _ACTIVE_MESH.get()
    if mesh is not None:
        return mesh
    get_abstract = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_abstract is not None:
        abstract = get_abstract()
        if abstract is not None and not abstract.empty:
            return abstract
    return None


def trial_devices() -> List[jax.Device]:
    """The devices this trial process may use.

    The TPU executor pins trials via ``MTPU_ASSIGNED_CHIPS`` (see
    executor/topology.py). When the runtime actually hides other chips
    (TPU_VISIBLE_CHIPS honored by the plugin) the id list matches
    ``jax.devices()`` directly; when it doesn't (CPU test meshes), the ids
    index into the visible device list — both cases resolve here.
    """
    devices = jax.devices()
    spec = os.environ.get("MTPU_ASSIGNED_CHIPS")
    if not spec:
        return list(devices)
    want = [int(s) for s in spec.split(",") if s != ""]
    if len(set(want)) != len(want):
        raise ValueError(f"MTPU_ASSIGNED_CHIPS={spec!r} repeats a chip id")
    by_id = {d.id: d for d in devices}
    if all(i in by_id for i in want):
        picked = [by_id[i] for i in want]
    elif all(i < len(devices) for i in want):
        # ids are slice-relative; index into the visible list
        picked = [devices[i] for i in want]
    elif len(want) == len(devices):
        # a pinned runtime honored TPU_VISIBLE_CHIPS and renumbered: the
        # assignment ids are global block ids, but the visible set IS
        # exactly the assignment — take it whole, each device once
        picked = list(devices)
    else:
        # never modulo-wrap: that would silently put the same device into
        # the mesh twice and corrupt every collective on it
        raise ValueError(
            f"MTPU_ASSIGNED_CHIPS={spec!r} matches no visible device id, "
            f"exceeds the visible index range, and its size differs from "
            f"the {len(devices)} visible devices — cannot map safely"
        )
    # a pinned runtime that already hides other chips needs no filtering
    return picked or list(devices)


def make_mesh(
    axes: Sequence[Tuple[str, int]],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """``make_mesh([("dp", 2), ("tp", 4)])`` → a 2×4 Mesh.

    Axis sizes must multiply to the device count; a size of -1 means "fill
    with whatever remains" (at most one axis).
    """
    devs = list(devices if devices is not None else trial_devices())
    names = [a for a, _ in axes]
    sizes = [int(s) for _, s in axes]
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    known = int(np.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if len(devs) % known:
            raise ValueError(
                f"{len(devs)} devices not divisible by fixed axes {known}"
            )
        sizes[sizes.index(-1)] = len(devs) // known
    if int(np.prod(sizes)) != len(devs):
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} needs {int(np.prod(sizes))} "
            f"devices, have {len(devs)}"
        )
    grid = np.asarray(devs, dtype=object).reshape(sizes)
    return Mesh(grid, tuple(names))


def trial_mesh(tp: int = 1, extra_axes: Sequence[Tuple[str, int]] = ()) -> Mesh:
    """The canonical trial mesh: data-parallel over the sub-slice, with an

    optional tensor-parallel inner axis — ``trial_mesh(tp=2)`` on a 4-chip
    sub-slice gives a ("dp", 2) × ("tp", 2) mesh. Demo-zoo models default to
    pure dp, matching SURVEY.md §2.8's "plain pjit data-parallel" scope.
    """
    axes = [("dp", -1), ("tp", tp), *extra_axes]
    return make_mesh(axes)
