"""``python -m metaopt_tpu`` entry point."""

import sys

from metaopt_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
