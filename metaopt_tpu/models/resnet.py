"""ResNet — BASELINE config 3 (ASHA on ResNet-50/CIFAR-10, 1 chip/trial).

Bottleneck-block ResNet in flax with the CIFAR stem (3×3, no max-pool).
Depth 50 by default; the ASHA fidelity axis is ``epochs``. bf16 conv/matmul
for the MXU, f32 batch-norm statistics, one jitted scan per epoch.
Searchable hparams in the BASELINE config: lr, momentum, weight_decay,
batch_size — see examples/resnet_cifar.py.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from metaopt_tpu.models.data import synthetic_images

#: layers-per-stage tables for the classic depths
STAGES = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3), 50: (3, 4, 6, 3),
          101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}
BOTTLENECK = {50, 101, 152}


class Bottleneck(nn.Module):
    filters: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, *, train: bool):
        conv = partial(nn.Conv, use_bias=False, dtype=jnp.bfloat16)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides))(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, *, train: bool):
        conv = partial(nn.Conv, use_bias=False, dtype=jnp.bfloat16)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            strides=(self.strides, self.strides))(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    depth: int = 50
    n_classes: int = 10
    width: int = 64

    @nn.compact
    def __call__(self, x, *, train: bool):
        block = Bottleneck if self.depth in BOTTLENECK else BasicBlock
        x = x.astype(jnp.bfloat16)
        # CIFAR stem: 3x3 stride 1 (no 7x7/maxpool — inputs are 32x32)
        x = nn.Conv(self.width, (3, 3), use_bias=False, dtype=jnp.bfloat16)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train,
                                 dtype=jnp.float32)(x))
        for i, n_blocks in enumerate(STAGES[self.depth]):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = block(self.width * (2 ** i), strides)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.n_classes, dtype=jnp.float32)(x)


def train_and_eval(
    hparams: Dict[str, Any],
    *,
    depth: int = 50,
    n_train: int = 4096,
    n_val: int = 1024,
    epochs: int = 1,
    seed: int = 0,
    hw: int = 32,
    on_epoch=None,
) -> float:
    """Train on synthetic CIFAR-shaped data; return validation error.

    ``on_epoch(epoch, val_error)`` fires after each epoch so multi-fidelity
    scripts can stream partials (client.report_partial) from ONE continuous
    training run — the fidelity axis continues training, it never restarts.
    """
    lr = float(hparams.get("lr", 0.1))
    momentum = float(hparams.get("momentum", 0.9))
    weight_decay = float(hparams.get("weight_decay", 1e-4))
    batch_size = int(hparams.get("batch_size", 128))

    model = ResNet(
        depth=int(hparams.get("depth", depth)),
        width=int(hparams.get("width", 64)),
    )
    key = jax.random.PRNGKey(seed)
    kd, kv, ki = jax.random.split(key, 3)
    x, y = synthetic_images(kd, n_train, hw=hw, channels=3)
    xv, yv = synthetic_images(kv, n_val, hw=hw, channels=3)

    variables = model.init(ki, x[:1], train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.chain(
        optax.add_decayed_weights(weight_decay),
        optax.sgd(lr, momentum=momentum, nesterov=True),
    )
    opt_state = tx.init(params)
    steps = max(1, n_train // batch_size)

    def loss_fn(p, bs, xb, yb):
        logits, new_model_state = model.apply(
            {"params": p, "batch_stats": bs}, xb, train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()
        return loss, new_model_state["batch_stats"]

    @jax.jit
    def epoch(carry, ekey):
        def step(c, _):
            p, bs, o, k = c
            k, sk = jax.random.split(k)
            idx = jax.random.permutation(sk, n_train)[:batch_size]
            (loss, bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                p, bs, x[idx], y[idx]
            )
            updates, o = tx.update(grads, o, p)
            p = optax.apply_updates(p, updates)
            return (p, bs, o, k), loss

        (p, bs, o, _), losses = jax.lax.scan(
            step, (*carry, ekey), jnp.arange(steps)
        )
        return (p, bs, o), losses.mean()

    @jax.jit
    def val_error(p, bs):
        logits = model.apply({"params": p, "batch_stats": bs}, xv, train=False)
        return 1.0 - jnp.mean(jnp.argmax(logits, -1) == yv)

    carry = (params, batch_stats, opt_state)
    err = 1.0
    for e in range(int(epochs)):
        carry, _ = epoch(carry, jax.random.fold_in(key, 1000 + e))
        if on_epoch is not None:
            err = float(val_error(carry[0], carry[1]))
            on_epoch(e + 1, err)
    if on_epoch is None:
        err = float(val_error(carry[0], carry[1]))
    return err


def make_objective(**fixed):
    def objective(params: Dict[str, Any]) -> float:
        kw = dict(fixed)
        if "epochs" in params:
            kw["epochs"] = int(params["epochs"])  # ASHA fidelity
        return train_and_eval(params, **kw)

    return objective
