"""Per-trial model checkpointing for the demo zoo (orbax-backed).

ref: SURVEY.md §5 checkpoint/resume — "per-trial model checkpoints stay the
user script's business (orbax in our demo models)". The ledger checkpoints
the SEARCH; this module checkpoints a TRIAL's training state so that

- a suspended/preempted trial resumes mid-run (``mtpu resume``), and
- a PBT continuation inherits its parent's weights
  (``client.checkpoint_paths``).

Trees are flattened to index-keyed arrays before saving: orbax round-trips
nested dicts natively, but optimizer states are namedtuple pytrees whose
field iteration order need not match a restored dict's key order —
index keys make the leaf order explicit and structure-independent.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_state(path: str, tree: Any) -> None:
    """Save any pytree of arrays under ``path`` (overwrites)."""
    leaves = jax.tree.leaves(tree)
    payload = {
        f"{i:05d}": np.asarray(jax.device_get(leaf))
        for i, leaf in enumerate(leaves)
    }
    _checkpointer().save(os.path.abspath(path), payload, force=True)


def restore_state(path: str, like: Any, shardings: Optional[Any] = None) -> Any:
    """Restore a pytree shaped like ``like``; re-shard when given.

    ``shardings``: a matching pytree of ``jax.sharding.Sharding`` (e.g. the
    specs ``init_sharded`` returns) — leaves are placed straight onto their
    mesh positions instead of landing replicated on device 0.
    """
    payload = _checkpointer().restore(os.path.abspath(path))
    leaves = [payload[k] for k in sorted(payload)]
    treedef = jax.tree.structure(like)
    if len(leaves) != treedef.num_leaves:
        raise ValueError(
            f"checkpoint at {path} has {len(leaves)} leaves, expected "
            f"{treedef.num_leaves} — saved from a different architecture?"
        )
    if shardings is not None:
        # zip flattened leaves rather than tree.map: the shardings tree
        # collapses each flax Partitioned box into ONE spec leaf, so its
        # STRUCTURE differs from the params tree even though the leaf
        # counts (one array per box) line up
        sharding_leaves = jax.tree.leaves(shardings)
        if len(sharding_leaves) == len(leaves):
            leaves = [
                jax.device_put(jnp.asarray(x), s)
                for x, s in zip(leaves, sharding_leaves)
            ]
        else:
            # a silent fallback here would land a multi-host restore fully
            # replicated on device 0 with no signal — make it loud
            log.warning(
                "restore_state(%s): shardings tree has %d leaves but the "
                "checkpoint has %d — IGNORING shardings, restoring "
                "unsharded (replicated on the default device)",
                path, len(sharding_leaves), len(leaves),
            )
            leaves = [jnp.asarray(x) for x in leaves]
    else:
        leaves = [jnp.asarray(x) for x in leaves]
    return jax.tree.unflatten(treedef, leaves)


def has_state(path: str) -> bool:
    return os.path.isdir(path) and bool(os.listdir(path))
