"""Transformer-base seq2seq — BASELINE config 4 (Hyperband/BOHB on WMT14,

4-chip sub-slice per trial). The zoo's flagship: encoder-decoder
Transformer-base (d_model 512, 8 heads, 6+6 layers, d_ff 2048) trained on the
synthetic translation-shaped task, sharded dp×tp over the trial's sub-slice
mesh:

- batch over ``dp``,
- attention heads and MLP hidden over ``tp`` (Megatron-style column/row
  split: qkv/wi kernels P(None, "tp"), out/wo kernels P("tp", None)) so the
  per-layer collective is one psum riding ICI,
- everything bf16 on the MXU with f32 layernorm/softmax accumulation.

__graft_entry__.entry() compile-checks the forward; dryrun_multichip() jits
the FULL train step over an n-device dp×tp mesh.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metaopt_tpu.models.data import synthetic_seq2seq
from metaopt_tpu.parallel.sharding import shard_batch, with_mesh_partitioning


def _pinit(partitioned: bool, axes):
    """Megatron partitioning metadata, or a plain init.

    ``partitioned=False`` exists for trunks that run INSIDE another
    shard_map (the pipeline stages): flax's ``Partitioned.unbox`` applies
    a sharding constraint whenever any mesh is active, and a "tp" spec
    inside a pp x dp manual mesh is an error, not a no-op.
    """
    init = nn.initializers.lecun_normal()
    return with_mesh_partitioning(init, axes) if partitioned else init


class MHA(nn.Module):
    d_model: int
    n_heads: int
    dropout: float = 0.0  # attention-weight dropout (Transformer-base: 0.1)
    partitioned: bool = True

    @nn.compact
    def __call__(self, q_in, kv_in, mask=None, *, train: bool = False):
        d_head = self.d_model // self.n_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (self.n_heads, d_head), axis=-1, dtype=jnp.bfloat16, name=name,
            kernel_init=_pinit(self.partitioned, (None, "tp", None)),
        )
        q = dense("q")(q_in) / np.sqrt(d_head)
        k = dense("k")(kv_in)
        v = dense("v")(kv_in)
        from metaopt_tpu.ops.attention import (
            _reference_attention,
            attention_impl,
            flash_attention,
            sharded_flash_attention,
        )
        from metaopt_tpu.parallel.mesh import active_mesh

        # masks here are (b, 1, q|1, k) with heads shared — flatten to the
        # kernel's (b, q, k) convention
        m3 = None
        if mask is not None:
            m3 = jnp.broadcast_to(
                mask[:, 0], (q.shape[0], q.shape[1], k.shape[1])
            )
        rate = self.dropout if train else 0.0
        key = self.make_rng("dropout") if rate > 0.0 else None
        out_proj = nn.DenseGeneral(
            self.d_model, axis=(-2, -1), dtype=jnp.bfloat16, name="out",
            kernel_init=_pinit(self.partitioned, ("tp", None, None)),
        )

        mesh = active_mesh()
        if mesh is not None and dict(mesh.shape).get("sp", 1) > 1:
            sp = mesh.shape["sp"]
            if q.shape[1] % sp or k.shape[1] % sp:
                # never silently fall back to sp-replicated attention: the
                # user asked for sequence sharding, and the fallback would
                # quietly pay the full O(S²) memory on every chip
                raise ValueError(
                    f"seq lengths (q={q.shape[1]}, kv={k.shape[1]}) must be "
                    f"multiples of the sp mesh axis ({sp}); pad the batch "
                    f"or drop sp from the trial mesh"
                )
            # sequence-parallel mesh: the long-context path. Default =
            # ring attention (K/V ride the ICI ring, lowest per-chip
            # memory); METAOPT_TPU_SP_IMPL=ulysses selects the all-to-all
            # head/sequence exchange instead (fewer collectives, needs
            # per-device heads % sp == 0)
            from metaopt_tpu.ops.ulysses import sp_impl, ulysses_attention

            if sp_impl() == "ulysses":
                return out_proj(ulysses_attention(
                    q, k, v, m3, mesh=mesh,
                    dropout_rate=rate, dropout_key=key,
                ))
            from metaopt_tpu.ops.ring_attention import ring_attention

            return out_proj(ring_attention(
                q, k, v, m3, mesh=mesh,
                dropout_rate=rate, dropout_key=key,
            ))

        impl = attention_impl()
        if impl == "pallas" and rate > 0.0:
            impl = "chunked"  # the Pallas forward carries no dropout RNG
        if impl is None:
            out = _reference_attention(q, k, v, m3, rate, key)
        else:
            if mesh is not None and getattr(mesh, "size", 1) > 1:
                # batch on dp, heads on tp: keeps the Megatron head split
                # local to each shard instead of GSPMD all-gathering q/k/v
                out = sharded_flash_attention(
                    mesh, q, k, v, m3,
                    dropout_rate=rate, dropout_key=key, impl=impl,
                )
            else:
                out = flash_attention(
                    q, k, v, m3,
                    dropout_rate=rate, dropout_key=key, impl=impl,
                )
        return out_proj(out)


class FeedForward(nn.Module):
    d_model: int
    d_ff: int
    dropout: float
    partitioned: bool = True

    @nn.compact
    def __call__(self, x, *, train: bool):
        wi = nn.Dense(
            self.d_ff, dtype=jnp.bfloat16, name="wi",
            kernel_init=_pinit(self.partitioned, (None, "tp")),
        )
        wo = nn.Dense(
            self.d_model, dtype=jnp.bfloat16, name="wo",
            kernel_init=_pinit(self.partitioned, ("tp", None)),
        )
        h = nn.relu(wi(x))
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return wo(h)


def _make_mlp(d_model, d_ff, dropout, n_experts, capacity_factor=1.25,
              partitioned=True, router_top_k=1):
    if n_experts > 0:
        from metaopt_tpu.models.moe import MoEFeedForward

        return MoEFeedForward(d_model, d_ff, n_experts, dropout,
                              capacity_factor, router_top_k, name="mlp")
    return FeedForward(d_model, d_ff, dropout, partitioned, name="mlp")


class EncoderLayer(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    dropout: float
    n_experts: int = 0
    capacity_factor: float = 1.25
    partitioned: bool = True
    router_top_k: int = 1

    @nn.compact
    def __call__(self, x, pad_mask, train: bool = False):
        ln = lambda n: nn.LayerNorm(dtype=jnp.float32, name=n)  # noqa: E731
        y = ln("ln1")(x)
        x = x + MHA(self.d_model, self.n_heads, self.dropout,
                    self.partitioned,
                    name="self_attn")(y, y, pad_mask, train=train)
        y = ln("ln2")(x)
        x = x + _make_mlp(self.d_model, self.d_ff, self.dropout,
                          self.n_experts, self.capacity_factor,
                          self.partitioned, self.router_top_k)(y, train=train)
        return x


class DecoderLayer(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    dropout: float
    n_experts: int = 0
    capacity_factor: float = 1.25
    partitioned: bool = True
    router_top_k: int = 1

    @nn.compact
    def __call__(self, x, enc, causal_mask, cross_mask, train: bool = False):
        ln = lambda n: nn.LayerNorm(dtype=jnp.float32, name=n)  # noqa: E731
        y = ln("ln1")(x)
        x = x + MHA(self.d_model, self.n_heads, self.dropout,
                    self.partitioned,
                    name="self_attn")(y, y, causal_mask, train=train)
        y = ln("ln2")(x)
        x = x + MHA(self.d_model, self.n_heads, self.dropout,
                    self.partitioned,
                    name="cross_attn")(y, enc, cross_mask, train=train)
        y = ln("ln3")(x)
        x = x + _make_mlp(self.d_model, self.d_ff, self.dropout,
                          self.n_experts, self.capacity_factor,
                          self.partitioned, self.router_top_k)(y, train=train)
        return x


class Transformer(nn.Module):
    """Encoder-decoder; Transformer-base defaults."""

    vocab: int = 1000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    dropout: float = 0.1
    max_len: int = 512
    #: >0 turns every FFN into a top-1-routed MoE with this many experts
    #: (weights sharded over the "ep" mesh axis when present)
    n_experts: int = 0
    #: per-expert queue = capacity_factor*T/E tokens; <=0 = dense dispatch
    capacity_factor: float = 1.25
    #: experts per token: 1 = Switch, 2 = GShard-style top-2
    router_top_k: int = 1
    #: rematerialize each layer in the backward pass: activation memory
    #: drops from O(layers) to O(1) layers, buying batch size (and with it
    #: MFU) at ~1/3 extra FLOPs — the standard TPU HBM trade
    remat: bool = False

    @nn.compact
    def __call__(self, src, tgt_in, *, train: bool, features: bool = False):
        emb = nn.Embed(
            self.vocab, self.d_model, dtype=jnp.bfloat16, name="embed",
            embedding_init=nn.with_partitioning(
                nn.initializers.normal(1.0), (None, None)
            ),
        )
        pos = self.param(
            "pos_embed",
            nn.with_partitioning(nn.initializers.normal(0.02), (None, None)),
            (self.max_len, self.d_model),
        )
        s_len, t_len = src.shape[1], tgt_in.shape[1]
        if max(s_len, t_len) > self.max_len:
            # shapes are static under jit, so this fires at trace time with
            # a readable message instead of a broadcast error deep in XLA
            raise ValueError(
                f"sequence length {max(s_len, t_len)} exceeds the positional "
                f"table (max_len={self.max_len}); pass max_len>=seq to "
                f"make_model"
            )
        src_pad = (src != 0)[:, None, None, :]                    # (b,1,1,k)
        causal = jnp.tril(jnp.ones((t_len, t_len), bool))[None, None]
        tgt_pad = (tgt_in != 0)[:, None, None, :]
        causal_mask = causal & tgt_pad
        cross_mask = src_pad

        # static_argnums pins `train` (python control flow inside);
        # counting includes self, so train sits at index 3 / 5
        enc_cls = (nn.remat(EncoderLayer, static_argnums=(3,))
                   if self.remat else EncoderLayer)
        dec_cls = (nn.remat(DecoderLayer, static_argnums=(5,))
                   if self.remat else DecoderLayer)
        x = emb(src) + pos[None, :s_len].astype(jnp.bfloat16)
        for i in range(self.n_layers):
            x = enc_cls(self.d_model, self.n_heads, self.d_ff,
                        self.dropout, self.n_experts,
                        self.capacity_factor, True, self.router_top_k,
                        name=f"enc{i}")(x, src_pad, train)
        enc = nn.LayerNorm(dtype=jnp.float32, name="enc_ln")(x).astype(jnp.bfloat16)

        y = emb(tgt_in) + pos[None, :t_len].astype(jnp.bfloat16)
        for i in range(self.n_layers):
            y = dec_cls(self.d_model, self.n_heads, self.d_ff,
                        self.dropout, self.n_experts,
                        self.capacity_factor, True, self.router_top_k,
                        name=f"dec{i}")(
                y, enc, causal_mask, cross_mask, train
            )
        y = nn.LayerNorm(dtype=jnp.float32, name="dec_ln")(y)
        if features:
            # pre-readout features for the blocked-xent loss (ops/xent.py):
            # the caller folds the tied embedding table in blockwise and
            # the (B, T, V) logits tensor never exists
            return y
        # weight-tied readout against the (bf16) embedding table
        logits = jnp.einsum(
            "btd,vd->btv", y.astype(jnp.bfloat16), emb.embedding
        )
        return logits.astype(jnp.float32)


# ---------------------------------------------------------------------------


def make_model(hparams: Optional[Dict[str, Any]] = None, **overrides) -> Transformer:
    h = dict(hparams or {})
    h.update(overrides)
    return Transformer(
        vocab=int(h.get("vocab", 1000)),
        d_model=int(h.get("d_model", 512)),
        n_heads=int(h.get("n_heads", 8)),
        n_layers=int(h.get("n_layers", 6)),
        d_ff=int(h.get("d_ff", 2048)),
        dropout=float(h.get("dropout", 0.1)),
        max_len=int(h.get("max_len", 512)),
        n_experts=int(h.get("n_experts", 0)),
        capacity_factor=float(h.get("capacity_factor", 1.25)),
        router_top_k=int(h.get("router_top_k", 1)),
        remat=bool(h.get("remat", False)),
    )


#: materialized f32 (B, T, V) logits size above which loss_fn switches to
#: the blocked xent. Below it the plain optax path is simpler AND faster:
#: measured on the v5e (bench 2026-08-01, vocab 32000) the 2.1 GB flagship
#: tensor fits HBM comfortably and materializing beats blocked 58.5 vs
#: 65.3 ms/step at seq256 (parity at seq512) — the blocked path only pays
#: for itself once the tensor genuinely threatens HBM capacity
_BLOCKED_XENT_MIN_LOGITS_BYTES = 4 << 30


def blocked_xent_enabled(
    batch: int, seq: int, vocab: int, shards: Optional[int] = None,
) -> bool:
    """True when :func:`loss_fn` folds the readout into the blocked xent.

    Gates on the PER-DEVICE materialized f32 logits size: on a parallel
    mesh the batch dims are sharded over dp/sp, so HBM pressure is
    ``global_bytes / batch_shards``, not the global tensor. bench.py labels
    its records with this same predicate — keep them in sync by calling it,
    not copying it.

    Routing: ``shards`` is the number of ways the (B, T) batch dims are
    split. With the default ``shards=None`` the predicate reads the
    ambient mesh (``active_mesh()``): inside a ``with mesh:`` scope it
    divides by ``dp * sp``; outside any mesh it treats the tensor as
    unsharded. Callers deciding routing FOR a mesh they have not entered
    yet (launchers, planners, bench labeling a future run) pass the shard
    count explicitly — the ambient lookup would silently read whatever
    mesh the caller happens to be inside, or none.
    """
    if shards is None:
        from metaopt_tpu.parallel.mesh import active_mesh

        shards = 1
        mesh = active_mesh()
        if mesh is not None:
            shape = dict(mesh.shape)
            shards = shape.get("dp", 1) * shape.get("sp", 1)
    per_device = 4 * batch * seq * vocab // max(shards, 1)
    return per_device >= _BLOCKED_XENT_MIN_LOGITS_BYTES


def readout_xent(out, params, labels, vocab, blocked):
    """Per-token xent from the model output against the tied embedding.

    ``out`` is pre-readout features when ``blocked`` (the f32 (B, T, V)
    logits tensor never exists in HBM — ops/xent.py folds the tied readout
    into a blocked online-softmax), else full logits. Shared by the
    seq2seq loss below and the decoder-only LM (models/lm.py), so the
    routing measured on the bench applies to both families.
    """
    if blocked:
        from metaopt_tpu.ops.xent import blocked_softmax_xent, pick_block_v

        emb = params["embed"]["embedding"]
        if hasattr(emb, "unbox"):  # nn.Partitioned leaf (sharded init path)
            emb = emb.unbox()
        feats = out.reshape(-1, out.shape[-1]).astype(jnp.bfloat16)
        return blocked_softmax_xent(
            feats, emb.astype(jnp.bfloat16), labels.reshape(-1),
            pick_block_v(vocab),
        ).reshape(labels.shape)
    return optax.softmax_cross_entropy_with_integer_labels(out, labels)


def masked_mean_with_aux(loss, mask, mutated, moe_aux_weight):
    """Masked token-mean plus the MoE switch load-balancing term."""
    total = (loss * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    aux = jax.tree.leaves(mutated.get("aux_loss", {}))
    if aux:
        total = total + moe_aux_weight * sum(jnp.sum(a) for a in aux)
    return total


def loss_fn(model, params, batch, dropout_key, moe_aux_weight: float = 0.01):
    from metaopt_tpu.parallel.sharding import pin_batch_layout

    src, tgt = batch
    bos = jnp.ones((tgt.shape[0], 1), tgt.dtype)
    tgt_in = pin_batch_layout(
        jnp.concatenate([bos, tgt[:, :-1]], axis=1))
    blocked = blocked_xent_enabled(tgt.shape[0], tgt.shape[1], model.vocab)
    out, mutated = model.apply(
        {"params": params}, src, tgt_in, train=True, features=blocked,
        rngs={"dropout": dropout_key},
        mutable=["aux_loss"],
    )
    mask = (tgt != 0).astype(jnp.float32)
    loss = readout_xent(out, params, tgt, model.vocab, blocked)
    return masked_mean_with_aux(loss, mask, mutated, moe_aux_weight)


def make_train_step(model, tx):
    """The jittable train step (donated params/opt state)."""

    def train_step(params, opt_state, batch, step_key):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(model, p, batch, step_key)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def init_sharded(
    model: Transformer, mesh: Mesh, tx, batch_shape: Tuple[int, int], seed: int = 0
):
    """Initialize params/opt state already laid out on the mesh.

    flax's ``nn.with_partitioning`` annotations (tp axes above) flow into
    jax.eval_shape → NamedSharding here, so big kernels materialize directly
    sharded — no host-resident full copy.
    """
    b, s = batch_shape
    src = jnp.zeros((b, s), jnp.int32)

    def init_fn(key):
        params = model.init(key, src, src, train=False)["params"]
        return params, tx.init(params)

    return sharded_init(init_fn, mesh, seed)


def _prune_spec(spec, mesh):
    """Drop partition-axis names the mesh doesn't have (→ replicated).

    Model code annotates the FULL parallel surface (tp/ep/...); a
    trial mesh that only carves out some axes still initializes — the
    un-carved axes just stay unsharded.
    """
    if not isinstance(spec, P):
        return spec
    cleaned = []
    for entry in spec:
        if entry is None:
            cleaned.append(None)
        elif isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in mesh.axis_names)
            cleaned.append(kept if kept else None)
        else:
            cleaned.append(entry if entry in mesh.axis_names else None)
    return P(*cleaned)


def sharded_init(init_fn, mesh: Mesh, seed: int = 0):
    """Run ``init_fn(key)`` with outputs materialized directly sharded.

    Shared by the seq2seq ``init_sharded`` above and the decoder-only LM
    (models/lm.py): partition annotations flow through jax.eval_shape →
    NamedSharding, so big kernels never exist host-resident/replicated.
    """
    key = jax.random.PRNGKey(seed)
    shapes = jax.eval_shape(init_fn, key)
    specs = nn.get_partition_spec(shapes)
    shardings = jax.tree.map(
        lambda sp: NamedSharding(mesh, _prune_spec(sp, mesh)), specs)
    out = jax.jit(init_fn, out_shardings=shardings)(key)
    return (*out, shardings)


def trial_setup(hparams: Dict[str, Any], mesh: Optional[Mesh],
                tp: int, sp: int, ep: int, steps: int):
    """The shared trial-harness preamble: mesh assembly + optimizer.

    sp > 1 shards the sequence axis (ring attention over ICI); ep > 1
    carves an expert axis for MoE FFNs (n_experts hparam). Used by both
    zoo training harnesses (seq2seq below, decoder-only LM in lm.py) so
    mesh/scheduler behavior cannot drift between families.
    """
    from metaopt_tpu.parallel.mesh import trial_mesh

    extra = []
    if sp > 1:
        extra.append(("sp", sp))
    if ep > 1:
        extra.append(("ep", ep))
    mesh = mesh or trial_mesh(tp=tp, extra_axes=tuple(extra))
    lr = float(hparams.get("lr", 1e-3))
    warmup = int(hparams.get("warmup", 10))
    sched = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup, max(steps, warmup + 1))
    tx = optax.adamw(sched,
                     weight_decay=float(hparams.get("weight_decay", 0.0)))
    return mesh, tx


def maybe_restore(restore_dir: Optional[str], params, opt_state, shardings):
    """Orbax trial-checkpoint restore (no-op when dir is empty/absent).

    How a PBT continuation inherits its parent's training state and a
    suspended trial resumes (models/checkpoint.py).
    """
    if restore_dir:
        from metaopt_tpu.models.checkpoint import has_state, restore_state

        if has_state(restore_dir):
            params = restore_state(restore_dir + "/params", params,
                                   shardings[0])
            opt_state = restore_state(restore_dir + "/opt_state",
                                      opt_state, shardings[1])
    return params, opt_state


def train_and_eval(
    hparams: Dict[str, Any],
    *,
    mesh: Optional[Mesh] = None,
    tp: int = 1,
    sp: int = 1,
    ep: int = 1,
    n_train: int = 2048,
    batch_size: int = 32,
    seq_len: int = 64,
    steps: int = 100,
    seed: int = 0,
    restore_dir: Optional[str] = None,
    save_dir: Optional[str] = None,
) -> float:
    """Train on the synthetic translation task; return final masked loss.

    ``restore_dir``/``save_dir``: orbax trial checkpoints (params +
    optimizer state) — how a PBT continuation inherits its parent's
    training state and a suspended trial resumes (models/checkpoint.py).
    """
    from metaopt_tpu.parallel.mesh import use_mesh

    if n_train < batch_size:
        raise ValueError(
            f"n_train ({n_train}) must be >= batch_size ({batch_size})")
    mesh, tx = trial_setup(hparams, mesh, tp, sp, ep, steps)
    model = make_model(hparams)

    key = jax.random.PRNGKey(seed)
    kd, kstep = jax.random.split(key)
    src, tgt = synthetic_seq2seq(kd, n_train, seq_len, model.vocab)

    with use_mesh(mesh):
        params, opt_state, shardings = init_sharded(
            model, mesh, tx, (batch_size, seq_len), seed
        )
        params, opt_state = maybe_restore(
            restore_dir, params, opt_state, shardings)
        step_fn = jax.jit(
            make_train_step(model, tx),
            in_shardings=(
                shardings[0], shardings[1],
                NamedSharding(mesh, P("dp")), None,
            ),
            out_shardings=(shardings[0], shardings[1], None),
            donate_argnums=(0, 1),
        )
        loss = None
        for i in range(steps):
            lo = (i * batch_size) % (n_train - batch_size + 1)
            sl = slice(lo, lo + batch_size)
            batch = shard_batch(mesh, (src[sl], tgt[sl]))
            params, opt_state, loss = step_fn(
                params, opt_state, batch, jax.random.fold_in(kstep, i)
            )
    if save_dir:
        from metaopt_tpu.models.checkpoint import save_state

        save_state(save_dir + "/params", params)
        save_state(save_dir + "/opt_state", opt_state)
    return float(loss)


def make_objective(**fixed):
    def objective(params: Dict[str, Any]) -> float:
        kw = dict(fixed)
        if "epochs" in params:  # fidelity axis maps to train steps
            kw["steps"] = int(params["epochs"]) * kw.get("steps_per_epoch", 50)
            kw.pop("steps_per_epoch", None)
        return train_and_eval(params, **kw)

    return objective
