"""A transformer LM assembled as pipeline stages (pp×dp demo-zoo surface).

The stage trunk is the REAL ``EncoderLayer`` from the demo Transformer —
self-attention + FFN with the same bf16/f32 mixed precision — stacked
P·V deep with one parameter slice per logical stage (the scan-over-layers
layout: every layer shares a structure, so one ``jax.vmap`` over init
keys builds the stacked pytree). ``pipeline_apply`` runs them under the
interleaved virtual-stage schedule with the token embedding as ``pre_fn``
and the vocab readout as ``post_fn`` — the full embed → blocks → logits
stack mapped onto a pp×dp mesh.

Run it OUTSIDE ``use_mesh``: the pipeline's ``shard_map`` owns the mesh,
and the layer's MHA must take its single-device path inside each shard
(an active mesh would make it try to nest another shard_map).

ref: the reference framework has no model code (SURVEY.md §2.8) — this is
TPU-native demo-zoo surface for pipeline-parallel trials.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from metaopt_tpu.models.transformer import EncoderLayer
from metaopt_tpu.parallel.pipeline import pipeline_apply


def make_pipeline_lm(
    hparams: Dict[str, Any], n_stages: int, virtual_stages: int = 2,
    seq: int = 16, seed: int = 0,
) -> Tuple[Any, Any]:
    """(stage_fn, pre/post fns, params) for a P·V-layer pipeline LM.

    Returns ``(fns, params)`` where ``fns = (stage_fn, pre_fn, post_fn)``
    and ``params = (stage_params, pre_params, post_params)`` —
    ``stage_params`` leaves lead with the logical-stage dimension P·V.
    """
    d = int(hparams.get("d_model", 32))
    n_heads = int(hparams.get("n_heads", 2))
    d_ff = int(hparams.get("d_ff", 64))
    vocab = int(hparams.get("vocab", 101))
    n_layers = n_stages * virtual_stages

    # partitioned=False: the pipeline shard_map owns the mesh; a tp spec
    # inside it would be rejected, not pruned
    layer = EncoderLayer(d, n_heads, d_ff, dropout=0.0, partitioned=False)
    key = jax.random.PRNGKey(seed)
    k_emb, k_pos, k_ro, k_layers = jax.random.split(key, 4)
    h_sample = jnp.zeros((1, seq, d), jnp.float32)

    def init_one(k):
        from flax import linen as nn

        # unbox the tp-partitioning metadata: stage params shard over the
        # LOGICAL-STAGE axis here (pp), not over a tp mesh axis
        return nn.meta.unbox(layer.init(k, h_sample, None, False)["params"])

    stage_params = jax.vmap(init_one)(jax.random.split(k_layers, n_layers))
    pre_params = {
        "emb": jax.random.normal(k_emb, (vocab, d)) * (1.0 / np.sqrt(d)),
        "pos": jax.random.normal(k_pos, (seq, d)) * 0.02,
    }
    post_params = {"ro": jax.random.normal(k_ro, (d, vocab)) / np.sqrt(d)}

    def pre_fn(p, toks):  # (mb, S) int32 -> (mb, S, d)
        return p["emb"][toks] + p["pos"][None, : toks.shape[1]]

    def stage_fn(p, h):
        # train pinned False (dropout 0 here); mask None = full attention
        return layer.apply({"params": p}, h, None, False)

    def post_fn(p, h):  # (mb, S, d) -> (mb, S, vocab)
        return h.astype(jnp.float32) @ p["ro"]

    return (stage_fn, pre_fn, post_fn), (stage_params, pre_params, post_params)


def reference_forward(fns, params, toks) -> jnp.ndarray:
    """The same stack applied sequentially — the numerics oracle."""
    stage_fn, pre_fn, post_fn = fns
    stage_params, pre_params, post_params = params
    h = pre_fn(pre_params, toks)
    n = jax.tree.leaves(stage_params)[0].shape[0]
    for i in range(n):
        h = stage_fn(jax.tree.map(lambda a: a[i], stage_params), h)
    return post_fn(post_params, h)


def make_pp_train_step(fns, mesh, *, n_microbatches, virtual_stages):
    """Jittable (loss, grads) over the pipeline: next-token cross-entropy."""
    stage_fn, pre_fn, post_fn = fns

    def train_step(params, toks):
        stage_params, pre_params, post_params = params

        def loss_fn(stage_params, pre_params, post_params):
            logits = pipeline_apply(
                stage_fn, stage_params, toks, mesh=mesh,
                n_microbatches=n_microbatches,
                virtual_stages=virtual_stages,
                pre_fn=pre_fn, pre_params=pre_params,
                post_fn=post_fn, post_params=post_params,
            )
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(
                    logits[:, :-1], toks[:, 1:]
                )
            )

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
            stage_params, pre_params, post_params
        )
        return loss, grads

    return train_step
