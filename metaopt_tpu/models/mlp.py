"""MLP classifier — BASELINE config 2 (TPE on MLP/MNIST, 4 hparams).

Searchable hparams: ``lr`` (loguniform), ``width`` (discrete), ``depth``
(discrete), ``dropout`` (uniform) — the config's "4 hparams". Single chip;
bf16 matmuls on the MXU; one jit-compiled epoch step via lax.scan so the
whole trial is a handful of XLA programs regardless of epoch count.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from metaopt_tpu.models.data import synthetic_images


def _mxu_dtype():
    # bf16 matmuls pay off on the MXU; on CPU they are emulated — slower
    # and noisier than f32.
    return jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32


class MLP(nn.Module):
    width: int
    depth: int
    dropout: float
    n_classes: int = 10

    @nn.compact
    def __call__(self, x, *, train: bool):
        dtype = _mxu_dtype()
        x = x.reshape((x.shape[0], -1)).astype(dtype)
        for _ in range(self.depth):
            x = nn.Dense(self.width, dtype=dtype)(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.n_classes, dtype=jnp.float32)(x)


def train_and_eval(
    hparams: Dict[str, Any],
    *,
    n_train: int = 8192,
    n_val: int = 2048,
    batch_size: int = 256,
    epochs: int = 3,
    seed: int = 0,
) -> float:
    """Train on synthetic MNIST-shaped data; return validation error rate."""
    lr = float(hparams["lr"])
    model = MLP(
        width=int(hparams["width"]),
        depth=int(hparams["depth"]),
        dropout=float(hparams.get("dropout", 0.0)),
    )
    key = jax.random.PRNGKey(seed)
    kdata, kval, kinit, kdrop = jax.random.split(key, 4)
    x, y = synthetic_images(kdata, n_train)
    xv, yv = synthetic_images(kval, n_val)

    params = model.init(kinit, x[:1], train=False)
    tx = optax.adam(lr)
    opt_state = tx.init(params)

    steps = n_train // batch_size

    def loss_fn(p, xb, yb, dkey):
        logits = model.apply(p, xb, train=True, rngs={"dropout": dkey})
        return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

    @jax.jit
    def epoch(carry, ekey):
        # one permutation per epoch, partitioned into static-shape batches —
        # every sample is visited exactly once per epoch
        kperm, kstep = jax.random.split(ekey)
        idx = jax.random.permutation(kperm, n_train)[: steps * batch_size]
        idx = idx.reshape(steps, batch_size)

        def step(c, ib):
            p, o, k = c
            k, dk = jax.random.split(k)
            xb, yb = x[ib], y[ib]
            loss, grads = jax.value_and_grad(loss_fn)(p, xb, yb, dk)
            updates, o = tx.update(grads, o, p)
            p = optax.apply_updates(p, updates)
            return (p, o, k), loss

        (p, o, _), losses = jax.lax.scan(
            step, (carry[0], carry[1], kstep), idx
        )
        return (p, o), losses.mean()

    carry = (params, opt_state)
    for e in range(int(epochs)):
        carry, _ = epoch(carry, jax.random.fold_in(kdrop, e))
    params = carry[0]

    @jax.jit
    def val_error(p):
        logits = model.apply(p, xv, train=False)
        return 1.0 - jnp.mean(jnp.argmax(logits, -1) == yv)

    return float(val_error(params))


def make_objective(**fixed):
    """Objective for InProcessExecutor: params dict → validation error."""

    def objective(params: Dict[str, Any]) -> float:
        kw = dict(fixed)
        if "epochs" in params:
            kw["epochs"] = int(params["epochs"])  # fidelity axis
        return train_and_eval(params, **kw)

    return objective
