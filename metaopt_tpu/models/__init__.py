"""Demo model zoo for the BASELINE benchmark configs.

The reference ships no models — the user script is opaque (SURVEY.md §0).
These exist so the five BASELINE configs are runnable end-to-end on TPU:

- :mod:`objectives` — CPU-only closed-form objectives (Rosenbrock; config 1)
- :mod:`mlp`         — MLP/MNIST-shaped, 4 hparams, single chip (config 2)
- :mod:`resnet`      — ResNet-50/CIFAR-shaped, multi-fidelity (config 3)
- :mod:`transformer` — Transformer-base, 4-chip sub-slice pjit (config 4)
- :mod:`ppo`         — PPO actor-critic populations (config 5)
- :mod:`lm`          — decoder-only causal LM (the long-context flagship
  shape; reuses the seq2seq blocks, sp ring/Ulysses attention, and the
  measured blocked-xent routing)

All use synthetic data generated on device (zero-egress environment — no
dataset downloads), bfloat16 matmuls for the MXU, donated buffers, and
jit-compiled train steps; batches and shapes are static so XLA compiles one
program per trial. Each module exposes ``make_objective(**fixed)`` returning
a callable usable with InProcessExecutor, and the hunt-able scripts live in
examples/.
"""

from metaopt_tpu.models import objectives

__all__ = ["objectives"]
