"""Synthetic on-device datasets (zero-egress environment — no downloads).

Each generator is deterministic in (seed, shapes) and *learnable*: labels come
from a fixed random teacher, so validation loss responds to hyperparameters
the way a real dataset's would — which is what an HPO benchmark needs.
Data is generated directly on device with jax.random (no host→HBM copies).
"""

from __future__ import annotations

from typing import Iterator, Tuple

import jax
import jax.numpy as jnp


def synthetic_images(
    key: jax.Array,
    n: int,
    hw: int = 28,
    channels: int = 1,
    n_classes: int = 10,
    teacher_seed: int = 7,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """MNIST/CIFAR-shaped images with teacher-assigned labels.

    The teacher is keyed by ``teacher_seed``, NOT by ``key`` — train and
    validation draws with different sample keys share one labeling function,
    otherwise generalization would be unmeasurable.
    """
    x = jax.random.normal(key, (n, hw, hw, channels), dtype=jnp.float32)
    kt = jax.random.PRNGKey(teacher_seed)
    teacher = jax.random.normal(kt, (hw * hw * channels, n_classes)) / hw
    logits = x.reshape(n, -1) @ teacher
    y = jnp.argmax(logits, axis=-1)
    return x, y


def synthetic_seq2seq(
    key: jax.Array,
    n: int,
    seq_len: int = 64,
    vocab: int = 1000,
    teacher_seed: int = 7,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Copy-through-permutation task: target is the source mapped through a

    fixed random vocabulary permutation — translation-shaped (WMT stand-in)
    and learnable. The permutation is keyed by ``teacher_seed`` so separate
    train/val draws share one "language".
    """
    src = jax.random.randint(key, (n, seq_len), 2, vocab)  # 0=pad, 1=bos
    perm = jax.random.permutation(jax.random.PRNGKey(teacher_seed), vocab)
    tgt = perm[src]
    return src, tgt


def synthetic_lm(
    key: jax.Array,
    n: int,
    seq_len: int = 64,
    vocab: int = 1000,
    teacher_seed: int = 7,
) -> jnp.ndarray:
    """Permutation-walk token streams: ``x[t+1] = perm[x[t]]`` from a random
    start — next-token prediction is exactly learnable (a one-step Markov
    map over [2, vocab), so 0=pad / 1=bos never appear mid-stream). The
    permutation is keyed by ``teacher_seed`` so train/val draws share one
    "language"."""
    perm = 2 + jax.random.permutation(
        jax.random.PRNGKey(teacher_seed), vocab - 2)
    start = jax.random.randint(key, (n,), 2, vocab)

    def body(tok, _):
        nxt = perm[tok - 2]
        return nxt, nxt

    _, cols = jax.lax.scan(body, start, None, length=seq_len - 1)
    return jnp.concatenate([start[:, None], cols.T], axis=1)


def batches(
    x: jnp.ndarray, y: jnp.ndarray, batch_size: int, key: jax.Array
) -> Iterator[Tuple[jnp.ndarray, jnp.ndarray]]:
    """Shuffled epoch of static-shaped batches (drop remainder)."""
    n = x.shape[0]
    idx = jax.random.permutation(key, n)
    for i in range(n // batch_size):
        sl = idx[i * batch_size : (i + 1) * batch_size]
        yield x[sl], y[sl]
