"""Decoder-only language model — the GPT-shaped sibling of the seq2seq zoo.

The encoder-decoder Transformer (transformer.py) covers BASELINE config 4's
WMT-shaped trials; this is the modern flagship shape for long-context work:
one causal trunk, tied readout, next-token loss. It deliberately reuses the
seq2seq building blocks rather than duplicating them —

- ``EncoderLayer`` under a causal mask IS a decoder-only block (pre-LN
  self-attention + FFN; MoE FFNs and Megatron tp partitioning included),
- ``MHA`` routes through chunked/Pallas flash attention on one chip and
  ring/Ulysses sequence parallelism on an ``sp`` mesh (ops/ring_attention,
  ops/ulysses) — exactly where a decoder-only model at long sequence needs
  them,
- the loss rides ``readout_xent``, so the measured per-device logits-bytes
  routing between materializing and blocked online-softmax xent
  (transformer.blocked_xent_enabled, calibrated on the 2026-08-01 v5e A/B)
  applies here unchanged — and a decoder-only model at big vocab × long
  sequence is precisely where the blocked path's HBM win binds.

SURVEY.md §2.8/§5 context: the reference ships no model code at all; the
zoo exists to exercise the executor/topology stack with real TPU-shaped
trial workloads.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metaopt_tpu.models.transformer import (
    EncoderLayer,
    blocked_xent_enabled,
    masked_mean_with_aux,
    readout_xent,
    sharded_init,
)


class DecoderOnlyLM(nn.Module):
    """Causal LM: embed + pos → n_layers pre-LN blocks → tied readout."""

    vocab: int = 1000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    dropout: float = 0.1
    max_len: int = 512
    #: >0 turns every FFN into a top-k-routed MoE (see models/moe.py)
    n_experts: int = 0
    capacity_factor: float = 1.25
    router_top_k: int = 1
    #: rematerialize each block in the backward pass (the HBM/FLOPs trade)
    remat: bool = False

    @nn.compact
    def __call__(self, tokens, *, train: bool, features: bool = False):
        emb = nn.Embed(
            self.vocab, self.d_model, dtype=jnp.bfloat16, name="embed",
            embedding_init=nn.with_partitioning(
                nn.initializers.normal(1.0), (None, None)
            ),
        )
        pos = self.param(
            "pos_embed",
            nn.with_partitioning(nn.initializers.normal(0.02), (None, None)),
            (self.max_len, self.d_model),
        )
        t_len = tokens.shape[1]
        if t_len > self.max_len:
            raise ValueError(
                f"sequence length {t_len} exceeds the positional table "
                f"(max_len={self.max_len}); pass max_len>=seq to make_lm"
            )
        pad = (tokens != 0)[:, None, None, :]                     # (b,1,1,k)
        causal = jnp.tril(jnp.ones((t_len, t_len), bool))[None, None]
        mask = causal & pad
        block_cls = (nn.remat(EncoderLayer, static_argnums=(3,))
                     if self.remat else EncoderLayer)
        x = emb(tokens) + pos[None, :t_len].astype(jnp.bfloat16)
        for i in range(self.n_layers):
            x = block_cls(self.d_model, self.n_heads, self.d_ff,
                          self.dropout, self.n_experts,
                          self.capacity_factor, True, self.router_top_k,
                          name=f"h{i}")(x, mask, train)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        if features:
            # pre-readout features for the blocked xent: the (B, T, V)
            # logits tensor never materializes (see readout_xent)
            return x
        logits = jnp.einsum(
            "btd,vd->btv", x.astype(jnp.bfloat16), emb.embedding
        )
        return logits.astype(jnp.float32)


def make_lm(hparams: Optional[Dict[str, Any]] = None,
            **overrides) -> DecoderOnlyLM:
    h = dict(hparams or {})
    h.update(overrides)
    return DecoderOnlyLM(
        vocab=int(h.get("vocab", 1000)),
        d_model=int(h.get("d_model", 512)),
        n_heads=int(h.get("n_heads", 8)),
        n_layers=int(h.get("n_layers", 6)),
        d_ff=int(h.get("d_ff", 2048)),
        dropout=float(h.get("dropout", 0.1)),
        max_len=int(h.get("max_len", 512)),
        n_experts=int(h.get("n_experts", 0)),
        capacity_factor=float(h.get("capacity_factor", 1.25)),
        router_top_k=int(h.get("router_top_k", 1)),
        remat=bool(h.get("remat", False)),
    )


def lm_loss_fn(model, params, tokens, dropout_key,
               moe_aux_weight: float = 0.01):
    """Next-token loss: predict ``tokens[:, 1:]`` from ``tokens[:, :-1]``."""
    from metaopt_tpu.parallel.sharding import pin_batch_layout

    inp, labels = pin_batch_layout(tokens[:, :-1]), tokens[:, 1:]
    blocked = blocked_xent_enabled(
        labels.shape[0], labels.shape[1], model.vocab)
    out, mutated = model.apply(
        {"params": params}, inp, train=True, features=blocked,
        rngs={"dropout": dropout_key},
        mutable=["aux_loss"],
    )
    mask = (labels != 0).astype(jnp.float32)
    loss = readout_xent(out, params, labels, model.vocab, blocked)
    return masked_mean_with_aux(loss, mask, mutated, moe_aux_weight)


def make_lm_train_step(model, tx):
    """The jittable train step (donated params/opt state)."""

    def train_step(params, opt_state, tokens, step_key):
        loss, grads = jax.value_and_grad(
            lambda p: lm_loss_fn(model, p, tokens, step_key)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def init_sharded_lm(model: DecoderOnlyLM, mesh: Mesh, tx,
                    batch_shape, seed: int = 0):
    """Params/opt state materialized directly on the mesh (one token input)."""
    b, s = batch_shape
    toks = jnp.zeros((b, s), jnp.int32)

    def init_fn(key):
        params = model.init(key, toks, train=False)["params"]
        return params, tx.init(params)

    return sharded_init(init_fn, mesh, seed)


def train_lm(
    hparams: Dict[str, Any],
    *,
    mesh: Optional[Mesh] = None,
    tp: int = 1,
    sp: int = 1,
    ep: int = 1,
    n_train: int = 2048,
    batch_size: int = 32,
    seq_len: int = 64,
    steps: int = 100,
    seed: int = 0,
    restore_dir: Optional[str] = None,
    save_dir: Optional[str] = None,
) -> float:
    """Train on the permutation-walk LM task; return final masked loss.

    ``seq_len`` is the length the MODEL trains on (inputs and labels):
    the stream generator produces ``seq_len + 1`` tokens so the shift in
    :func:`lm_loss_fn` lands back on ``seq_len`` — which therefore only
    needs to divide the ``sp`` mesh axis, exactly like the seq2seq
    harness. ``restore_dir``/``save_dir``: orbax trial checkpoints, same
    PBT-handoff/suspend-resume contract as ``train_and_eval``.
    """
    from metaopt_tpu.models.data import synthetic_lm
    from metaopt_tpu.models.transformer import maybe_restore, trial_setup
    from metaopt_tpu.parallel.mesh import use_mesh
    from metaopt_tpu.parallel.sharding import shard_batch

    if n_train < batch_size:
        raise ValueError(
            f"n_train ({n_train}) must be >= batch_size ({batch_size})")
    mesh, tx = trial_setup(hparams, mesh, tp, sp, ep, steps)
    model = make_lm(hparams, max_len=max(int(hparams.get("max_len", 512)),
                                         seq_len))

    key = jax.random.PRNGKey(seed)
    kd, kstep = jax.random.split(key)
    toks = synthetic_lm(kd, n_train, seq_len + 1, model.vocab)

    with use_mesh(mesh):
        params, opt_state, shardings = init_sharded_lm(
            model, mesh, tx, (batch_size, seq_len), seed
        )
        params, opt_state = maybe_restore(
            restore_dir, params, opt_state, shardings)
        step_fn = jax.jit(
            make_lm_train_step(model, tx),
            in_shardings=(
                shardings[0], shardings[1],
                NamedSharding(mesh, P("dp")), None,
            ),
            out_shardings=(shardings[0], shardings[1], None),
            donate_argnums=(0, 1),
        )
        loss = None
        for i in range(steps):
            lo = (i * batch_size) % (n_train - batch_size + 1)
            batch = shard_batch(mesh, toks[lo:lo + batch_size])
            params, opt_state, loss = step_fn(
                params, opt_state, batch, jax.random.fold_in(kstep, i)
            )
    if save_dir:
        from metaopt_tpu.models.checkpoint import save_state

        save_state(save_dir + "/params", params)
        save_state(save_dir + "/opt_state", opt_state)
    return float(loss)
