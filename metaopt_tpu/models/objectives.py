"""Closed-form CPU objectives (BASELINE config 1 and test fodder)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List


def rosenbrock(params: Dict[str, Any]) -> float:
    """Rosenbrock-2D: minimum 0 at (a, a^2); classic a=1, b=100."""
    x, y = float(params["x"]), float(params["y"])
    a, b = 1.0, 100.0
    return (a - x) ** 2 + b * (y - x * x) ** 2


def rosenbrock_nd(params: Dict[str, Any]) -> float:
    """N-D Rosenbrock over params named x0..xN sorted by index."""
    xs = [float(v) for _, v in sorted(params.items()) if _.startswith("x")]
    return sum(
        100.0 * (xs[i + 1] - xs[i] ** 2) ** 2 + (1.0 - xs[i]) ** 2
        for i in range(len(xs) - 1)
    )


def sphere(params: Dict[str, Any]) -> float:
    return sum(float(v) ** 2 for v in params.values())


def branin(params: Dict[str, Any]) -> float:
    """Branin-Hoo on x∈[-5,10], y∈[0,15]; min ≈ 0.397887."""
    import math

    x, y = float(params["x"]), float(params["y"])
    a, b, c = 1.0, 5.1 / (4 * math.pi ** 2), 5.0 / math.pi
    r, s, t = 6.0, 10.0, 1.0 / (8 * math.pi)
    return a * (y - b * x * x + c * x - r) ** 2 + s * (1 - t) * math.cos(x) + s


def make_objective(name: str) -> Callable[[Dict[str, Any]], float]:
    table = {
        "rosenbrock": rosenbrock,
        "rosenbrock_nd": rosenbrock_nd,
        "sphere": sphere,
        "branin": branin,
    }
    return table[name]


# -- vectorized zoo -------------------------------------------------------
#
# Column-form variants for the BatchedExecutor: each takes the
# ``Space.stack_points`` layout ``{name: (B,) array}`` and returns a
# ``(B,)`` value vector in pure jnp, so an entire suggestion pool traces
# into one device program. The mlp objective is the "zoo" flavor: a tiny
# regression net whose *init and train steps* are vmapped over the
# hyperparameter axis — k trials train as one compiled program.

#: search-space DSL for the vmapped mlp train objective
MLP_SPACE: Dict[str, str] = {
    "lr": "loguniform(0.001, 1.0)",
    "init": "uniform(0.1, 2.0)",
}


def rosenbrock_batch(cols) -> Any:
    """Column form of :func:`rosenbrock` over ``{'x','y'}``."""
    import jax.numpy as jnp

    x = jnp.asarray(cols["x"], jnp.float32)
    y = jnp.asarray(cols["y"], jnp.float32)
    return (1.0 - x) ** 2 + 100.0 * (y - x * x) ** 2


def sphere_batch(cols) -> Any:
    """Column form of :func:`sphere` over any all-real column dict."""
    import jax.numpy as jnp

    return sum(jnp.asarray(c, jnp.float32) ** 2 for c in cols.values())


def branin_batch(cols) -> Any:
    """Column form of :func:`branin` over ``{'x','y'}``."""
    import math

    import jax.numpy as jnp

    x = jnp.asarray(cols["x"], jnp.float32)
    y = jnp.asarray(cols["y"], jnp.float32)
    b, c = 5.1 / (4 * math.pi ** 2), 5.0 / math.pi
    s, t = 10.0, 1.0 / (8 * math.pi)
    return (y - b * x * x + c * x - 6.0) ** 2 + s * (1 - t) * jnp.cos(x) + s


def _mlp_core(width: int, steps: int, n: int, d: int):
    """Scalar train core: (lr, init_scale) → final train loss.

    Everything inside is jnp on a fixed synthetic regression set (seeded
    PRNG folds to constants at trace time), so the core is both jittable
    per-trial and vmappable over the hyperparameter axis.
    """
    import jax
    import jax.numpy as jnp

    def core(lr, init_scale):
        kx, kt, k1, k2 = jax.random.split(jax.random.PRNGKey(0), 4)
        X = jax.random.normal(kx, (n, d), jnp.float32)
        y = jnp.tanh(X @ jax.random.normal(kt, (d,), jnp.float32))
        params = {
            "W1": jax.random.normal(k1, (d, width), jnp.float32) * init_scale,
            "b1": jnp.zeros(width, jnp.float32),
            "w2": jax.random.normal(k2, (width,), jnp.float32) * init_scale,
            "b2": jnp.float32(0.0),
        }

        def loss(p):
            h = jnp.tanh(X @ p["W1"] + p["b1"])
            return jnp.mean((h @ p["w2"] + p["b2"] - y) ** 2)

        def step(p, _):
            g = jax.grad(loss)(p)
            return jax.tree_util.tree_map(lambda a, ga: a - lr * ga, p, g), None

        params, _ = jax.lax.scan(step, params, None, length=steps)
        return loss(params)

    return core


def make_mlp_objective(
    width: int = 16, steps: int = 12, n: int = 64, d: int = 8
) -> Callable[[Dict[str, Any]], float]:
    """Per-trial zoo objective: one jitted dispatch per evaluation."""
    import jax
    import jax.numpy as jnp

    jitted = jax.jit(_mlp_core(width, steps, n, d))

    def objective(params: Dict[str, Any]) -> float:
        return float(jitted(
            jnp.float32(params["lr"]), jnp.float32(params["init"])
        ))

    return objective


def make_mlp_batch_objective(
    width: int = 16, steps: int = 12, n: int = 64, d: int = 8
):
    """Vectorized zoo objective: vmapped init+train over the pool axis."""
    import jax
    import jax.numpy as jnp

    vcore = jax.vmap(_mlp_core(width, steps, n, d))

    def batch(cols):
        return vcore(
            jnp.asarray(cols["lr"], jnp.float32),
            jnp.asarray(cols["init"], jnp.float32),
        )

    return batch


def make_batch_objective(name: str):
    """Vectorized objective lookup, mirroring :func:`make_objective`."""
    if name == "mlp":
        return make_mlp_batch_objective()
    table = {
        "rosenbrock": rosenbrock_batch,
        "sphere": sphere_batch,
        "branin": branin_batch,
    }
    return table[name]
