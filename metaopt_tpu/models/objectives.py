"""Closed-form CPU objectives (BASELINE config 1 and test fodder)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List


def rosenbrock(params: Dict[str, Any]) -> float:
    """Rosenbrock-2D: minimum 0 at (a, a^2); classic a=1, b=100."""
    x, y = float(params["x"]), float(params["y"])
    a, b = 1.0, 100.0
    return (a - x) ** 2 + b * (y - x * x) ** 2


def rosenbrock_nd(params: Dict[str, Any]) -> float:
    """N-D Rosenbrock over params named x0..xN sorted by index."""
    xs = [float(v) for _, v in sorted(params.items()) if _.startswith("x")]
    return sum(
        100.0 * (xs[i + 1] - xs[i] ** 2) ** 2 + (1.0 - xs[i]) ** 2
        for i in range(len(xs) - 1)
    )


def sphere(params: Dict[str, Any]) -> float:
    return sum(float(v) ** 2 for v in params.values())


def branin(params: Dict[str, Any]) -> float:
    """Branin-Hoo on x∈[-5,10], y∈[0,15]; min ≈ 0.397887."""
    import math

    x, y = float(params["x"]), float(params["y"])
    a, b, c = 1.0, 5.1 / (4 * math.pi ** 2), 5.0 / math.pi
    r, s, t = 6.0, 10.0, 1.0 / (8 * math.pi)
    return a * (y - b * x * x + c * x - r) ** 2 + s * (1 - t) * math.cos(x) + s


def make_objective(name: str) -> Callable[[Dict[str, Any]], float]:
    table = {
        "rosenbrock": rosenbrock,
        "rosenbrock_nd": rosenbrock_nd,
        "sphere": sphere,
        "branin": branin,
    }
    return table[name]
