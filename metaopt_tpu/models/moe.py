"""Mixture-of-Experts feed-forward with expert parallelism ("ep").

Top-k routing with **capacity-bounded dispatch**: ``router_top_k=1`` is
Switch (Fedus et al. — gate = the chosen expert's raw probability);
``router_top_k=2`` is GShard-style top-2 (gates renormalized over the
chosen pair). Each expert processes at most ``capacity = ceil(
capacity_factor · k · T / E)`` dispatch items per step: the (token,
choice) pairs are scattered into per-expert slabs of that static shape,
the expert FFNs run as batched einsums over ``(E, capacity, d)``, and
results gather back and sum per token — FLOPs scale with
``capacity_factor · k · T``, not with ``E × T`` like a dense all-experts
dispatch. Items that overflow an expert's queue are dropped for the layer
(that choice contributes zero; the transformer's residual connection
carries the token through — standard Switch/GShard behavior) and counted
in the ``"moe_stats"`` collection.

Everything is static-shaped for XLA: capacity comes from the (static)
token count, queue positions are a cumsum over dispatch order, and
drop-vs-keep is a branchless scatter to an overflow slot that is sliced
away. Expert weights shard E/ep per chip via ``nn.with_partitioning``;
GSPMD inserts the token-shuffle collectives around the scatter/gather, the
analogue of the hand-written all_to_all in CUDA-era MoE stacks. Inside
each expert the hidden dim still splits over "tp", so ep composes with the
Megatron split.

The router adds the standard switch load-balancing auxiliary loss
(``n_experts · Σ_e fraction_e · mean_prob_e``, assignment fractions
averaged over the k choices), surfaced through the module's
``"aux_loss"`` collection so the train step can weigh it in; the
dropped-item fraction rides the ``"moe_stats"`` collection the same way.

``capacity_factor <= 0`` selects the dense dispatch — O(k·E·T) compute,
no dropping — kept as the numerics oracle the capacity path is tested
against.

ref: the reference framework has no model code (SURVEY.md §2.8) — this is
demo-zoo surface, here so trials can exercise expert-parallel shardings
on gang-scheduled sub-slices.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from flax import linen as nn

from metaopt_tpu.parallel.sharding import with_mesh_partitioning


class MoEFeedForward(nn.Module):
    d_model: int
    d_ff: int
    n_experts: int
    dropout: float = 0.0
    #: per-expert queue = capacity_factor·k·T/E items; <= 0 = dense oracle
    capacity_factor: float = 1.25
    #: experts per token: 1 = Switch (raw top prob gate), 2 = GShard-style
    #: top-2 (gates renormalized over the chosen pair)
    router_top_k: int = 1

    @nn.compact
    def __call__(self, x, *, train: bool):
        b, s, d = x.shape
        e, f = self.n_experts, self.d_ff
        k = max(1, min(int(self.router_top_k), e))

        router = nn.Dense(e, dtype=jnp.float32, name="router")
        wi = self.param(
            "wi",
            with_mesh_partitioning(nn.initializers.lecun_normal(),
                                   ("ep", None, "tp")),
            (e, d, f),
        )
        wo = self.param(
            "wo",
            with_mesh_partitioning(nn.initializers.lecun_normal(),
                                   ("ep", "tp", None)),
            (e, f, d),
        )

        logits = router(x.astype(jnp.float32))            # (b, s, E)
        probs = nn.softmax(logits, axis=-1)
        top_p, top_idx = jax.lax.top_k(probs, k)          # (b, s, k)
        if k == 1:
            gates = top_p                                 # Switch: raw prob
        else:  # GShard: renormalize over the chosen experts
            gates = top_p / jnp.clip(
                jnp.sum(top_p, axis=-1, keepdims=True), 1e-9, None
            )
        onehot_k = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)  # (b,s,k,E)
        assigned = jnp.sum(onehot_k, axis=2)              # (b, s, E)

        # switch load-balancing loss: fraction of assignments vs mean prob
        # per expert — pushes the router toward uniform utilization
        frac = jnp.mean(assigned, axis=(0, 1)) / k        # (E,)
        mean_prob = jnp.mean(probs, axis=(0, 1))          # (E,)
        self.sow("aux_loss", "moe_balance",
                 e * jnp.sum(frac * mean_prob))

        dropout = nn.Dropout(self.dropout, deterministic=not train)

        def expert_ffn(xe):
            """Batched-over-experts two-matmul FFN on bf16."""
            h = nn.relu(jnp.einsum(
                "e...d,edf->e...f",
                xe.astype(jnp.bfloat16), wi.astype(jnp.bfloat16)
            ))
            return jnp.einsum(
                "e...f,efd->e...d", dropout(h), wo.astype(jnp.bfloat16)
            )

        if self.capacity_factor <= 0:
            # dense oracle: every expert sees every (token, choice) copy —
            # k·E× the useful FLOPs, but exact (nothing dropped)
            y = jnp.zeros((b, s, d), jnp.float32)
            for j in range(k):
                oh = onehot_k[:, :, j]                    # (b, s, E)
                xe = jnp.einsum("bse,bsd->ebsd", oh, x.astype(jnp.float32))
                ye = expert_ffn(xe)
                yj = jnp.einsum("ebsd,bse->bsd", ye.astype(jnp.float32), oh)
                y = y + yj * gates[:, :, j][..., None]
            return y.astype(x.dtype)

        # ---- capacity-bounded scatter/gather dispatch over t·k items ----
        t = b * s
        cap = max(1, int(math.ceil(self.capacity_factor * k * t / e)))
        items = jnp.repeat(x.reshape(t, d), k, axis=0)    # (t·k, d)
        expf = top_idx.reshape(t * k)                     # item -> expert
        gatef = gates.reshape(t * k)
        # queue position of each item within its expert, in dispatch order
        ohf = onehot_k.reshape(t * k, e)
        pos_all = jnp.cumsum(ohf, axis=0) - 1.0           # (t·k, E)
        pos = jnp.take_along_axis(
            pos_all, expf[:, None], axis=1
        )[:, 0].astype(jnp.int32)                         # (t·k,)
        kept = pos < cap
        self.sow("moe_stats", "dropped_fraction",
                 1.0 - jnp.mean(kept.astype(jnp.float32)))

        # branchless scatter: overflowing items land in slot `cap`, which
        # is sliced away; kept (expert, slot) pairs are unique by cumsum
        dst = jnp.where(kept, pos, cap)                   # (t·k,)
        buf = jnp.zeros((e, cap + 1, d), x.dtype)
        expert_in = buf.at[expf, dst].set(items)[:, :cap]  # (E, cap, d)

        out = expert_ffn(expert_in)

        # gather back per item; dropped items contribute zero (the caller's
        # residual connection carries their token through)
        y = out[expf, jnp.minimum(dst, cap - 1)].astype(jnp.float32)
        y = jnp.where(kept[:, None], y, 0.0) * gatef[:, None]
        y = jnp.sum(y.reshape(t, k, d), axis=1).reshape(b, s, d)
        return y.astype(x.dtype)
