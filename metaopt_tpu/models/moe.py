"""Mixture-of-Experts feed-forward with expert parallelism ("ep").

Switch-style top-1 routing (Fedus et al.; see PAPERS.md) with
**capacity-bounded dispatch**: each expert processes at most
``capacity = ceil(capacity_factor · tokens / n_experts)`` tokens per step.
Kept tokens are scattered into per-expert slabs of that static shape, the
expert FFNs run as batched einsums over ``(E, capacity, d)``, and results
gather back to token order — so FLOPs scale with the *token* count
(``E · capacity ≈ capacity_factor · T``), not with ``E × T`` like a dense
all-experts dispatch. Tokens that overflow an expert's queue are dropped
for the layer (their FFN output is zero; the transformer's residual
connection carries them through unchanged — standard Switch behavior) and
counted in the ``"moe_stats"`` collection.

Everything is static-shaped for XLA: capacity comes from the (static)
token count, queue positions are a cumsum over token order, and
drop-vs-keep is a branchless scatter to an overflow slot that is sliced
away. Expert weights shard E/ep per chip via ``nn.with_partitioning``;
GSPMD inserts the token-shuffle collectives around the scatter/gather, the
analogue of the hand-written all_to_all in CUDA-era MoE stacks. Inside
each expert the hidden dim still splits over "tp", so ep composes with the
Megatron split.

The router adds the standard switch load-balancing auxiliary loss
(``n_experts · Σ_e fraction_e · mean_prob_e``), surfaced through the
module's ``"aux_loss"`` collection so the train step can weigh it in; the
dropped-token fraction rides the ``"moe_stats"`` collection the same way.

``capacity_factor <= 0`` selects the dense all-experts dispatch — O(E·T)
compute, no dropping — kept as the numerics oracle the capacity path is
tested against.

ref: the reference framework has no model code (SURVEY.md §2.8) — this is
demo-zoo surface, here so trials can exercise expert-parallel shardings
on gang-scheduled sub-slices.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from flax import linen as nn


class MoEFeedForward(nn.Module):
    d_model: int
    d_ff: int
    n_experts: int
    dropout: float = 0.0
    #: per-expert queue length = capacity_factor · T / E; <= 0 = dense oracle
    capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, x, *, train: bool):
        b, s, d = x.shape
        e, f = self.n_experts, self.d_ff

        router = nn.Dense(e, dtype=jnp.float32, name="router")
        wi = self.param(
            "wi",
            nn.with_partitioning(nn.initializers.lecun_normal(),
                                 ("ep", None, "tp")),
            (e, d, f),
        )
        wo = self.param(
            "wo",
            nn.with_partitioning(nn.initializers.lecun_normal(),
                                 ("ep", "tp", None)),
            (e, f, d),
        )

        logits = router(x.astype(jnp.float32))            # (b, s, E)
        probs = nn.softmax(logits, axis=-1)
        top = jnp.argmax(probs, axis=-1)                  # (b, s)
        onehot = jax.nn.one_hot(top, e, dtype=jnp.float32)
        gate = jnp.sum(probs * onehot, axis=-1)           # (b, s)

        # switch load-balancing loss: fraction of tokens vs mean prob per
        # expert — pushes the router toward uniform utilization
        frac = jnp.mean(onehot, axis=(0, 1))              # (E,)
        mean_prob = jnp.mean(probs, axis=(0, 1))          # (E,)
        self.sow("aux_loss", "moe_balance",
                 e * jnp.sum(frac * mean_prob))

        dropout = nn.Dropout(self.dropout, deterministic=not train)

        if self.capacity_factor <= 0:
            # dense all-experts oracle: (E, b, s, d) masked token copies —
            # E× the useful FLOPs, but exact (nothing dropped)
            xe = jnp.einsum("bse,bsd->ebsd", onehot, x.astype(jnp.float32))
            h = nn.relu(jnp.einsum(
                "ebsd,edf->ebsf",
                xe.astype(jnp.bfloat16), wi.astype(jnp.bfloat16)
            ))
            h = dropout(h)
            ye = jnp.einsum("ebsf,efd->ebsd", h, wo.astype(jnp.bfloat16))
            y = jnp.einsum("ebsd,bse->bsd", ye.astype(jnp.float32), onehot)
            return (y * gate[..., None]).astype(x.dtype)

        # ---- capacity-bounded scatter/gather dispatch ----
        t = b * s
        cap = max(1, int(math.ceil(self.capacity_factor * t / e)))
        xf = x.reshape(t, d)
        topf = top.reshape(t)
        # queue position of each token within its expert, in token order
        ohf = onehot.reshape(t, e)
        pos_all = jnp.cumsum(ohf, axis=0) - 1.0           # (t, E)
        pos = jnp.take_along_axis(
            pos_all, topf[:, None], axis=1
        )[:, 0].astype(jnp.int32)                         # (t,)
        kept = pos < cap
        self.sow("moe_stats", "dropped_fraction",
                 1.0 - jnp.mean(kept.astype(jnp.float32)))

        # branchless scatter: overflowing tokens land in slot `cap`, which
        # is sliced away; kept (expert, slot) pairs are unique by cumsum
        dst = jnp.where(kept, pos, cap)                   # (t,)
        buf = jnp.zeros((e, cap + 1, d), x.dtype)
        expert_in = buf.at[topf, dst].set(xf)[:, :cap]    # (E, cap, d)

        h = nn.relu(jnp.einsum(
            "ecd,edf->ecf",
            expert_in.astype(jnp.bfloat16), wi.astype(jnp.bfloat16)
        ))
        h = dropout(h)
        out = jnp.einsum("ecf,efd->ecd", h, wo.astype(jnp.bfloat16))

        # gather back to token order; dropped tokens contribute zero (the
        # caller's residual connection carries them through)
        y = out[topf, jnp.minimum(dst, cap - 1)].astype(jnp.float32)
        y = jnp.where(kept[:, None], y, 0.0)
        y = (y * gate.reshape(t)[:, None]).reshape(b, s, d)
        return y.astype(x.dtype)
