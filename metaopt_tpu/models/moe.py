"""Mixture-of-Experts feed-forward with expert parallelism ("ep").

Switch-style top-1 routing (Fedus et al.; see PAPERS.md): a router picks
one expert per token, tokens are dispatched with a one-hot combine so the
whole layer stays dense einsums — XLA partitions the expert axis over the
"ep" mesh dimension (expert weights are sharded E/ep per chip via
``nn.with_partitioning``) and inserts the dispatch/return collectives
itself, the GSPMD analogue of the hand-written all_to_all in
CUDA-era MoE stacks. Inside each expert the hidden dim still splits over
"tp", so ep composes with the Megatron split.

The router adds the standard switch load-balancing auxiliary loss
(``n_experts · Σ_e fraction_e · mean_prob_e``), surfaced through the
module's ``"aux_loss"`` collection so the train step can weigh it in.

ref: the reference framework has no model code (SURVEY.md §2.8) — this is
demo-zoo surface, here so trials can exercise expert-parallel shardings
on gang-scheduled sub-slices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


class MoEFeedForward(nn.Module):
    d_model: int
    d_ff: int
    n_experts: int
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, *, train: bool):
        b, s, d = x.shape
        e, f = self.n_experts, self.d_ff

        router = nn.Dense(e, dtype=jnp.float32, name="router")
        wi = self.param(
            "wi",
            nn.with_partitioning(nn.initializers.lecun_normal(),
                                 ("ep", None, "tp")),
            (e, d, f),
        )
        wo = self.param(
            "wo",
            nn.with_partitioning(nn.initializers.lecun_normal(),
                                 ("ep", "tp", None)),
            (e, f, d),
        )

        logits = router(x.astype(jnp.float32))            # (b, s, E)
        probs = nn.softmax(logits, axis=-1)
        top = jnp.argmax(probs, axis=-1)                  # (b, s)
        onehot = jax.nn.one_hot(top, e, dtype=jnp.float32)
        gate = jnp.sum(probs * onehot, axis=-1)           # (b, s)

        # switch load-balancing loss: fraction of tokens vs mean prob per
        # expert — pushes the router toward uniform utilization
        frac = jnp.mean(onehot, axis=(0, 1))              # (E,)
        mean_prob = jnp.mean(probs, axis=(0, 1))          # (E,)
        self.sow("aux_loss", "moe_balance",
                 e * jnp.sum(frac * mean_prob))

        # dense dispatch: (E, b, s, d) masked token copies. Fine at
        # demo expert counts; GSPMD shards the E axis over "ep" so each
        # chip materializes only E/ep expert slabs
        xe = jnp.einsum("bse,bsd->ebsd", onehot, x.astype(jnp.float32))
        h = nn.relu(jnp.einsum(
            "ebsd,edf->ebsf", xe.astype(jnp.bfloat16), wi.astype(jnp.bfloat16)
        ))
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        ye = jnp.einsum("ebsf,efd->ebsd", h, wo.astype(jnp.bfloat16))
        y = jnp.einsum("ebsd,bse->bsd", ye.astype(jnp.float32), onehot)
        return (y * gate[..., None]).astype(x.dtype)
