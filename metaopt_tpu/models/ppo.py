"""PPO actor-critic — BASELINE config 5 (EvolutionES population search on

PPO/Atari, gang-scheduled slices). Zero-egress stand-in for Atari: a fully
jittable vectorized control environment (noisy double-integrator with a
reward for stabilising at the origin), so rollout + GAE + the clipped PPO
update compile into ONE lax.scan program per trial — no host↔device
round-trip per env step, which is the TPU-idiomatic answer to the reference
era's CPU env loops.

Searchable hparams (the EvolutionES population axes): lr, clip_eps, entropy
coefficient, gae_lambda, hidden width. Fidelity = training iterations.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn


class EnvState(NamedTuple):
    pos: jnp.ndarray   # (n_envs, dim)
    vel: jnp.ndarray   # (n_envs, dim)
    t: jnp.ndarray     # (n_envs,)


DIM = 2
DT = 0.1
HORIZON = 200


def env_reset(key, n_envs: int) -> Tuple[EnvState, jnp.ndarray]:
    kp, kv = jax.random.split(key)
    pos = jax.random.uniform(kp, (n_envs, DIM), minval=-1.0, maxval=1.0)
    vel = jax.random.uniform(kv, (n_envs, DIM), minval=-0.5, maxval=0.5)
    state = EnvState(pos, vel, jnp.zeros(n_envs, jnp.int32))
    return state, obs_of(state)


def obs_of(s: EnvState) -> jnp.ndarray:
    return jnp.concatenate([s.pos, s.vel], axis=-1)  # (n_envs, 2*DIM)


def env_step(
    s: EnvState, action: jnp.ndarray, key
) -> Tuple[EnvState, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """action in [-1,1]^DIM accelerates the mass; reward favors the origin."""
    noise = 0.05 * jax.random.normal(key, s.vel.shape)
    vel = 0.98 * s.vel + DT * (jnp.clip(action, -1, 1) + noise)
    pos = s.pos + DT * vel
    t = s.t + 1
    # 0.1 scale keeps discounted returns O(10) so value regression is tame
    reward = -0.1 * (jnp.sum(pos ** 2, -1) + 0.1 * jnp.sum(vel ** 2, -1)
                     + 0.01 * jnp.sum(action ** 2, -1))
    done = (t >= HORIZON) | (jnp.sum(pos ** 2, -1) > 25.0)
    # auto-reset finished envs
    reset_pos = jnp.zeros_like(pos).at[:, 0].set(1.0)
    pos = jnp.where(done[:, None], reset_pos, pos)
    vel = jnp.where(done[:, None], jnp.zeros_like(vel), vel)
    t = jnp.where(done, 0, t)
    return EnvState(pos, vel, t), obs_of(EnvState(pos, vel, t)), reward, done


class ActorCritic(nn.Module):
    """Separate actor/critic trunks — a shared trunk lets the critic's

    large-magnitude regression gradients wreck the policy features.
    """

    hidden: int = 64

    @nn.compact
    def __call__(self, obs):
        x = obs.astype(jnp.float32)
        a = x
        for i in range(2):
            a = jnp.tanh(nn.Dense(self.hidden, name=f"pi_{i}")(a))
        mean = nn.Dense(
            DIM, name="pi_mean", kernel_init=nn.initializers.orthogonal(0.01)
        )(a)
        log_std = self.param("log_std", nn.initializers.constant(-0.5), (DIM,))
        c = x
        for i in range(2):
            c = jnp.tanh(nn.Dense(self.hidden, name=f"v_{i}")(c))
        value = nn.Dense(1, name="v")(c)[..., 0]
        return mean, log_std, value


def train(
    hparams: Dict[str, Any],
    *,
    n_envs: int = 64,
    rollout_len: int = 128,
    iterations: int = 20,
    ppo_epochs: int = 4,
    seed: int = 0,
) -> float:
    """Run PPO; return NEGATIVE mean episode return (HPO minimizes)."""
    # every scalar hyperparameter is a TRACED value, not a baked-in Python
    # constant: all trials of a sweep (same hidden width) then share ONE
    # XLA program, so the persistent compile cache turns a per-trial
    # remote compile (~2-3 min through the relay) into a per-sweep one —
    # the difference between evolution_ppo timing out and finishing
    hp = {
        "clip_eps": jnp.float32(hparams.get("clip_eps", 0.2)),
        "ent_coef": jnp.float32(hparams.get("ent_coef", 0.01)),
        "vf_coef": jnp.float32(hparams.get("vf_coef", 0.5)),
        "gamma": jnp.float32(hparams.get("gamma", 0.99)),
        "lam": jnp.float32(hparams.get("gae_lambda", 0.95)),
    }
    lr = float(hparams.get("lr", 3e-4))
    model = ActorCritic(hidden=int(hparams.get("hidden", 64)))

    key = jax.random.PRNGKey(seed)
    key, k_init, k_env = jax.random.split(key, 3)
    env_state, obs = env_reset(k_env, n_envs)
    params = model.init(k_init, obs)
    # inject_hyperparams carries lr inside opt_state as a traced leaf —
    # the update rule compiles once for any learning rate
    tx = optax.chain(
        optax.clip_by_global_norm(0.5),
        optax.inject_hyperparams(optax.adam)(learning_rate=lr),
    )
    opt_state = tx.init(params)

    def policy_logp(mean, log_std, action):
        var = jnp.exp(2 * log_std)
        return -0.5 * jnp.sum(
            (action - mean) ** 2 / var + 2 * log_std + jnp.log(2 * np.pi), -1
        )

    def rollout(carry, _):
        params, env_state, obs, key = carry
        key, ka, ks = jax.random.split(key, 3)
        mean, log_std, value = model.apply(params, obs)
        action = mean + jnp.exp(log_std) * jax.random.normal(ka, mean.shape)
        logp = policy_logp(mean, log_std, action)
        env_state, next_obs, reward, done = env_step(env_state, action, ks)
        frame = (obs, action, logp, value, reward, done)
        return (params, env_state, next_obs, key), frame

    def gae(values, rewards, dones, last_value, hp):
        def scan_fn(adv, inp):
            v, r, d, v_next = inp
            delta = r + hp["gamma"] * v_next * (1 - d) - v
            adv = delta + hp["gamma"] * hp["lam"] * (1 - d) * adv
            return adv, adv

        v_nexts = jnp.concatenate([values[1:], last_value[None]], 0)
        _, advs = jax.lax.scan(
            scan_fn, jnp.zeros_like(last_value),
            (values, rewards, dones.astype(jnp.float32), v_nexts),
            reverse=True,
        )
        return advs, advs + values

    def ppo_loss(params, batch, hp):
        obs, action, logp_old, adv, ret = batch
        mean, log_std, value = model.apply(params, obs)
        logp = policy_logp(mean, log_std, action)
        ratio = jnp.exp(logp - logp_old)
        adv_n = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg = -jnp.minimum(
            ratio * adv_n,
            jnp.clip(ratio, 1 - hp["clip_eps"], 1 + hp["clip_eps"]) * adv_n,
        ).mean()
        vloss = jnp.mean((value - ret) ** 2)
        entropy = jnp.sum(log_std + 0.5 * jnp.log(2 * np.pi * np.e))
        return pg + hp["vf_coef"] * vloss - hp["ent_coef"] * entropy

    @jax.jit
    def iteration(params, opt_state, env_state, obs, key, hp):
        (params, env_state, obs, key), frames = jax.lax.scan(
            rollout, (params, env_state, obs, key), None, length=rollout_len
        )
        f_obs, f_act, f_logp, f_val, f_rew, f_done = frames
        _, _, last_value = model.apply(params, obs)
        advs, rets = gae(f_val, f_rew, f_done, last_value, hp)
        flat = lambda a: a.reshape((-1,) + a.shape[2:])  # noqa: E731
        batch = (flat(f_obs), flat(f_act), flat(f_logp), flat(advs), flat(rets))

        def epoch(carry, _):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(ppo_loss)(params, batch, hp)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        (params, opt_state), _ = jax.lax.scan(
            epoch, (params, opt_state), None, length=ppo_epochs
        )
        mean_reward = f_rew.mean() * HORIZON  # per-episode scale
        return params, opt_state, env_state, obs, key, mean_reward

    mean_return = jnp.asarray(0.0)
    for _ in range(int(iterations)):
        params, opt_state, env_state, obs, key, mean_return = iteration(
            params, opt_state, env_state, obs, key, hp
        )
    return float(-mean_return)


def make_objective(**fixed):
    def objective(params: Dict[str, Any]) -> float:
        kw = dict(fixed)
        if "epochs" in params:
            kw["iterations"] = int(params["epochs"])  # fidelity axis
        return train(params, **kw)

    return objective
