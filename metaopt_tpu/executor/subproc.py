"""Subprocess executor: the reference-parity black-box protocol.

ref: src/metaopt/core/worker/consumer.py (SURVEY.md §2.1, §3.1) — materialize
params into the user's argv (and config file template if present), launch the
script as a subprocess, wait, read the results JSON written via
``client.report_results``. Non-zero exit → broken; SIGINT → interrupted.

TPU-era additions beyond the reference:

- heartbeat callbacks while waiting (the lineage's pacemaker, built in),
- the ``judge`` poll: streams ``client.report_partial`` lines to the
  algorithm's early-stop hook and terminates pruned trials,
- env injection (``METAOPT_TPU_RESULTS_PATH``, ``METAOPT_TPU_TRIAL_INFO``,
  plus any executor extras such as chip pinning from the TPU executor).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

from metaopt_tpu.client import (
    RESULTS_PATH_ENV,
    STOP_PATH_ENV,
    TRIAL_INFO_ENV,
)
from metaopt_tpu.executor.base import ExecutionResult, Executor, HeartbeatFn, JudgeFn
from metaopt_tpu.executor.faults import faults

log = logging.getLogger(__name__)


def _stop_path(results_path: str) -> str:
    """The stop-sentinel path — ONE derivation for the env injection and
    the prune-time touch, so the two can never drift apart."""
    return results_path + ".stop"
from metaopt_tpu.ledger.trial import Trial
from metaopt_tpu.space.builder import CommandTemplate


class SubprocessExecutor(Executor):
    def __init__(
        self,
        template: CommandTemplate,
        working_dir: Optional[str] = None,
        interpreter: Optional[List[str]] = None,
        poll_interval_s: float = 0.2,
        heartbeat_every_s: float = 5.0,
        timeout_s: Optional[float] = None,
        prune_grace_s: float = 1.0,
        extra_env: Optional[Dict[str, str]] = None,
        profile_dir: Optional[str] = None,
        ckpt_root: Optional[str] = None,
        jax_cache_dir: Optional[str] = None,
        device_probe_timeout_s: float = 90.0,
        park_max_s: float = 1800.0,
        park_poll_s: float = 60.0,
        probe_fn=None,
    ):
        self.template = template
        self.working_dir = working_dir
        self.interpreter = interpreter  # e.g. [sys.executable]; None = direct exec
        self.poll_interval_s = poll_interval_s
        self.heartbeat_every_s = heartbeat_every_s
        self.timeout_s = timeout_s
        self.prune_grace_s = prune_grace_s
        self.extra_env = dict(extra_env or {})
        if profile_dir:  # opt-in per-trial jax.profiler traces (client.profiled)
            self.extra_env["METAOPT_TPU_PROFILE_DIR"] = profile_dir
        if ckpt_root:  # PBT weight handoff root (client.checkpoint_paths)
            self.extra_env["METAOPT_TPU_CKPT_ROOT"] = ckpt_root
        # Persistent XLA compilation cache shared across trials (opt-in,
        # `hunt --jax-cache DIR`): every trial of a sweep traces the same
        # program modulo hyperparameter VALUES (shapes are static), so
        # trial N reuses trial 1's compile — the biggest trials/hour lever
        # for short TPU trials. The XLA:CPU AOT sub-cache is forced OFF
        # (same doctrine as utils/procs.setup_xla_cache): it stores
        # host-specific machine code, and a cache dir that outlives one
        # sweep — or is shared with the repo-wide .cache/xla — must never
        # SIGILL a later hunt on different hardware. The jax-level
        # executable cache alone carries the speedup.
        if jax_cache_dir:
            cache = os.path.expanduser(jax_cache_dir)
            os.makedirs(cache, exist_ok=True)
            self.extra_env.setdefault("JAX_COMPILATION_CACHE_DIR", cache)
            self.extra_env.setdefault(
                "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1"
            )
            self.extra_env["JAX_PERSISTENT_CACHE_ENABLE_XLA_CACHES"] = "none"
            # the PRODUCER process compiles too (the TPE suggest kernel):
            # share the same cache so a worker restart — or the N-th
            # parallel worker — skips the first-suggest compile stall.
            # jax is already imported here (env vars would be ignored), so
            # go through the live config; import alone never dials a relay
            import jax

            if not jax.config.jax_compilation_cache_dir:
                jax.config.update("jax_compilation_cache_dir", cache)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 1
                )
                jax.config.update(
                    "jax_persistent_cache_enable_xla_caches", "none"
                )
        # device circuit breaker (failure detection, SURVEY.md §5): a
        # relay/runtime wedge makes EVERY trial burn its full wall-clock
        # timeout and break — three of those and the worker's max_broken
        # guard aborts the hunt over an infrastructure flap. After a
        # timeout-shaped breakage (in a TPU-expecting environment only),
        # probe the backend in a disposable child before the next launch;
        # while unreachable, PARK (pumping the reservation heartbeat)
        # instead of feeding more trials to a dead chip. Lives here, not
        # only in TPUExecutor: un-pinned hunts through a relay (the
        # 5-config smoke) hit the identical failure mode.
        from metaopt_tpu.utils.procs import tpu_backend_reachable

        self.device_probe_timeout_s = device_probe_timeout_s
        self.park_max_s = park_max_s
        self.park_poll_s = park_poll_s
        self._probe = probe_fn or tpu_backend_reachable
        self._suspect_device = False

    # -- device circuit breaker --------------------------------------------
    @staticmethod
    def _device_expected() -> bool:
        """Is there a TPU this environment is SUPPOSED to reach?

        Distinguishes "no TPU ever" (breaker stays disarmed — on a CPU
        box the probe returns False by design and would park every trial
        after one slow script) from "TPU stopped answering" (park).
        Mirrors the environment signals ``tpu_backend_reachable`` keys on.
        """
        platforms = (os.environ.get("JAX_PLATFORMS") or "").strip()
        if platforms == "cpu":
            return False
        if os.environ.get("PALLAS_AXON_POOL_IPS"):  # relay-tunneled chip
            return True
        if "tpu" in platforms or "axon" in platforms:
            return True
        import glob

        return bool(glob.glob("/dev/accel*"))  # directly-attached runtime

    def _probe_with_beats(self, heartbeat: Optional[HeartbeatFn]):
        """Run the (blocking, up to 90s) probe while pumping heartbeats.

        The probe child outlives the stale-reservation window — going
        silent for its whole duration would let another worker steal the
        trial mid-probe. Returns True/False (probe verdict) or None when
        the reservation was lost while waiting.
        """
        import threading

        out: Dict[str, bool] = {}

        def run() -> None:
            out["ok"] = bool(
                self._probe(timeout_s=self.device_probe_timeout_s)
            )

        th = threading.Thread(target=run, daemon=True)
        th.start()
        while th.is_alive():
            if heartbeat and not heartbeat():
                return None  # probe child dies on its own deadline
            th.join(timeout=2.0)
        return out.get("ok", False)

    def _await_device(self, heartbeat: Optional[HeartbeatFn]) -> str:
        """Probe until the backend answers; park (beating) while it won't.

        ``"ok"`` = device reachable (suspicion cleared); ``"budget"`` =
        park budget exhausted; ``"lost"`` = reservation lost meanwhile.
        """
        deadline = time.time() + self.park_max_s
        while True:
            verdict = self._probe_with_beats(heartbeat)
            if verdict is None:
                return "lost"
            if verdict:
                self._suspect_device = False
                return "ok"
            if time.time() >= deadline:
                return "budget"
            log.warning(
                "TPU backend unreachable; parking %.1fs before re-probe "
                "(not launching trials at a dead device)", self.park_poll_s,
            )
            sleep_until = time.time() + self.park_poll_s
            while time.time() < min(sleep_until, deadline):
                if heartbeat and not heartbeat():
                    return "lost"
                time.sleep(min(5.0, self.park_poll_s))

    # -- env/argv assembly -------------------------------------------------
    def _prepare(self, trial: Trial, tmpdir: str) -> tuple[List[str], Dict[str, str], str]:
        results_path = os.path.join(tmpdir, "results.json")
        config_out = None
        if self.template.has_config:
            ext = os.path.splitext(self.template.config_path or "c.yaml")[1]
            config_out = os.path.join(tmpdir, f"trial_config{ext}")
            self.template.materialize_config(trial.params, config_out)
        argv = self.template.format(trial.params, config_out=config_out)
        if self.interpreter:
            argv = list(self.interpreter) + argv
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update(trial.resources.get("env", {}))
        # the trial process must be able to import metaopt_tpu.client even
        # when the framework runs from a source tree rather than site-packages
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
        parts = env.get("PYTHONPATH", "").split(os.pathsep)
        if pkg_root not in parts:
            env["PYTHONPATH"] = os.pathsep.join([pkg_root] + [p for p in parts if p])
        env[RESULTS_PATH_ENV] = results_path
        env[STOP_PATH_ENV] = _stop_path(results_path)
        env[TRIAL_INFO_ENV] = json.dumps(
            {
                "id": trial.id,
                "experiment": trial.experiment,
                "params": trial.params,
                "parent": trial.parent,
                "resources": {k: v for k, v in trial.resources.items() if k != "env"},
            }
        )
        return argv, env, results_path

    @staticmethod
    def _read_partial(path: str, already: int) -> List[Dict[str, Any]]:
        try:
            with open(path) as f:
                lines = f.readlines()
        except FileNotFoundError:
            return []
        out = []
        for line in lines[already:]:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # torn tail write; picked up next poll
        return out

    # -- main --------------------------------------------------------------
    def execute(
        self,
        trial: Trial,
        heartbeat: Optional[HeartbeatFn] = None,
        judge: Optional[JudgeFn] = None,
    ) -> ExecutionResult:
        if self._suspect_device:
            outcome = self._await_device(heartbeat)
            if outcome == "lost":
                return ExecutionResult(
                    "interrupted",
                    note="lost reservation while parked at an "
                         "unreachable TPU backend",
                )
            if outcome == "budget":
                return ExecutionResult(
                    "interrupted",
                    note=f"TPU backend unreachable; parked "
                    f"{self.park_max_s:.0f}s without recovery (trial "
                    f"released for retry)",
                    requeue=True,
                )
        result = self._execute_inner(trial, heartbeat, judge)
        # arm ONLY on the executor's own wall-clock-timeout note (a
        # script's stderr tail may mention "timeout" for other reasons)
        if (result.status == "broken"
                and (result.note or "").startswith("timeout after")
                and self._device_expected()):
            self._suspect_device = True
            log.warning(
                "trial %s broke by timeout — probing the TPU backend "
                "before the next launch", trial.id[:8],
            )
            # Attribution: if the backend is down RIGHT NOW, the timeout
            # was infrastructure, not the user script — "broken" would
            # count it toward max_broken and a relay wedge would abort the
            # hunt (the r3 smoke lost 3 PPO trials exactly this way).
            # Reclassify as interrupted: the reservation is released for
            # retry and the next execute() parks on the armed suspicion.
            verdict = self._probe_with_beats(heartbeat)
            if verdict is None:
                return ExecutionResult(
                    "interrupted",
                    note="lost reservation while attributing a timeout",
                )
            if verdict is False:
                return ExecutionResult(
                    "interrupted",
                    note=f"{result.note}, with the TPU backend unreachable "
                         "— attributed to a device wedge; trial released "
                         "for retry",
                    requeue=True,
                )
            self._suspect_device = False  # backend fine: a real timeout
        return result

    def _execute_inner(
        self,
        trial: Trial,
        heartbeat: Optional[HeartbeatFn] = None,
        judge: Optional[JudgeFn] = None,
    ) -> ExecutionResult:
        with tempfile.TemporaryDirectory(prefix="mtpu_trial_") as tmpdir:
            argv, env, results_path = self._prepare(trial, tmpdir)
            # stdout/stderr go to files, not PIPEs: an undrained PIPE deadlocks
            # a chatty script once the ~64KB buffer fills
            stdout_path = os.path.join(tmpdir, "stdout")
            stderr_path = os.path.join(tmpdir, "stderr")
            if faults.fire("spawn_fail"):
                return ExecutionResult("broken", note="spawn failed: injected")
            try:
                with open(stdout_path, "wb") as so, open(stderr_path, "wb") as se:
                    proc = subprocess.Popen(
                        argv,
                        env=env,
                        cwd=self.working_dir,
                        stdout=so,
                        stderr=se,
                        start_new_session=True,  # isolate signals (we kill the group)
                    )
            except OSError as e:
                return ExecutionResult("broken", note=f"spawn failed: {e}")

            if faults.fire("kill_trial"):  # simulate mid-run preemption
                self._kill(proc)

            partial: List[Dict[str, Any]] = []
            started = time.time()
            last_beat = started
            pruned = False
            try:
                while True:
                    rc = proc.poll()
                    if rc is not None:
                        break
                    now = time.time()
                    if self.timeout_s and now - started > self.timeout_s:
                        self._kill(proc)
                        return ExecutionResult(
                            "broken", note=f"timeout after {self.timeout_s}s"
                        )
                    if heartbeat and now - last_beat >= self.heartbeat_every_s:
                        last_beat = now
                        if faults.fire("drop_heartbeat") or not heartbeat():
                            self._kill(proc)
                            return ExecutionResult(
                                "interrupted", note="lost reservation"
                            )
                    new = self._read_partial(results_path + ".partial", len(partial))
                    if new:
                        partial.extend(new)
                        if judge:
                            decision = judge(trial, partial)
                            if decision and decision.get("stop"):
                                pruned = True
                                # cooperative first: touch the stop
                                # sentinel (client.stop_requested) so a
                                # gang-scheduled multi-process trial can
                                # agree-to-stop on its mesh and exit
                                # cleanly; SIGTERM only after the grace —
                                # a kill mid-collective strands the rest
                                # of the gang
                                self._touch(_stop_path(results_path))
                                deadline = time.time() + self.prune_grace_s
                                while (proc.poll() is None
                                       and time.time() < deadline):
                                    # the lease must not lapse during a
                                    # long grace: keep beating (and honor
                                    # the overall timeout) while waiting
                                    now2 = time.time()
                                    if (self.timeout_s
                                            and now2 - started
                                            > self.timeout_s):
                                        break
                                    if (heartbeat
                                            and now2 - last_beat
                                            >= self.heartbeat_every_s):
                                        last_beat = now2
                                        if not heartbeat():
                                            break
                                    time.sleep(self.poll_interval_s)
                                if proc.poll() is None:
                                    self._kill(proc)
                                proc.wait()
                                break
                    time.sleep(self.poll_interval_s)
            except KeyboardInterrupt:
                self._kill(proc)
                proc.wait()
                return ExecutionResult("interrupted", note="SIGINT")

            rc = proc.returncode if not pruned else 0
            results = self._collect(results_path, partial, pruned)
            if results is None:
                try:
                    with open(stderr_path, "rb") as f:
                        stderr_tail = f.read()[-2000:]
                except OSError:
                    stderr_tail = b""
                return ExecutionResult(
                    "broken",
                    exit_code=rc,
                    note=(
                        f"exit={rc}, no results reported; stderr tail: "
                        f"{stderr_tail.decode(errors='replace')}"
                    ),
                )
            if rc != 0:
                return ExecutionResult(
                    "broken", exit_code=rc, note=f"non-zero exit {rc}"
                )
            note = "pruned by judge" if pruned else ""
            return ExecutionResult("completed", results=results, exit_code=rc, note=note)

    @staticmethod
    def _touch(path: str) -> None:
        try:
            with open(path, "w"):
                pass
        except OSError:
            pass  # sentinel is best-effort; the SIGTERM fallback remains

    @staticmethod
    def _kill(proc: subprocess.Popen) -> None:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass

    @staticmethod
    def _collect(
        results_path: str, partial: List[Dict[str, Any]], pruned: bool
    ) -> Optional[List[Dict[str, Any]]]:
        """Final results file wins; a pruned trial falls back to its last

        partial objective (the rung's measurement, per ASHA semantics).
        """
        try:
            with open(results_path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            pass
        if partial:
            last = partial[-1]
            return [
                {
                    "name": "objective",
                    "type": "objective",
                    "value": float(last["objective"]),
                },
                {
                    "name": "pruned_at_step" if pruned else "last_step",
                    "type": "statistic",
                    "value": int(last.get("step", -1)),
                },
            ]
        return None
