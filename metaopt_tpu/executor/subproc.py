"""Subprocess executor: the reference-parity black-box protocol.

ref: src/metaopt/core/worker/consumer.py (SURVEY.md §2.1, §3.1) — materialize
params into the user's argv (and config file template if present), launch the
script as a subprocess, wait, read the results JSON written via
``client.report_results``. Non-zero exit → broken; SIGINT → interrupted.

TPU-era additions beyond the reference:

- heartbeat callbacks while waiting (the lineage's pacemaker, built in),
- the ``judge`` poll: streams ``client.report_partial`` lines to the
  algorithm's early-stop hook and terminates pruned trials,
- env injection (``METAOPT_TPU_RESULTS_PATH``, ``METAOPT_TPU_TRIAL_INFO``,
  plus any executor extras such as chip pinning from the TPU executor).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

from metaopt_tpu.client import (
    RESULTS_PATH_ENV,
    STOP_PATH_ENV,
    TRIAL_INFO_ENV,
)
from metaopt_tpu.executor.base import ExecutionResult, Executor, HeartbeatFn, JudgeFn
from metaopt_tpu.executor.faults import faults


def _stop_path(results_path: str) -> str:
    """The stop-sentinel path — ONE derivation for the env injection and
    the prune-time touch, so the two can never drift apart."""
    return results_path + ".stop"
from metaopt_tpu.ledger.trial import Trial
from metaopt_tpu.space.builder import CommandTemplate


class SubprocessExecutor(Executor):
    def __init__(
        self,
        template: CommandTemplate,
        working_dir: Optional[str] = None,
        interpreter: Optional[List[str]] = None,
        poll_interval_s: float = 0.2,
        heartbeat_every_s: float = 5.0,
        timeout_s: Optional[float] = None,
        prune_grace_s: float = 1.0,
        extra_env: Optional[Dict[str, str]] = None,
        profile_dir: Optional[str] = None,
        ckpt_root: Optional[str] = None,
        jax_cache_dir: Optional[str] = None,
    ):
        self.template = template
        self.working_dir = working_dir
        self.interpreter = interpreter  # e.g. [sys.executable]; None = direct exec
        self.poll_interval_s = poll_interval_s
        self.heartbeat_every_s = heartbeat_every_s
        self.timeout_s = timeout_s
        self.prune_grace_s = prune_grace_s
        self.extra_env = dict(extra_env or {})
        if profile_dir:  # opt-in per-trial jax.profiler traces (client.profiled)
            self.extra_env["METAOPT_TPU_PROFILE_DIR"] = profile_dir
        if ckpt_root:  # PBT weight handoff root (client.checkpoint_paths)
            self.extra_env["METAOPT_TPU_CKPT_ROOT"] = ckpt_root
        # Persistent XLA compilation cache shared across trials (opt-in,
        # `hunt --jax-cache DIR`): every trial of a sweep traces the same
        # program modulo hyperparameter VALUES (shapes are static), so
        # trial N reuses trial 1's compile — the biggest trials/hour lever
        # for short TPU trials. Opt-in because XLA:CPU caches are AOT
        # machine code: sharing the dir across heterogeneous hosts risks
        # SIGILL, a call the user must make.
        if jax_cache_dir:
            cache = os.path.expanduser(jax_cache_dir)
            os.makedirs(cache, exist_ok=True)
            self.extra_env.setdefault("JAX_COMPILATION_CACHE_DIR", cache)
            self.extra_env.setdefault(
                "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1"
            )
            # the PRODUCER process compiles too (the TPE suggest kernel):
            # share the same cache so a worker restart — or the N-th
            # parallel worker — skips the first-suggest compile stall.
            # jax is already imported here (env vars would be ignored), so
            # go through the live config; import alone never dials a relay
            import jax

            if not jax.config.jax_compilation_cache_dir:
                jax.config.update("jax_compilation_cache_dir", cache)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 1
                )

    # -- env/argv assembly -------------------------------------------------
    def _prepare(self, trial: Trial, tmpdir: str) -> tuple[List[str], Dict[str, str], str]:
        results_path = os.path.join(tmpdir, "results.json")
        config_out = None
        if self.template.has_config:
            ext = os.path.splitext(self.template.config_path or "c.yaml")[1]
            config_out = os.path.join(tmpdir, f"trial_config{ext}")
            self.template.materialize_config(trial.params, config_out)
        argv = self.template.format(trial.params, config_out=config_out)
        if self.interpreter:
            argv = list(self.interpreter) + argv
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update(trial.resources.get("env", {}))
        # the trial process must be able to import metaopt_tpu.client even
        # when the framework runs from a source tree rather than site-packages
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
        parts = env.get("PYTHONPATH", "").split(os.pathsep)
        if pkg_root not in parts:
            env["PYTHONPATH"] = os.pathsep.join([pkg_root] + [p for p in parts if p])
        env[RESULTS_PATH_ENV] = results_path
        env[STOP_PATH_ENV] = _stop_path(results_path)
        env[TRIAL_INFO_ENV] = json.dumps(
            {
                "id": trial.id,
                "experiment": trial.experiment,
                "params": trial.params,
                "parent": trial.parent,
                "resources": {k: v for k, v in trial.resources.items() if k != "env"},
            }
        )
        return argv, env, results_path

    @staticmethod
    def _read_partial(path: str, already: int) -> List[Dict[str, Any]]:
        try:
            with open(path) as f:
                lines = f.readlines()
        except FileNotFoundError:
            return []
        out = []
        for line in lines[already:]:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # torn tail write; picked up next poll
        return out

    # -- main --------------------------------------------------------------
    def execute(
        self,
        trial: Trial,
        heartbeat: Optional[HeartbeatFn] = None,
        judge: Optional[JudgeFn] = None,
    ) -> ExecutionResult:
        with tempfile.TemporaryDirectory(prefix="mtpu_trial_") as tmpdir:
            argv, env, results_path = self._prepare(trial, tmpdir)
            # stdout/stderr go to files, not PIPEs: an undrained PIPE deadlocks
            # a chatty script once the ~64KB buffer fills
            stdout_path = os.path.join(tmpdir, "stdout")
            stderr_path = os.path.join(tmpdir, "stderr")
            if faults.fire("spawn_fail"):
                return ExecutionResult("broken", note="spawn failed: injected")
            try:
                with open(stdout_path, "wb") as so, open(stderr_path, "wb") as se:
                    proc = subprocess.Popen(
                        argv,
                        env=env,
                        cwd=self.working_dir,
                        stdout=so,
                        stderr=se,
                        start_new_session=True,  # isolate signals (we kill the group)
                    )
            except OSError as e:
                return ExecutionResult("broken", note=f"spawn failed: {e}")

            if faults.fire("kill_trial"):  # simulate mid-run preemption
                self._kill(proc)

            partial: List[Dict[str, Any]] = []
            started = time.time()
            last_beat = started
            pruned = False
            try:
                while True:
                    rc = proc.poll()
                    if rc is not None:
                        break
                    now = time.time()
                    if self.timeout_s and now - started > self.timeout_s:
                        self._kill(proc)
                        return ExecutionResult(
                            "broken", note=f"timeout after {self.timeout_s}s"
                        )
                    if heartbeat and now - last_beat >= self.heartbeat_every_s:
                        last_beat = now
                        if faults.fire("drop_heartbeat") or not heartbeat():
                            self._kill(proc)
                            return ExecutionResult(
                                "interrupted", note="lost reservation"
                            )
                    new = self._read_partial(results_path + ".partial", len(partial))
                    if new:
                        partial.extend(new)
                        if judge:
                            decision = judge(trial, partial)
                            if decision and decision.get("stop"):
                                pruned = True
                                # cooperative first: touch the stop
                                # sentinel (client.stop_requested) so a
                                # gang-scheduled multi-process trial can
                                # agree-to-stop on its mesh and exit
                                # cleanly; SIGTERM only after the grace —
                                # a kill mid-collective strands the rest
                                # of the gang
                                self._touch(_stop_path(results_path))
                                deadline = time.time() + self.prune_grace_s
                                while (proc.poll() is None
                                       and time.time() < deadline):
                                    # the lease must not lapse during a
                                    # long grace: keep beating (and honor
                                    # the overall timeout) while waiting
                                    now2 = time.time()
                                    if (self.timeout_s
                                            and now2 - started
                                            > self.timeout_s):
                                        break
                                    if (heartbeat
                                            and now2 - last_beat
                                            >= self.heartbeat_every_s):
                                        last_beat = now2
                                        if not heartbeat():
                                            break
                                    time.sleep(self.poll_interval_s)
                                if proc.poll() is None:
                                    self._kill(proc)
                                proc.wait()
                                break
                    time.sleep(self.poll_interval_s)
            except KeyboardInterrupt:
                self._kill(proc)
                proc.wait()
                return ExecutionResult("interrupted", note="SIGINT")

            rc = proc.returncode if not pruned else 0
            results = self._collect(results_path, partial, pruned)
            if results is None:
                try:
                    with open(stderr_path, "rb") as f:
                        stderr_tail = f.read()[-2000:]
                except OSError:
                    stderr_tail = b""
                return ExecutionResult(
                    "broken",
                    exit_code=rc,
                    note=(
                        f"exit={rc}, no results reported; stderr tail: "
                        f"{stderr_tail.decode(errors='replace')}"
                    ),
                )
            if rc != 0:
                return ExecutionResult(
                    "broken", exit_code=rc, note=f"non-zero exit {rc}"
                )
            note = "pruned by judge" if pruned else ""
            return ExecutionResult("completed", results=results, exit_code=rc, note=note)

    @staticmethod
    def _touch(path: str) -> None:
        try:
            with open(path, "w"):
                pass
        except OSError:
            pass  # sentinel is best-effort; the SIGTERM fallback remains

    @staticmethod
    def _kill(proc: subprocess.Popen) -> None:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass

    @staticmethod
    def _collect(
        results_path: str, partial: List[Dict[str, Any]], pruned: bool
    ) -> Optional[List[Dict[str, Any]]]:
        """Final results file wins; a pruned trial falls back to its last

        partial objective (the rung's measurement, per ASHA semantics).
        """
        try:
            with open(results_path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            pass
        if partial:
            last = partial[-1]
            return [
                {
                    "name": "objective",
                    "type": "objective",
                    "value": float(last["objective"]),
                },
                {
                    "name": "pruned_at_step" if pruned else "last_step",
                    "type": "statistic",
                    "value": int(last.get("step", -1)),
                },
            ]
        return None
