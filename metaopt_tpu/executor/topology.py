"""TPU topology model + sub-slice allocation.

The reference has no equivalent — its "device placement" is whatever
`CUDA_VISIBLE_DEVICES` the user script saw (SURVEY.md §2.7/§2.8). On a pod,
trial placement is a first-class scheduler resource: a trial occupies one chip
or an ICI-contiguous sub-slice, and a broken trial must hand its chips back.

Design: chips are addressed by their linear index in the pod's natural torus
ordering. Sub-slices are power-of-two sized, size-aligned blocks — aligned
blocks of the natural ordering are ICI-contiguous on TPU slices, which makes
a classic **buddy allocator** the right shape: allocate/free are O(log n),
fragmentation is bounded, and every allocation is automatically contiguous
and aligned. Cross-process safety (multiple workon processes on one host
sharing a slice) comes from an optional flock-guarded state file, the same
doctrine as the FileLedger.
"""

from __future__ import annotations

import fcntl
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


@dataclass(frozen=True)
class SubSlice:
    """An allocated, ICI-contiguous block of chips."""

    start: int
    size: int

    @property
    def chips(self) -> List[int]:
        return list(range(self.start, self.start + self.size))


class BuddyAllocator:
    """Power-of-two buddy allocator over ``total`` linearly-ordered chips."""

    def __init__(self, total: int):
        if not _is_pow2(total):
            raise ValueError(f"total chips must be a power of two, got {total}")
        self.total = total
        # free lists per block size
        self._free: Dict[int, List[int]] = {total: [0]}
        self._lock = threading.Lock()

    def allocate(self, n: int) -> Optional[SubSlice]:
        """Allocate an aligned block of next_pow2(n) chips, or None if full."""
        size = next_pow2(max(1, n))
        if size > self.total:
            raise ValueError(f"requested {n} chips > slice size {self.total}")
        with self._lock:
            return self._alloc_locked(size)

    def _alloc_locked(self, size: int) -> Optional[SubSlice]:
        s = size
        while s <= self.total and not self._free.get(s):
            s *= 2
        if s > self.total or not self._free.get(s):
            return None
        start = self._free[s].pop(0)
        while s > size:  # split down, keeping the upper buddy free
            s //= 2
            self._free.setdefault(s, []).append(start + s)
        return SubSlice(start, size)

    def free(self, block: SubSlice) -> None:
        """Return a block; coalesce with its buddy where possible."""
        with self._lock:
            start, size = block.start, block.size
            while size < self.total:
                buddy = start ^ size
                lst = self._free.get(size, [])
                if buddy in lst:
                    lst.remove(buddy)
                    start = min(start, buddy)
                    size *= 2
                else:
                    break
            self._free.setdefault(size, []).append(start)
            self._free[size].sort()

    @property
    def n_free_chips(self) -> int:
        with self._lock:
            return sum(s * len(lst) for s, lst in self._free.items())


class ChipRegistry:
    """Cross-process chip accounting for one host/slice.

    State file (flock-guarded JSON) maps claimed blocks to (pid, heartbeat).
    Dead claimants (stale heartbeat or vanished pid) are reaped on every
    allocate — a broken or killed trial can never leak its sub-slice, the
    failure-semantics gap SURVEY.md §2.7 flags in the reference.
    """

    def __init__(self, total: int, state_path: Optional[str] = None,
                 stale_s: float = 120.0):
        if not _is_pow2(total):
            raise ValueError(f"total chips must be a power of two, got {total}")
        self.total = total
        self.state_path = state_path
        self.stale_s = stale_s
        self._local = BuddyAllocator(total) if state_path is None else None

    # -- in-process fast path ---------------------------------------------
    def allocate(self, n: int, owner: str = "") -> Optional[SubSlice]:
        if self._local is not None:
            return self._local.allocate(n)
        return self._file_op("alloc", n=n, owner=owner)

    def free(self, block: SubSlice) -> None:
        if self._local is not None:
            self._local.free(block)
            return
        self._file_op("free", start=block.start, size=block.size)

    def heartbeat(self, block: SubSlice) -> None:
        if self._local is None:
            self._file_op("beat", start=block.start, size=block.size)

    @property
    def n_free_chips(self) -> int:
        if self._local is not None:
            return self._local.n_free_chips
        state = self._file_op("read")
        # block size lives in the claim KEY ("start:size"), not the value
        used = sum(int(key.split(":")[1]) for key in state["claims"])
        return self.total - used

    # -- file-backed path --------------------------------------------------
    def _file_op(self, op: str, **kw):
        assert self.state_path is not None
        os.makedirs(os.path.dirname(self.state_path) or ".", exist_ok=True)
        with open(self.state_path + ".lock", "a+") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                try:
                    with open(self.state_path) as f:
                        state = json.load(f)
                except (FileNotFoundError, json.JSONDecodeError):
                    state = {"claims": {}}
                self._reap(state)
                result = None
                if op == "alloc":
                    result = self._file_alloc(state, kw["n"], kw["owner"])
                elif op == "free":
                    state["claims"].pop(f"{kw['start']}:{kw['size']}", None)
                elif op == "beat":
                    key = f"{kw['start']}:{kw['size']}"
                    if key in state["claims"]:
                        state["claims"][key]["t"] = time.time()
                elif op == "read":
                    return state
                tmp = self.state_path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(state, f)
                # atomic, deliberately not durable: claims are leases —
                # a power-lost registry is healed by _reap() on the next
                # flock'd read (stale heartbeats expire the claims)
                os.replace(tmp, self.state_path)  # mtpu: lint-ok MTP001 lease state, heal-on-read
                return result
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)

    def _reap(self, state: Dict) -> None:
        now = time.time()
        dead = []
        for key, claim in state["claims"].items():
            pid_alive = True
            try:
                os.kill(int(claim["pid"]), 0)
            except (ProcessLookupError, ValueError):
                pid_alive = False
            except PermissionError:
                pass
            if not pid_alive or now - claim.get("t", 0) > self.stale_s:
                dead.append(key)
        for key in dead:
            del state["claims"][key]

    def _file_alloc(self, state: Dict, n: int, owner: str) -> Optional[SubSlice]:
        size = next_pow2(max(1, n))
        if size > self.total:
            raise ValueError(f"requested {n} chips > slice size {self.total}")
        used = set()
        for key in state["claims"]:
            start, bsize = (int(v) for v in key.split(":"))
            used.update(range(start, start + bsize))
        for start in range(0, self.total, size):  # aligned scan
            block = range(start, start + size)
            if not used.intersection(block):
                state["claims"][f"{start}:{size}"] = {
                    "pid": os.getpid(),
                    "owner": owner,
                    "t": time.time(),
                }
                return SubSlice(start, size)
        return None


def detect_slice_size(default: int = 1) -> int:
    """Chips visible to this host (env override > jax > default)."""
    env = os.environ.get("MTPU_SLICE_CHIPS")
    if env:
        return int(env)
    try:
        import jax

        return max(1, len(jax.devices()))
    except Exception:
        return default


def chip_env(block: SubSlice) -> Dict[str, str]:
    """Env vars pinning a trial subprocess to its sub-slice.

    ``TPU_VISIBLE_CHIPS``/``TPU_PROCESS_BOUNDS`` is the TPU analogue of the
    reference's `CUDA_VISIBLE_DEVICES` story; `MTPU_ASSIGNED_CHIPS` is the
    framework-level contract (read by `client.get_trial_info` users and the
    demo models) and works on any backend.
    """
    ids = ",".join(str(c) for c in block.chips)
    return {
        "MTPU_ASSIGNED_CHIPS": ids,
        "TPU_VISIBLE_CHIPS": ids,
        "TPU_CHIPS_PER_PROCESS_BOUNDS": f"1,1,{block.size}",
        "TPU_PROCESS_BOUNDS": "1,1,1",
    }
