"""Batched executor: a whole suggestion pool as one device program.

No reference equivalent — the lineage treats every trial as an opaque
subprocess. Here, for vectorizable spaces (``Space.vectorizable()``) and
vectorized objectives (``benchmark/tasks.py`` ``batch`` forms, the
``models/objectives.py`` vmapped zoo), an entire pool of reserved trials
stacks into per-dimension device columns and evaluates as a *single*
jitted launch, so population HPO (EvolutionES / PBT / CMA-ES generations,
ASHA rungs) is FLOPs-bound instead of dispatch-bound.

Semantics, relative to :class:`InProcessExecutor`:

- **Failure isolation is per trial.** A NaN/inf row marks *that* trial
  ``broken``; its batch siblings still complete. A failure to stack or
  trace (heterogeneous pool, objective raising) breaks the affected
  chunk only, never the worker.
- **Fidelity cohorts.** The single fidelity dim must be constant per
  launch; a mixed-fidelity pool is split into per-rung sub-batches
  (ASHA hands workers exactly such cohorts).
- **Heartbeats still matter.** Each trial's heartbeat is checked before
  its chunk launches and again after results land, so a reservation the
  sweeper reclaimed mid-flight is reported ``interrupted`` — the batch
  never complete-stomps a reassigned trial.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

import numpy as np

from metaopt_tpu.executor.base import ExecutionResult, Executor, HeartbeatFn, JudgeFn
from metaopt_tpu.ledger.trial import Trial
from metaopt_tpu.space.space import Space

#: vectorized objective: ``{name: (B,) column}`` → ``(B,)`` values
BatchObjectiveFn = Callable[[Mapping[str, Any]], Any]


def _make_kernel(batch_fn: BatchObjectiveFn):
    """Close the vectorized objective into the fused pool-eval kernel."""
    import jax
    import jax.numpy as jnp

    # mtpu: hotpath
    def pool_eval(cols):
        """One launch per pool: objective values for every row at once."""
        out = jnp.asarray(batch_fn(cols))
        return jnp.reshape(out.astype(jnp.float32), (-1,))

    return jax.jit(pool_eval)


class BatchedExecutor(Executor):
    """Evaluates pools of trials through a single jitted ``vmap`` program.

    ``batch_fn`` takes the :meth:`Space.stack_points` column layout and
    returns a ``(B,)`` value vector; ``space`` proves the pool is
    batchable (and does the stacking) before anything traces.
    ``chunk_size`` bounds one launch — heartbeats are re-checked between
    chunks so a long pool can still abort early.
    """

    def __init__(
        self,
        batch_fn: BatchObjectiveFn,
        space: Space,
        *,
        chunk_size: Optional[int] = None,
        result_name: str = "objective",
    ):
        reason = space.why_not_vectorizable()
        if reason is not None:
            raise ValueError(f"space is not vectorizable: {reason}")
        if chunk_size is not None and chunk_size < 1:
            raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
        self.batch_fn = batch_fn
        self.space = space
        self.chunk_size = chunk_size
        self.result_name = result_name
        self._kernel = _make_kernel(batch_fn)
        # telemetry counters; executors are shared across worker threads
        # in batched hunts, so bookkeeping takes the lock
        self._tel_lock = threading.Lock()
        self._launches = 0
        self._rows = 0
        self._pools = 0

    # -- telemetry ---------------------------------------------------------
    def telemetry(self) -> Dict[str, int]:
        with self._tel_lock:
            return {
                "kernel_launches": self._launches,
                "rows_evaluated": self._rows,
                "pools": self._pools,
            }

    # -- Executor contract -------------------------------------------------
    def execute(
        self,
        trial: Trial,
        heartbeat: Optional[HeartbeatFn] = None,
        judge: Optional[JudgeFn] = None,
    ) -> ExecutionResult:
        return self.execute_batch([trial], heartbeats=[heartbeat], judge=judge)[0]

    def execute_batch(
        self,
        trials: Sequence[Trial],
        heartbeats: Optional[Sequence[Optional[HeartbeatFn]]] = None,
        judge: Optional[JudgeFn] = None,
    ) -> List[ExecutionResult]:
        """Evaluate a pool; returns one :class:`ExecutionResult` per trial.

        ``judge`` is accepted for interface parity but unused: a batched
        pool completes as a unit, there is no partial-results stream to
        prune against.
        """
        n = len(trials)
        if heartbeats is None:
            heartbeats = [None] * n
        if len(heartbeats) != n:
            raise ValueError(f"{n} trials but {len(heartbeats)} heartbeats")
        out: List[Optional[ExecutionResult]] = [None] * n

        fid = self.space.fidelity
        # per-rung cohorts: one launch may only hold one budget level
        groups: Dict[Any, List[int]] = {}
        for i, t in enumerate(trials):
            key = t.params.get(fid.name) if fid is not None else None
            groups.setdefault(key, []).append(i)

        for idxs in groups.values():
            step = self.chunk_size or len(idxs)
            for start in range(0, len(idxs), step):
                chunk = idxs[start:start + step]
                for i, res in zip(chunk, self._run_chunk(trials, heartbeats, chunk)):
                    out[i] = res
        return out  # type: ignore[return-value]  # every index was assigned

    # -- internals ---------------------------------------------------------
    def _run_chunk(
        self,
        trials: Sequence[Trial],
        heartbeats: Sequence[Optional[HeartbeatFn]],
        chunk: List[int],
    ) -> List[ExecutionResult]:
        """One launch: stack → fused kernel → fan results back out."""
        results: Dict[int, ExecutionResult] = {}
        live: List[int] = []
        for i in chunk:
            hb = heartbeats[i]
            if hb is not None and not hb():
                results[i] = ExecutionResult("interrupted", note="lost reservation")
            else:
                live.append(i)
        if live:
            try:
                cols, _ = self.space.stack_points([trials[i].params for i in live])
                values = np.asarray(self._kernel(cols), dtype=np.float64)
            except KeyboardInterrupt:
                raise
            except Exception as e:  # a broken chunk must not kill the worker
                note = f"{type(e).__name__}: {e}"
                for i in live:
                    results[i] = ExecutionResult("broken", note=note)
                live = []
            else:
                with self._tel_lock:
                    self._launches += 1
                    self._rows += len(live)
                    self._pools += 1
        for row, i in enumerate(live):
            hb = heartbeats[i]
            if hb is not None and not hb():
                # reservation reclaimed while the pool ran: the result is
                # stale, some other worker owns this trial now
                results[i] = ExecutionResult(
                    "interrupted", note="lost reservation during evaluation"
                )
                continue
            v = float(values[row])
            if not np.isfinite(v):
                results[i] = ExecutionResult(
                    "broken", note=f"non-finite objective: {v}"
                )
            else:
                results[i] = ExecutionResult(
                    "completed",
                    results=[{
                        "name": self.result_name,
                        "type": "objective",
                        "value": v,
                    }],
                    exit_code=0,
                )
        return [results[i] for i in chunk]
