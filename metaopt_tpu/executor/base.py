"""Executor contract."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from metaopt_tpu.ledger.trial import Trial

#: periodic callback while a trial runs; returning False means the worker
#: lost its reservation and the executor should abort the trial.
HeartbeatFn = Callable[[], bool]

#: early-stop hook: given the partial-results stream, return {"stop": True}
#: to prune the running trial.
JudgeFn = Callable[[Trial, List[Dict[str, Any]]], Optional[Dict[str, Any]]]


@dataclass
class ExecutionResult:
    status: str                                   # completed | broken | interrupted
    results: List[Dict[str, Any]] = field(default_factory=list)
    exit_code: Optional[int] = None
    note: str = ""
    #: infrastructure (not script) failure: the worker releases the trial
    #: back to 'new' so the hunt retries it once the device recovers,
    #: instead of leaving it for a manual `mtpu resume`
    requeue: bool = False


class Executor:
    """Runs one reserved trial to completion."""

    def execute(
        self,
        trial: Trial,
        heartbeat: Optional[HeartbeatFn] = None,
        judge: Optional[JudgeFn] = None,
    ) -> ExecutionResult:
        raise NotImplementedError

    def close(self) -> None:
        pass
