"""In-process executor: the objective is a Python callable.

No reference equivalent (the reference always subprocesses) — this exists for
unit tests, benchmarks, and library-style use where the objective is cheap
Python/JAX. The callable may return a float (treated as the objective) or a
full list of typed result dicts.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

from metaopt_tpu.executor.base import ExecutionResult, Executor, HeartbeatFn, JudgeFn
from metaopt_tpu.ledger.trial import Trial

ObjectiveFn = Callable[[Dict[str, Any]], Union[float, List[Dict[str, Any]]]]


class InProcessExecutor(Executor):
    def __init__(self, fn: ObjectiveFn):
        self.fn = fn

    def execute(
        self,
        trial: Trial,
        heartbeat: Optional[HeartbeatFn] = None,
        judge: Optional[JudgeFn] = None,
    ) -> ExecutionResult:
        if heartbeat is not None and not heartbeat():
            return ExecutionResult("interrupted", note="lost reservation")
        try:
            out = self.fn(dict(trial.params))
        except KeyboardInterrupt:
            raise
        except Exception as e:  # a broken trial must not kill the worker
            return ExecutionResult("broken", note=f"{type(e).__name__}: {e}")
        if isinstance(out, (int, float)):
            results = [{"name": "objective", "type": "objective", "value": float(out)}]
        else:
            results = [dict(r) for r in out]
        # re-check after evaluation: a reservation lost *while* fn ran means
        # the sweeper already reassigned this trial — completing it now
        # would stomp the other worker's run with a stale result.
        if heartbeat is not None and not heartbeat():
            return ExecutionResult(
                "interrupted", note="lost reservation during evaluation"
            )
        return ExecutionResult("completed", results=results, exit_code=0)
