"""Trial executors: how a reserved trial actually runs.

ref: src/metaopt/core/worker/consumer.py (SURVEY.md §2.1) — the reference
materializes hyperparameters into the user's command line / config file,
subprocesses the script, and reads back the results JSON. Executors here:

- :class:`InProcessExecutor` — objective is a Python callable (tests,
  benchmarks, BASELINE config 1's CPU-only Rosenbrock),
- :class:`SubprocessExecutor` — full reference-parity black-box protocol
  (argv/config materialization + report_results handshake + heartbeats +
  the ``judge`` early-stop poll over ``report_partial`` streams),
- :class:`TPUExecutor` (:mod:`metaopt_tpu.executor.tpu`) — subprocess
  execution with chip / ICI-sub-slice pinning and gang scheduling,
- :class:`BatchedExecutor` (:mod:`metaopt_tpu.executor.batched`) — a whole
  suggestion pool evaluated as one jitted ``vmap`` program over stacked
  hyperparameter columns (vectorizable spaces only).
"""

from metaopt_tpu.executor.base import ExecutionResult, Executor
from metaopt_tpu.executor.batched import BatchedExecutor
from metaopt_tpu.executor.inprocess import InProcessExecutor
from metaopt_tpu.executor.subproc import SubprocessExecutor

__all__ = [
    "Executor",
    "ExecutionResult",
    "BatchedExecutor",
    "InProcessExecutor",
    "SubprocessExecutor",
]
