"""Fault injection for executor tests.

The reference has no fault-injection tooling (SURVEY.md §5: failure handling
is trial-level statuses only); this module is the build's deliberate
addition so failure-detection paths — broken trials, lost heartbeats,
spawn failures, stale-reservation release — are testable deterministically
instead of waiting for real preemptions.

Usage (tests or chaos runs):

    from metaopt_tpu.executor.faults import faults
    faults.arm("kill_trial", times=1)        # next trial gets SIGKILLed
    faults.arm("drop_heartbeat", times=2)    # next 2 heartbeats report lost
    faults.arm("spawn_fail", times=1)        # next spawn errors out

or via env (picked up at import, for subprocess-launched workers):

    METAOPT_TPU_FAULTS="kill_trial:1,drop_heartbeat:2"

Each armed rule fires ``times`` times then disarms. ``fire(kind)`` is the
single hook executors consult; it is thread-safe and cheap when nothing is
armed (one dict lookup).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict

log = logging.getLogger(__name__)

FAULTS_ENV = "METAOPT_TPU_FAULTS"


class FaultInjector:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._armed: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}
        env = os.environ.get(FAULTS_ENV, "")
        for part in env.split(","):
            part = part.strip()
            if not part:
                continue
            kind, _, n = part.partition(":")
            try:
                self._armed[kind] = int(n) if n else 1
            except ValueError:
                # a chaos-test env typo must not kill the worker at import
                log.warning("ignoring malformed %s entry %r", FAULTS_ENV, part)

    def arm(self, kind: str, times: int = 1) -> None:
        with self._lock:
            self._armed[kind] = self._armed.get(kind, 0) + times

    def fire(self, kind: str) -> bool:
        """Consume one charge of ``kind``; True = the fault should happen."""
        if not self._armed:  # fast path: nothing armed anywhere
            return False
        with self._lock:
            n = self._armed.get(kind, 0)
            if n <= 0:
                return False
            if n == 1:
                del self._armed[kind]
            else:
                self._armed[kind] = n - 1
            self._fired[kind] = self._fired.get(kind, 0) + 1
        log.warning("fault injected: %s", kind)
        return True

    def fired(self, kind: str) -> int:
        with self._lock:
            return self._fired.get(kind, 0)

    def reset(self) -> None:
        with self._lock:
            self._armed.clear()
            self._fired.clear()


#: process-global injector — executors consult this instance
faults = FaultInjector()
