"""Fault injection for executor tests.

The reference has no fault-injection tooling (SURVEY.md §5: failure handling
is trial-level statuses only); this module is the build's deliberate
addition so failure-detection paths — broken trials, lost heartbeats,
spawn failures, stale-reservation release — are testable deterministically
instead of waiting for real preemptions.

Usage (tests or chaos runs):

    from metaopt_tpu.executor.faults import faults
    faults.arm("kill_trial", times=1)        # next trial gets SIGKILLed
    faults.arm("drop_heartbeat", times=2)    # next 2 heartbeats report lost
    faults.arm("spawn_fail", times=1)        # next spawn errors out

or via env (picked up at import, for subprocess-launched workers AND
subprocess-launched coordinators):

    METAOPT_TPU_FAULTS="kill_trial:1,drop_heartbeat:2"
    METAOPT_TPU_FAULTS="crash_server:1@5"    # skip 5 firings, then fire
    METAOPT_TPU_FAULTS="drop_heartbeat:p=0.01@7"  # 1% per firing, seed 7

Each armed rule fires ``times`` times then disarms; an optional ``@skip``
suffix (or ``arm(..., skip=N)``) swallows the first N firings first — how
the crash-chaos sweep kills a coordinator at EVERY injection point in turn
(skip=0 dies at the first barrier, skip=1 at the second, …).

The second spec form, ``kind:p=<prob>@<seed>`` (or
``arm_probability(kind, p, seed)``), arms a SEEDED probabilistic rule:
every ``fire(kind)`` call flips a coin from a per-kind
``random.Random(seed)`` stream and fires with probability ``p``,
indefinitely. Because the stream is seeded per kind and advanced once
per ``fire`` call, a whole fault sweep is reproducible from the seed
alone — the property the scale simulator (``metaopt_tpu/sim``) builds
its deterministic fault schedules on. Deterministic ``times@skip`` rules
take precedence when both are armed for the same kind.

``fire(kind)`` is the single hook executors consult; it is thread-safe and
cheap when nothing is armed (one dict lookup).

Coordinator durability kinds (consumed in ``coord/server.py`` and
``coord/wal.py``; each SIGKILLs the process at a crash-consistent point,
so arm them only in a subprocess-hosted server):

- ``crash_server``: die in the connection sender thread AFTER the WAL
  durability barrier but BEFORE the reply is sent — the write is durable,
  the ack is lost; the client's retry must be answered from the journaled
  reply cache after restart.
- ``torn_wal_tail``: die mid-WAL-batch with only half the batch's bytes
  written — recovery must truncate the torn tail and keep every
  previously-acknowledged record.
- ``partial_snapshot``: die mid-snapshot with a truncated ``.tmp`` on
  disk, before the atomic rename — recovery must ignore the torn tmp and
  come back from the previous snapshot + un-compacted WAL.

Hand-off kinds (consumed in ``coord/server.py``; armed at every barrier
of the live-migration protocol by the chaos sweep — the ``@skip``
selector walks the barriers in order):

- ``crash_handoff_source``: die on the SOURCE shard — skip 0 fires
  after the experiment is fenced but before its state is captured
  (pre-snapshot), skip 1 fires after capture, before the reply ships
  (post-snapshot). Either way nothing was shipped; the source's own
  WAL + fence journaling must bring it back still owning the
  experiment.
- ``crash_handoff_dest``: die on the DESTINATION shard — skip 0 fires
  before any shipped state is applied (pre-commit), skip 1 fires after
  the shipped state is journaled + fsynced but before the apply reply
  (post-commit). The orchestrator's retry against the respawned dest
  must be idempotent.
- ``torn_handoff_ship``: die on the destination mid-apply with only a
  prefix of the shipped trial docs journaled — recovery replays the
  partial prefix harmlessly and the orchestrator's retried apply
  completes the move.

Incremental-snapshot kinds (consumed in ``coord/server.py``; the
columnar trial-archive manifest pipeline):

- ``crash_segment_seal``: die right after a sealed archive segment's
  file is durable under ``<snapshot>.segments/`` but before any
  manifest references it — recovery restores from the previous
  manifest + WAL; the orphan segment file must be GC'd by a later
  snapshot, never loaded.
- ``crash_manifest_commit``: die with the new manifest ``.tmp`` fully
  fsynced but the atomic rename not yet issued — recovery comes back
  on the PREVIOUS manifest plus the un-compacted WAL, bit-identically.

Eviction kind (consumed in ``coord/server.py``; the lazy
hydration/eviction plane of the multi-tenant service):

- ``crash_evict``: die mid-eviction — skip 0 fires after the evict
  file is fsynced but before the WAL evict record (recovery serves
  the experiment fully resident; the orphaned file is harmless),
  skip 1 fires after the record is durable but before any state is
  dropped (recovery replays the drop and comes back cleanly
  evicted). Either way no acknowledged write is lost — there is no
  in-between state.
"""

from __future__ import annotations

import logging
import os
import random
import threading
from typing import Dict, Optional

log = logging.getLogger(__name__)

FAULTS_ENV = "METAOPT_TPU_FAULTS"


class FaultInjector:
    def __init__(self, spec: Optional[str] = None) -> None:
        """Parse ``spec`` (default: the ``METAOPT_TPU_FAULTS`` env var).

        An explicit ``spec`` builds a private injector — the scale
        simulator constructs one per run so its seeded schedule can't
        leak into (or be polluted by) the process-global instance.
        """
        self._lock = threading.Lock()
        self._armed: Dict[str, int] = {}
        self._skip: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}
        #: kind → (probability, seeded stream) for ``p=`` rules
        self._prob: Dict[str, tuple] = {}
        if spec is None:
            spec = os.environ.get(FAULTS_ENV, "")
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            kind, _, rule = part.partition(":")
            times, _, suffix = rule.partition("@")
            try:
                if times.startswith("p="):
                    # probabilistic: kind:p=<prob>@<seed> (seed optional)
                    self.arm_probability(
                        kind, float(times[2:]),
                        seed=int(suffix) if suffix else 0)
                else:
                    self._armed[kind] = int(times) if times else 1
                    if suffix:
                        self._skip[kind] = int(suffix)
            except ValueError:
                # a chaos-test env typo must not kill the worker at import
                log.warning("ignoring malformed %s entry %r", FAULTS_ENV, part)

    def arm(self, kind: str, times: int = 1, skip: int = 0) -> None:
        """Arm ``kind`` to fire ``times`` times, after swallowing its first
        ``skip`` firings (the injection-point selector for chaos sweeps)."""
        with self._lock:
            self._armed[kind] = self._armed.get(kind, 0) + times
            if skip:
                self._skip[kind] = self._skip.get(kind, 0) + skip

    def arm_probability(self, kind: str, p: float, seed: int = 0) -> None:
        """Arm ``kind`` to fire with probability ``p`` on EVERY consult.

        The coin stream is ``random.Random(seed)`` salted with the kind
        name, advanced exactly once per ``fire(kind)`` call — so a sweep's
        entire fault pattern replays bit-identically from (spec, seed)
        regardless of what other kinds are armed. ``p<=0`` disarms.
        """
        with self._lock:
            if p <= 0:
                self._prob.pop(kind, None)
            else:
                self._prob[kind] = (
                    min(1.0, p), random.Random(f"{kind}@{seed}"))

    def fire(self, kind: str) -> bool:
        """Consume one charge of ``kind``; True = the fault should happen."""
        if not self._armed and not self._prob:  # fast path: nothing armed
            return False
        with self._lock:
            n = self._armed.get(kind, 0)
            if n <= 0:
                rule = self._prob.get(kind)
                if rule is None:
                    return False
                p, rng = rule
                # always advance the stream: the draw sequence must be a
                # pure function of how many times this kind was consulted
                if rng.random() >= p:
                    return False
                self._fired[kind] = self._fired.get(kind, 0) + 1
                log.warning("fault injected (p=%g): %s", p, kind)
                return True
            s = self._skip.get(kind, 0)
            if s > 0:
                self._skip[kind] = s - 1
                return False
            if n == 1:
                del self._armed[kind]
            else:
                self._armed[kind] = n - 1
            self._fired[kind] = self._fired.get(kind, 0) + 1
        log.warning("fault injected: %s", kind)
        return True

    def fired(self, kind: str) -> int:
        with self._lock:
            return self._fired.get(kind, 0)

    def reset(self) -> None:
        with self._lock:
            self._armed.clear()
            self._skip.clear()
            self._fired.clear()
            self._prob.clear()


#: process-global injector — executors consult this instance
faults = FaultInjector()
