"""TPU executor: subprocess trials pinned to chips / ICI sub-slices.

The TPU-native replacement for the reference Consumer's "launch on whatever
GPU the script grabs" (SURVEY.md §2.7 TPU-native equivalent): each trial is
gang-scheduled onto an ICI-contiguous sub-slice via the buddy allocator, the
subprocess sees only its chips (env pinning), and the sub-slice is returned
on ANY exit path — completion, breakage, prune, or executor kill — so a
broken trial never leaks capacity.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, List, Optional

from metaopt_tpu.executor.base import ExecutionResult, HeartbeatFn, JudgeFn
from metaopt_tpu.executor.subproc import SubprocessExecutor
from metaopt_tpu.executor.topology import (
    ChipRegistry,
    SubSlice,
    chip_env,
    detect_slice_size,
)
from metaopt_tpu.ledger.trial import Trial
from metaopt_tpu.space.builder import CommandTemplate

log = logging.getLogger(__name__)


class TPUExecutor(SubprocessExecutor):
    def __init__(
        self,
        template: CommandTemplate,
        n_chips: int = 1,
        total_chips: Optional[int] = None,
        registry: Optional[ChipRegistry] = None,
        registry_path: Optional[str] = None,
        allocate_timeout_s: float = 600.0,
        allocate_poll_s: float = 0.5,
        device_probe_timeout_s: float = 90.0,
        park_max_s: float = 1800.0,
        park_poll_s: float = 60.0,
        probe_fn=None,
        **kwargs,
    ):
        super().__init__(template, **kwargs)
        # device circuit breaker (failure detection, SURVEY.md §5): a
        # relay/runtime wedge makes EVERY trial burn its full wall-clock
        # timeout and break — three of those and the worker's max_broken
        # guard aborts the hunt over an infrastructure flap. After a
        # timeout-shaped breakage, probe the backend in a disposable
        # child before launching the next trial; while unreachable, PARK
        # (pump the reservation's heartbeat, poll the device) instead of
        # feeding more trials to a dead chip.
        from metaopt_tpu.utils.procs import tpu_backend_reachable

        self.device_probe_timeout_s = device_probe_timeout_s
        self.park_max_s = park_max_s
        self.park_poll_s = park_poll_s
        self._probe = probe_fn or tpu_backend_reachable
        self._suspect_device = False
        self.n_chips = int(n_chips)
        total = total_chips or detect_slice_size()
        # round the slice size down to a power of two for the buddy allocator
        p = 1
        while p * 2 <= total:
            p *= 2
        if registry is None and registry_path is None:
            # default to the flock'd per-host state file: every executor on
            # this host — other hunt PROCESSES and `--n-workers` threads
            # alike — must arbitrate the same physical chips, not each
            # believe the whole slice is free
            import tempfile

            registry_path = os.path.join(
                tempfile.gettempdir(), f"metaopt_tpu-chips-{p}.json"
            )
        self.registry = registry or ChipRegistry(p, state_path=registry_path)
        self.allocate_timeout_s = allocate_timeout_s
        self.allocate_poll_s = allocate_poll_s

    def execute(
        self,
        trial: Trial,
        heartbeat: Optional[HeartbeatFn] = None,
        judge: Optional[JudgeFn] = None,
    ) -> ExecutionResult:
        if self._suspect_device:
            outcome = self._await_device(heartbeat)
            if outcome == "lost":
                return ExecutionResult(
                    "interrupted",
                    note="lost reservation while parked at an "
                         "unreachable TPU backend",
                )
            if outcome == "budget":
                return ExecutionResult(
                    "interrupted",
                    note=f"TPU backend unreachable; parked "
                    f"{self.park_max_s:.0f}s without recovery (trial "
                    f"released for retry — see `mtpu resume`)",
                )
        block = self._acquire(trial, heartbeat)
        if block is None:
            return ExecutionResult(
                "interrupted",
                note=f"no {self.n_chips}-chip sub-slice became available "
                f"within {self.allocate_timeout_s}s",
            )
        trial.resources = {
            "chips": block.chips,
            "slice": {"start": block.start, "size": block.size},
            "env": chip_env(block),
        }
        log.debug("trial %s pinned to chips %s", trial.id[:8], block.chips)

        def beating() -> bool:
            self.registry.heartbeat(block)
            return heartbeat() if heartbeat else True

        try:
            result = super().execute(trial, heartbeat=beating, judge=judge)
        finally:
            self.registry.free(block)  # every exit path returns the sub-slice
        # arm ONLY on the executor's own wall-clock-timeout note (the
        # exact shape subproc.py emits) — a script's stderr tail may
        # mention "timeout" for unrelated reasons — and only where a TPU
        # is actually expected: on a CPU-only box the probe returns False
        # by design and would park every trial after one slow script
        if (result.status == "broken"
                and (result.note or "").startswith("timeout after")
                and self._device_expected()):
            self._suspect_device = True
            log.warning(
                "trial %s broke by timeout — probing the TPU backend "
                "before the next launch", trial.id[:8],
            )
        return result

    @staticmethod
    def _device_expected() -> bool:
        """Is there a TPU this environment is SUPPOSED to reach?

        Distinguishes "no TPU ever" (breaker must stay disarmed) from
        "TPU stopped answering" (park). Mirrors the environment signals
        ``tpu_backend_reachable`` keys on.
        """
        platforms = (os.environ.get("JAX_PLATFORMS") or "").strip()
        if platforms == "cpu":
            return False
        if os.environ.get("PALLAS_AXON_POOL_IPS"):  # relay-tunneled chip
            return True
        if "tpu" in platforms or "axon" in platforms:
            return True
        import glob

        return bool(glob.glob("/dev/accel*"))  # directly-attached runtime

    def _probe_with_beats(self, heartbeat: Optional[HeartbeatFn]):
        """Run the (blocking, up to 90s) probe while pumping heartbeats.

        The probe child outlives the stale-reservation window — going
        silent for its whole duration would let another worker steal the
        trial mid-probe. Returns True/False (probe verdict) or None when
        the reservation was lost while waiting.
        """
        import threading

        out: Dict[str, bool] = {}

        def run() -> None:
            out["ok"] = bool(
                self._probe(timeout_s=self.device_probe_timeout_s)
            )

        th = threading.Thread(target=run, daemon=True)
        th.start()
        while th.is_alive():
            if heartbeat and not heartbeat():
                return None  # probe child dies on its own deadline
            th.join(timeout=2.0)
        return out.get("ok", False)

    def _await_device(self, heartbeat: Optional[HeartbeatFn]) -> str:
        """Probe until the backend answers; park (beating) while it won't.

        ``"ok"`` = device reachable (suspicion cleared); ``"budget"`` =
        park budget exhausted; ``"lost"`` = reservation lost meanwhile.
        """
        deadline = time.time() + self.park_max_s
        while True:
            verdict = self._probe_with_beats(heartbeat)
            if verdict is None:
                return "lost"
            if verdict:
                self._suspect_device = False
                return "ok"
            if time.time() >= deadline:
                return "budget"
            log.warning(
                "TPU backend unreachable; parking %.1fs before re-probe "
                "(not launching trials at a dead device)", self.park_poll_s,
            )
            sleep_until = time.time() + self.park_poll_s
            while time.time() < min(sleep_until, deadline):
                if heartbeat and not heartbeat():
                    return "lost"
                time.sleep(min(5.0, self.park_poll_s))

    def _acquire(
        self, trial: Trial, heartbeat: Optional[HeartbeatFn]
    ) -> Optional[SubSlice]:
        deadline = time.time() + self.allocate_timeout_s
        while time.time() < deadline:
            block = self.registry.allocate(self.n_chips, owner=trial.id)
            if block is not None:
                return block
            if heartbeat and not heartbeat():
                return None
            time.sleep(self.allocate_poll_s)
        return None
