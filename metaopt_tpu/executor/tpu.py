"""TPU executor: subprocess trials pinned to chips / ICI sub-slices.

The TPU-native replacement for the reference Consumer's "launch on whatever
GPU the script grabs" (SURVEY.md §2.7 TPU-native equivalent): each trial is
gang-scheduled onto an ICI-contiguous sub-slice via the buddy allocator, the
subprocess sees only its chips (env pinning), and the sub-slice is returned
on ANY exit path — completion, breakage, prune, or executor kill — so a
broken trial never leaks capacity.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

from metaopt_tpu.executor.base import ExecutionResult, HeartbeatFn, JudgeFn
from metaopt_tpu.executor.subproc import SubprocessExecutor
from metaopt_tpu.executor.topology import (
    ChipRegistry,
    SubSlice,
    chip_env,
    detect_slice_size,
)
from metaopt_tpu.ledger.trial import Trial
from metaopt_tpu.space.builder import CommandTemplate

log = logging.getLogger(__name__)


class TPUExecutor(SubprocessExecutor):
    def __init__(
        self,
        template: CommandTemplate,
        n_chips: int = 1,
        total_chips: Optional[int] = None,
        registry: Optional[ChipRegistry] = None,
        registry_path: Optional[str] = None,
        allocate_timeout_s: float = 600.0,
        allocate_poll_s: float = 0.5,
        **kwargs,
    ):
        # the device circuit breaker (park at a wedged backend) lives in
        # SubprocessExecutor — un-pinned relay hunts hit the identical
        # failure mode; its knobs (park_max_s, probe_fn, ...) pass
        # through **kwargs
        super().__init__(template, **kwargs)
        self.n_chips = int(n_chips)
        total = total_chips or detect_slice_size()
        # round the slice size down to a power of two for the buddy allocator
        p = 1
        while p * 2 <= total:
            p *= 2
        if registry is None and registry_path is None:
            # default to the flock'd per-host state file: every executor on
            # this host — other hunt PROCESSES and `--n-workers` threads
            # alike — must arbitrate the same physical chips, not each
            # believe the whole slice is free
            import tempfile

            registry_path = os.path.join(
                tempfile.gettempdir(), f"metaopt_tpu-chips-{p}.json"
            )
        self.registry = registry or ChipRegistry(p, state_path=registry_path)
        self.allocate_timeout_s = allocate_timeout_s
        self.allocate_poll_s = allocate_poll_s

    def execute(
        self,
        trial: Trial,
        heartbeat: Optional[HeartbeatFn] = None,
        judge: Optional[JudgeFn] = None,
    ) -> ExecutionResult:
        block = self._acquire(trial, heartbeat)
        if block is None:
            return ExecutionResult(
                "interrupted",
                note=f"no {self.n_chips}-chip sub-slice became available "
                f"within {self.allocate_timeout_s}s",
            )
        # MERGE the chip assignment — never replace the dict: the worker
        # loop persists its per-trial requeue budget in this same dict
        # (worker/loop.py), and clobbering it makes the budget infinite
        # (the exact wedge-convergence failure the breaker exists to stop)
        trial.resources.update(
            {
                "chips": block.chips,
                "slice": {"start": block.start, "size": block.size},
                "env": chip_env(block),
            }
        )
        log.debug("trial %s pinned to chips %s", trial.id[:8], block.chips)

        def beating() -> bool:
            self.registry.heartbeat(block)
            return heartbeat() if heartbeat else True

        try:
            # the inherited breaker parks/arms inside (while holding the
            # sub-slice — nothing else can use it during a wedge anyway,
            # and `beating` keeps both the reservation and the registry
            # lease alive)
            return super().execute(trial, heartbeat=beating, judge=judge)
        finally:
            self.registry.free(block)  # every exit path returns the sub-slice

    def _acquire(
        self, trial: Trial, heartbeat: Optional[HeartbeatFn]
    ) -> Optional[SubSlice]:
        deadline = time.time() + self.allocate_timeout_s
        while time.time() < deadline:
            block = self.registry.allocate(self.n_chips, owner=trial.id)
            if block is not None:
                return block
            if heartbeat and not heartbeat():
                return None
            time.sleep(self.allocate_poll_s)
        return None
