"""Benchmark tasks: standard black-box objectives with declared spaces.

ref: the reference lineage's benchmark task definitions (post-v0; the v0-era
snapshot has no benchmark module — SURVEY.md §6). Each task is a callable
objective plus a search-space declaration and a trial budget, so a
:class:`~metaopt_tpu.benchmark.Benchmark` can run algorithm comparisons
without any user script. The functions are the classic public test
objectives (Rosenbrock, Branin, Sphere, Rastrigin).

The four classics also expose a ``batch(cols)`` vectorized variant — pure
``jnp`` over ``(B,)`` columns (the :meth:`Space.stack_points` layout, or a
``(B, d)`` matrix) — so a :class:`~metaopt_tpu.executor.BatchedExecutor`
can evaluate an entire suggestion pool as one compiled device program.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Mapping

from metaopt_tpu.utils.registry import Registry

task_registry: Registry = Registry("benchmark task")


class BenchmarkTask:
    """A self-contained objective: space spec + budget + callable."""

    def __init__(self, max_trials: int = 20):
        self.max_trials = int(max_trials)

    @property
    def space(self) -> Dict[str, str]:
        raise NotImplementedError

    def __call__(self, params: Dict[str, Any]) -> List[Dict[str, Any]]:
        raise NotImplementedError

    @property
    def vectorized(self) -> bool:
        """True when this task overrides ``batch`` with a jnp column form."""
        return type(self).batch is not BenchmarkTask.batch

    def batch(self, cols):
        """Vectorized objective: ``(B,)`` columns → ``(B,)`` values."""
        raise NotImplementedError(f"{self.name} has no vectorized form")

    @property
    def name(self) -> str:
        return type(self).__name__.lower()

    @property
    def configuration(self) -> Dict[str, Any]:
        return {self.name: {"max_trials": self.max_trials}}


def _objective(value: float) -> List[Dict[str, Any]]:
    return [{"name": "objective", "type": "objective", "value": float(value)}]


def _columns(cols, names):
    """Normalize a stacked pool — ``{name: (B,)}`` dict or ``(B, d)``
    matrix — into the named column list a batch objective closes over."""
    import jax.numpy as jnp

    if isinstance(cols, Mapping):
        return [jnp.asarray(cols[n], dtype=jnp.float32) for n in names]
    mat = jnp.asarray(cols, dtype=jnp.float32)
    if mat.ndim != 2 or mat.shape[1] != len(names):
        raise ValueError(
            f"expected (B, {len(names)}) matrix or column dict, got {mat.shape}"
        )
    return [mat[:, i] for i in range(len(names))]


@task_registry.register("rosenbrock")
class RosenBrock(BenchmarkTask):
    """f(x) = Σ 100(x_{i+1} − x_i²)² + (1 − x_i)²; minimum 0 at x=1."""

    def __init__(self, max_trials: int = 30, dim: int = 2):
        super().__init__(max_trials)
        self.dim = int(dim)

    @property
    def space(self) -> Dict[str, str]:
        return {f"x{i}": "uniform(-5, 10)" for i in range(self.dim)}

    def __call__(self, params):
        x = [params[f"x{i}"] for i in range(self.dim)]
        return _objective(sum(
            100.0 * (x[i + 1] - x[i] ** 2) ** 2 + (1.0 - x[i]) ** 2
            for i in range(self.dim - 1)
        ))

    def batch(self, cols):
        x = _columns(cols, [f"x{i}" for i in range(self.dim)])
        return sum(
            100.0 * (x[i + 1] - x[i] ** 2) ** 2 + (1.0 - x[i]) ** 2
            for i in range(self.dim - 1)
        )

    @property
    def configuration(self):
        return {self.name: {"max_trials": self.max_trials, "dim": self.dim}}


@task_registry.register("branin")
class Branin(BenchmarkTask):
    """The 2-D Branin-Hoo function; global minimum ≈ 0.397887."""

    @property
    def space(self) -> Dict[str, str]:
        return {"x0": "uniform(-5, 10)", "x1": "uniform(0, 15)"}

    def __call__(self, params):
        x0, x1 = params["x0"], params["x1"]
        b = 5.1 / (4 * math.pi ** 2)
        c = 5.0 / math.pi
        s = 10.0
        t = 1.0 / (8 * math.pi)
        return _objective(
            (x1 - b * x0 ** 2 + c * x0 - 6.0) ** 2
            + s * (1 - t) * math.cos(x0) + s
        )

    def batch(self, cols):
        import jax.numpy as jnp

        x0, x1 = _columns(cols, ["x0", "x1"])
        b = 5.1 / (4 * math.pi ** 2)
        c = 5.0 / math.pi
        s = 10.0
        t = 1.0 / (8 * math.pi)
        return (
            (x1 - b * x0 ** 2 + c * x0 - 6.0) ** 2
            + s * (1 - t) * jnp.cos(x0) + s
        )


@task_registry.register("sphere")
class Sphere(BenchmarkTask):
    """f(x) = Σ x_i²; minimum 0 at the origin."""

    def __init__(self, max_trials: int = 20, dim: int = 2):
        super().__init__(max_trials)
        self.dim = int(dim)

    @property
    def space(self) -> Dict[str, str]:
        return {f"x{i}": "uniform(-5.12, 5.12)" for i in range(self.dim)}

    def __call__(self, params):
        return _objective(sum(
            params[f"x{i}"] ** 2 for i in range(self.dim)
        ))

    def batch(self, cols):
        x = _columns(cols, [f"x{i}" for i in range(self.dim)])
        return sum(c ** 2 for c in x)

    @property
    def configuration(self):
        return {self.name: {"max_trials": self.max_trials, "dim": self.dim}}


@task_registry.register("zdt1")
class ZDT1(BenchmarkTask):
    """The classic bi-objective ZDT1 trade-off (both minimized).

    f1 = x0; g = 1 + 9·mean(x1..x_{d−1}); f2 = g·(1 − √(f1/g)).
    The Pareto set is x1..x_{d−1} = 0 with x0 sweeping [0, 1]; the true
    front is f2 = 1 − √f1. ``reference_point`` bounds the attainable
    region (f1 ≤ 1, f2 ≤ 10 at d=2) so hypervolume is comparable across
    algorithms.
    """

    #: fixed box for the Hypervolume assessment
    reference_point = [1.0, 10.0]

    def __init__(self, max_trials: int = 40, dim: int = 2):
        super().__init__(max_trials)
        self.dim = int(dim)

    @property
    def space(self) -> Dict[str, str]:
        return {f"x{i}": "uniform(0, 1)" for i in range(self.dim)}

    def __call__(self, params):
        f1 = float(params["x0"])
        tail = [params[f"x{i}"] for i in range(1, self.dim)]
        g = 1.0 + 9.0 * (sum(tail) / len(tail) if tail else 0.0)
        f2 = g * (1.0 - math.sqrt(max(f1, 0.0) / g))
        return [
            {"name": "f1", "type": "objective", "value": f1},
            {"name": "f2", "type": "objective", "value": f2},
        ]

    @property
    def configuration(self):
        return {self.name: {"max_trials": self.max_trials, "dim": self.dim}}


@task_registry.register("rastrigin")
class Rastrigin(BenchmarkTask):
    """f(x) = 10d + Σ (x_i² − 10 cos 2πx_i); highly multimodal, min 0."""

    def __init__(self, max_trials: int = 30, dim: int = 2):
        super().__init__(max_trials)
        self.dim = int(dim)

    @property
    def space(self) -> Dict[str, str]:
        return {f"x{i}": "uniform(-5.12, 5.12)" for i in range(self.dim)}

    def __call__(self, params):
        return _objective(10.0 * self.dim + sum(
            params[f"x{i}"] ** 2
            - 10.0 * math.cos(2 * math.pi * params[f"x{i}"])
            for i in range(self.dim)
        ))

    def batch(self, cols):
        import jax.numpy as jnp

        x = _columns(cols, [f"x{i}" for i in range(self.dim)])
        return 10.0 * self.dim + sum(
            c ** 2 - 10.0 * jnp.cos(2 * math.pi * c) for c in x
        )

    @property
    def configuration(self):
        return {self.name: {"max_trials": self.max_trials, "dim": self.dim}}
