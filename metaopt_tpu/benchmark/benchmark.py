"""The Benchmark orchestrator: algorithms × tasks × assessments.

ref: the reference lineage's benchmark module (post-v0; SURVEY.md §6 notes
the lineage grew task definitions without published numbers). API shape
preserved — a benchmark is a named bundle of *studies* (assessment +
task), processed over a list of algorithm configurations — but execution
re-uses this framework's own machinery: each (algorithm, task, repetition)
is a real Experiment on the ledger driven by ``workon`` with the in-process
executor, so the benchmark exercises exactly the code path users run.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from metaopt_tpu.benchmark.assessments import Assessment
from metaopt_tpu.benchmark.tasks import BenchmarkTask
from metaopt_tpu.executor import InProcessExecutor
from metaopt_tpu.ledger import Experiment, MemoryLedger
from metaopt_tpu.ledger.backends import LedgerBackend
from metaopt_tpu.worker import workon

log = logging.getLogger(__name__)

AlgoSpec = Union[str, Dict[str, Any]]


def _algo_config(spec: AlgoSpec) -> Tuple[str, Dict[str, Any]]:
    if isinstance(spec, str):
        return spec, {}
    (name, kwargs), = spec.items()
    return name, dict(kwargs or {})


class Study:
    """One assessment applied to one task across all algorithms."""

    def __init__(self, assessment: Assessment, task: BenchmarkTask):
        self.assessment = assessment
        self.task = task
        #: series key -> list (one per repetition) of regret series. The
        #: key is the algorithm name — suffixed ``@wN`` when the
        #: assessment runs multiple worker counts (ParallelAssessment)
        self.series: Dict[str, List[List[float]]] = {}
        #: series key -> wall-clock seconds per repetition
        self.walls: Dict[str, List[float]] = {}

    def record(self, key: str, series: List[float],
               wall_s: Optional[float] = None) -> None:
        self.series.setdefault(key, []).append(series)
        if wall_s is not None:
            self.walls.setdefault(key, []).append(wall_s)

    def analyze(self) -> Dict[str, Any]:
        extra = (
            {"walls": self.walls}
            if getattr(self.assessment, "wants_walls", False) else {}
        )
        return {
            "task": self.task.name,
            "task_config": self.task.configuration,
            **self.assessment.analyze(self.series, **extra),
        }


class Benchmark:
    """Compare algorithms over task/assessment studies.

    >>> bench = Benchmark(
    ...     "demo",
    ...     algorithms=["random", {"tpe": {"n_initial": 5}}],
    ...     targets=[{"assess": [AverageResult(3)], "task": [RosenBrock(25)]}],
    ... )
    >>> bench.process()
    >>> bench.analysis()
    """

    def __init__(
        self,
        name: str,
        algorithms: Sequence[AlgoSpec],
        targets: Sequence[Dict[str, Sequence[Any]]],
        ledger: Optional[LedgerBackend] = None,
    ):
        self.name = name
        self.algorithms = list(algorithms)
        self.ledger = ledger if ledger is not None else MemoryLedger()
        self.studies: List[Study] = []
        for target in targets:
            for assessment in target["assess"]:
                for task in target["task"]:
                    self.studies.append(Study(assessment, task))
        self._processed = False

    # -- execution ---------------------------------------------------------
    def _run_one(
        self, study: Study, algo_name: str, algo_kwargs: Dict[str, Any],
        repetition: int, n_workers: int = 1,
    ) -> Tuple[List[float], float]:
        from metaopt_tpu.space import build_space

        exp_name = (
            f"{self.name}-{study.task.name}-{study.assessment.name}-"
            f"{algo_name}-rep{repetition}"
        )
        if n_workers != 1:
            exp_name += f"-w{n_workers}"
        kwargs = dict(algo_kwargs)
        kwargs.setdefault("seed", repetition)
        exp = Experiment(
            exp_name,
            self.ledger,
            space=build_space(study.task.space),
            algorithm={algo_name: kwargs},
            max_trials=study.task.max_trials,
            pool_size=1,
            metadata={"benchmark": self.name},
        ).configure()
        t0 = time.perf_counter()
        if n_workers == 1:
            workon(exp, InProcessExecutor(study.task), worker_id=exp_name)
        else:
            # N full workon loops racing one shared ledger — the same
            # async-suggestion semantics as `hunt --n-workers` (each loop
            # has its own Experiment handle; the reserve CAS arbitrates).
            # Deliberately simpler than the CLI's thread loop
            # (cli/main.py::_cmd_hunt): in-process tasks need no
            # stop_event wind-down, per-thread executors, or coord
            # socket handling
            import threading

            errors: Dict[int, str] = {}

            def run(i: int) -> None:
                try:
                    w_exp = Experiment(exp_name, self.ledger).configure()
                    workon(w_exp, InProcessExecutor(study.task),
                           worker_id=f"{exp_name}-w{i}")
                except BaseException as err:  # must surface, not vanish
                    errors[i] = f"{type(err).__name__}: {err}"

            threads = [threading.Thread(target=run, args=(i,), daemon=True)
                       for i in range(n_workers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errors:
                raise RuntimeError(
                    f"benchmark worker(s) died: {errors}"
                )
        wall_s = time.perf_counter() - t0
        # the assessment owns what "progress" means: best-so-far objective
        # by default, hypervolume-so-far for multi-objective studies
        return (
            study.assessment.series(self.ledger, exp_name, task=study.task),
            wall_s,
        )

    def process(self) -> None:
        """Run every (study × algorithm × repetition [× workers]) run."""
        t0 = time.perf_counter()
        for study in self.studies:
            raw = getattr(study.assessment, "worker_counts", None)
            counts = raw or [1]
            # an assessment that DECLARES worker counts always gets @wN
            # keys (its analyze parses them), even for worker_counts=[1]
            multi = raw is not None
            for spec in self.algorithms:
                algo_name, algo_kwargs = _algo_config(spec)
                for rep in range(study.assessment.repetitions):
                    for nw in counts:
                        series, wall_s = self._run_one(
                            study, algo_name, algo_kwargs, rep, nw
                        )
                        key = (f"{algo_name}@w{nw}" if multi else algo_name)
                        study.record(key, series, wall_s=wall_s)
                        log.info(
                            "benchmark %s: %s/%s/%s rep %d w%d -> best %s "
                            "(%.1fs)",
                            self.name, study.task.name,
                            study.assessment.name, algo_name, rep, nw,
                            series[-1] if series else None, wall_s,
                        )
        self._processed = True
        log.info("benchmark %s processed in %.1fs",
                 self.name, time.perf_counter() - t0)

    # -- results -----------------------------------------------------------
    def analysis(self) -> List[Dict[str, Any]]:
        if not self._processed:
            raise RuntimeError("call process() before analysis()")
        return [s.analyze() for s in self.studies]

    @property
    def configuration(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "algorithms": self.algorithms,
            "studies": [
                {"task": s.task.configuration,
                 "assessment": s.assessment.configuration}
                for s in self.studies
            ],
        }
