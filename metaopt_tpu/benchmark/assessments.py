"""Benchmark assessments: how algorithm comparisons are scored.

ref: the reference lineage's assessment classes (post-v0). An assessment
consumes the per-repetition regret series the Benchmark collected and
produces a JSON-able analysis table.
"""

from __future__ import annotations

from typing import Any, Dict, List


class Assessment:
    """Turns {algorithm: [series per repetition]} into an analysis dict.

    A series is one float per completed-trial index (one list per
    repetition). What that float *is* belongs to the assessment:
    best-so-far objective by default (:meth:`series`), hypervolume-so-far
    for :class:`Hypervolume`.
    """

    #: how many independent repetitions the benchmark should run
    repetitions: int = 1

    def series(self, ledger, exp_name: str, task=None) -> List[float]:
        """Extract one repetition's progress series from the ledger."""
        from metaopt_tpu.io.webapi import regret_series

        return [p["best"] for p in regret_series(ledger, exp_name)]

    def analyze(
        self, series: Dict[str, List[List[float]]]
    ) -> Dict[str, Any]:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__.lower()

    @property
    def configuration(self) -> Dict[str, Any]:
        return {self.name: {"repetitions": self.repetitions}}


def _mean_curves(runs: List[List[float]]) -> List[float]:
    """Element-wise mean over repetitions, up to the shortest run."""
    if not runs:
        return []
    n = min(len(r) for r in runs)
    return [sum(r[i] for r in runs) / len(runs) for i in range(n)]


class AverageResult(Assessment):
    """Mean best-so-far objective per trial index, per algorithm."""

    def __init__(self, repetitions: int = 3):
        self.repetitions = int(repetitions)

    def analyze(self, series):
        curves = {algo: _mean_curves(runs) for algo, runs in series.items()}
        final = {
            algo: (curve[-1] if curve else None)
            for algo, curve in curves.items()
        }
        ranked = sorted(
            (a for a, v in final.items() if v is not None), key=final.get
        )
        return {
            "assessment": "averageresult",
            "repetitions": self.repetitions,
            "curves": curves,
            "final_best": final,
            "winner": ranked[0] if ranked else None,
        }


def hypervolume_2d(points: List[List[float]],
                   reference: List[float]) -> float:
    """Exact 2-D hypervolume dominated by ``points`` w.r.t. ``reference``.

    Both objectives minimized; points at or beyond the reference
    contribute nothing. O(n log n): sort the nondominated set by f1 and
    sum the staircase slabs.
    """
    r1, r2 = float(reference[0]), float(reference[1])
    pts = sorted((float(p[0]), float(p[1])) for p in points
                 if p[0] < r1 and p[1] < r2)
    hv = 0.0
    best_f2 = r2  # f2 level of the staircase so far
    for f1, f2 in pts:  # ascending f1: only improving f2 adds area
        if f2 < best_f2:
            hv += (r1 - f1) * (best_f2 - f2)
            best_f2 = f2
    return hv


class Hypervolume(Assessment):
    """Mean hypervolume-so-far per trial index (multi-objective studies).

    The series value at index i is the hypervolume of the nondominated
    set of the first i+1 completed trials, w.r.t. a fixed reference
    point — the task's declared ``reference_point`` (so every algorithm
    in a study is scored against the same box) unless one is given here.
    HIGHER is better; `winner` is the argmax of the final mean HV.
    Exact 2-D computation; tasks with more objectives are scored on
    their first two.
    """

    def __init__(self, repetitions: int = 3,
                 reference_point: List[float] = None):
        self.repetitions = int(repetitions)
        self.reference_point = reference_point
        #: the box actually used (task-declared when ours is None) —
        #: recorded so the report never claims "reference_point": null
        #: for numbers that are meaningless without it
        self._resolved_reference: List[float] = reference_point

    def resolve_reference(self, task=None) -> List[float]:
        ref = self.reference_point
        if ref is None:
            ref = getattr(task, "reference_point", None)
        if ref is None:
            raise ValueError(
                "Hypervolume needs a reference_point (on the assessment "
                f"or the task; {getattr(task, 'name', task)!r} declares "
                "none)"
            )
        return list(ref)

    def series(self, ledger, exp_name: str, task=None) -> List[float]:
        from metaopt_tpu.io.webapi import completed_in_order

        ref = self.resolve_reference(task)
        self._resolved_reference = ref
        out, pts = [], []
        for t in completed_in_order(ledger, exp_name):
            if len(t.objectives) < 2:
                continue
            pts.append(t.objectives[:2])
            out.append(hypervolume_2d(pts, ref))
        return out

    def analyze(self, series):
        curves = {algo: _mean_curves(runs) for algo, runs in series.items()}
        final = {algo: (curve[-1] if curve else None)
                 for algo, curve in curves.items()}
        ranked = sorted((a for a, v in final.items() if v is not None),
                        key=final.get, reverse=True)  # higher HV wins
        return {
            "assessment": "hypervolume",
            "repetitions": self.repetitions,
            "reference_point": self._resolved_reference,
            "curves": curves,
            "final_hypervolume": final,
            "winner": ranked[0] if ranked else None,
        }

    @property
    def configuration(self):
        return {self.name: {"repetitions": self.repetitions,
                            "reference_point": self.reference_point}}


class ParallelAssessment(Assessment):
    """How an algorithm holds up when N workers race one experiment.

    ref: the lineage's ParallelAssessment — same trial budget, executed by
    1 vs N concurrent workers against one shared ledger. Two questions:

    - **quality**: asynchronous suggestion means later points are chosen
      with stale observations (suggest happens while N−1 evaluations are
      still in flight) — how much final regret does that cost?
    - **throughput**: wall-clock speedup (and efficiency = speedup/N)
      from the coordination plane. With in-process numpy tasks the GIL
      bounds raw speedup; the number is still the honest cost of the
      reserve/observe contention the workers actually experience.

    The Benchmark runs each (algorithm, repetition) once per entry in
    ``worker_counts``, recording series under ``algo@wN`` keys and wall
    times alongside.
    """

    wants_walls = True

    def __init__(self, repetitions: int = 2,
                 worker_counts: List[int] = (1, 4)):
        self.repetitions = int(repetitions)
        # dedup: a repeated count would rebuild the SAME experiment name,
        # join the finished run, and record a ~0s wall that fakes speedup
        self.worker_counts = sorted({int(n) for n in worker_counts})
        if any(n < 1 for n in self.worker_counts):
            raise ValueError("worker_counts must be >= 1")

    @staticmethod
    def _split(key: str):
        algo, _, w = key.rpartition("@w")
        return algo, int(w)

    def analyze(self, series, walls=None):
        walls = walls or {}
        per_algo: Dict[str, Dict[int, Dict[str, Any]]] = {}
        for key, runs in series.items():
            algo, nw = self._split(key)
            curves = _mean_curves(runs)
            wall_list = walls.get(key) or []
            per_algo.setdefault(algo, {})[nw] = {
                "final_best": curves[-1] if curves else None,
                "mean_wall_s": (round(sum(wall_list) / len(wall_list), 3)
                                if wall_list else None),
            }
        table: Dict[str, Any] = {}
        for algo, by_n in per_algo.items():
            base = by_n.get(1) or {}
            rows = {}
            for nw in sorted(by_n):
                row = dict(by_n[nw])
                if nw != 1 and base.get("mean_wall_s") and row["mean_wall_s"]:
                    sp = base["mean_wall_s"] / row["mean_wall_s"]
                    row["speedup_vs_1w"] = round(sp, 2)
                    row["efficiency"] = round(sp / nw, 2)
                if nw != 1 and base.get("final_best") is not None \
                        and row["final_best"] is not None:
                    row["regret_penalty_vs_1w"] = (
                        row["final_best"] - base["final_best"]
                    )
                rows[f"w{nw}"] = row
            table[algo] = rows
        top_n = max(self.worker_counts)
        finals = {
            a: rows.get(f"w{top_n}", {}).get("final_best")
            for a, rows in table.items()
        }
        ranked = sorted((a for a, v in finals.items() if v is not None),
                        key=finals.get)
        return {
            "assessment": "parallelassessment",
            "repetitions": self.repetitions,
            "worker_counts": self.worker_counts,
            "algorithms": table,
            "winner": ranked[0] if ranked else None,
        }

    @property
    def configuration(self):
        return {self.name: {"repetitions": self.repetitions,
                            "worker_counts": self.worker_counts}}


class AverageRank(Assessment):
    """Mean rank (1 = best) of each algorithm across repetitions.

    Ranks are computed per repetition on the final best objective, so an
    algorithm that wins most seeds ranks near 1 even if another wins big
    on one lucky seed.
    """

    def __init__(self, repetitions: int = 3):
        self.repetitions = int(repetitions)

    def analyze(self, series):
        algos = [a for a, runs in series.items() if runs]
        if not algos:
            return {"assessment": "averagerank", "ranks": {}, "winner": None}
        reps = min(len(series[a]) for a in algos)
        totals = {a: 0.0 for a in algos}
        for rep in range(reps):
            finals = {a: series[a][rep][-1] for a in algos if series[a][rep]}
            order = sorted(finals, key=finals.get)
            for rank, a in enumerate(order, start=1):
                totals[a] += rank
            for a in algos:  # no completed trials this rep = worst rank
                if a not in finals:
                    totals[a] += len(algos)
        ranks = {a: (totals[a] / reps if reps else None) for a in algos}
        ranked = sorted(ranks, key=ranks.get)
        return {
            "assessment": "averagerank",
            "repetitions": reps,
            "ranks": ranks,
            "winner": ranked[0] if ranked else None,
        }
