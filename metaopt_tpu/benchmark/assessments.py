"""Benchmark assessments: how algorithm comparisons are scored.

ref: the reference lineage's assessment classes (post-v0). An assessment
consumes the per-repetition regret series the Benchmark collected and
produces a JSON-able analysis table.
"""

from __future__ import annotations

from typing import Any, Dict, List


class Assessment:
    """Turns {algorithm: [series per repetition]} into an analysis dict.

    A series is the best-so-far objective per completed-trial index (one
    list per repetition, produced by the Benchmark's runs).
    """

    #: how many independent repetitions the benchmark should run
    repetitions: int = 1

    def analyze(
        self, series: Dict[str, List[List[float]]]
    ) -> Dict[str, Any]:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__.lower()

    @property
    def configuration(self) -> Dict[str, Any]:
        return {self.name: {"repetitions": self.repetitions}}


def _mean_curves(runs: List[List[float]]) -> List[float]:
    """Element-wise mean over repetitions, up to the shortest run."""
    if not runs:
        return []
    n = min(len(r) for r in runs)
    return [sum(r[i] for r in runs) / len(runs) for i in range(n)]


class AverageResult(Assessment):
    """Mean best-so-far objective per trial index, per algorithm."""

    def __init__(self, repetitions: int = 3):
        self.repetitions = int(repetitions)

    def analyze(self, series):
        curves = {algo: _mean_curves(runs) for algo, runs in series.items()}
        final = {
            algo: (curve[-1] if curve else None)
            for algo, curve in curves.items()
        }
        ranked = sorted(
            (a for a, v in final.items() if v is not None), key=final.get
        )
        return {
            "assessment": "averageresult",
            "repetitions": self.repetitions,
            "curves": curves,
            "final_best": final,
            "winner": ranked[0] if ranked else None,
        }


class AverageRank(Assessment):
    """Mean rank (1 = best) of each algorithm across repetitions.

    Ranks are computed per repetition on the final best objective, so an
    algorithm that wins most seeds ranks near 1 even if another wins big
    on one lucky seed.
    """

    def __init__(self, repetitions: int = 3):
        self.repetitions = int(repetitions)

    def analyze(self, series):
        algos = [a for a, runs in series.items() if runs]
        if not algos:
            return {"assessment": "averagerank", "ranks": {}, "winner": None}
        reps = min(len(series[a]) for a in algos)
        totals = {a: 0.0 for a in algos}
        for rep in range(reps):
            finals = {a: series[a][rep][-1] for a in algos if series[a][rep]}
            order = sorted(finals, key=finals.get)
            for rank, a in enumerate(order, start=1):
                totals[a] += rank
            for a in algos:  # no completed trials this rep = worst rank
                if a not in finals:
                    totals[a] += len(algos)
        ranks = {a: (totals[a] / reps if reps else None) for a in algos}
        ranked = sorted(ranks, key=ranks.get)
        return {
            "assessment": "averagerank",
            "repetitions": reps,
            "ranks": ranks,
            "winner": ranked[0] if ranked else None,
        }
