"""Benchmark suite: compare algorithms over standard tasks.

ref: the reference lineage's benchmark module (task + assessment +
benchmark orchestration; post-v0 — SURVEY.md §6 records that the lineage
grew benchmark *definitions* without published numbers). The five graded
BASELINE configs live separately in ``benchmarks/run.py``; this package is
the library API for user-defined algorithm comparisons.
"""

from metaopt_tpu.benchmark.assessments import (
    Assessment,
    AverageRank,
    AverageResult,
    Hypervolume,
    ParallelAssessment,
    hypervolume_2d,
)
from metaopt_tpu.benchmark.benchmark import Benchmark, Study
from metaopt_tpu.benchmark.tasks import (
    BenchmarkTask,
    Branin,
    ZDT1,
    Rastrigin,
    RosenBrock,
    Sphere,
    task_registry,
)

__all__ = [
    "Assessment",
    "AverageRank",
    "AverageResult",
    "Hypervolume",
    "ParallelAssessment",
    "hypervolume_2d",
    "Benchmark",
    "BenchmarkTask",
    "Branin",
    "Rastrigin",
    "RosenBrock",
    "Sphere",
    "ZDT1",
    "Study",
    "task_registry",
]
