"""CLI implementation.

ref: src/metaopt/core/cli/ (SURVEY.md §2.5, §3.1): parse argv → resolve
config → build space from the user command → configure experiment → workon.
Everything after the user script path is the script's own command line, with
``~priors`` marking searchable arguments.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time
from typing import Any, Dict, List, Optional

from metaopt_tpu.executor import SubprocessExecutor
from metaopt_tpu.io.resolve_config import resolve_config
from metaopt_tpu.ledger import Experiment, Trial
from metaopt_tpu.ledger.backends import make_ledger
from metaopt_tpu.space import SpaceBuilder
from metaopt_tpu.utils.fsjournal import fsync_dir
from metaopt_tpu.worker import workon

log = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mtpu",
        description="TPU-native asynchronous hyperparameter optimization",
    )
    p.add_argument("-v", "--verbose", action="count", default=0)
    sub = p.add_subparsers(dest="command", required=True)

    def common(sp):
        sp.add_argument("-n", "--name", help="experiment name")
        sp.add_argument("--config", help="framework config YAML")
        sp.add_argument("--algo", default=None,
                        help="algorithm name with default settings — the "
                             "no-YAML shortcut for `algorithm: {NAME: {}}` "
                             "(e.g. --algo tpe | gp | asha)")
        sp.add_argument("--max-trials", type=int, dest="max_trials")
        sp.add_argument("--pool-size", type=int, dest="pool_size")
        sp.add_argument(
            "--ledger",
            help="ledger spec: 'memory', a dir path (native engine preferred), 'native:<dir>', 'file:<dir>', or 'coord://host:port'",
        )

    hunt = sub.add_parser("hunt", help="run the optimization loop")
    common(hunt)
    hunt.add_argument("--worker-trials", type=int, dest="worker_trials")
    hunt.add_argument("--worker-id", default=None)
    hunt.add_argument("--n-workers", type=int, dest="n_workers", default=1,
                      help="parallel workers in this process (each runs the "
                           "full produce/reserve/execute loop; trials are "
                           "subprocesses, so N trials run concurrently)")
    hunt.add_argument("--exp-max-broken", type=int, default=None,
                      help="abort after this many broken trials")
    hunt.add_argument("--working-dir")
    hunt.add_argument("--n-chips", type=int, default=None,
                      help="TPU chips per trial (enables the TPU executor)")
    hunt.add_argument("--timeout-s", type=float, default=None,
                      help="per-trial wall-clock timeout")
    hunt.add_argument("--warm-start", dest="warm_start", default=None,
                      help="observe another experiment's completed trials "
                           "into this experiment's algorithm before "
                           "suggesting (same ledger)")
    hunt.add_argument("--branch-from", dest="branch_from", default=None,
                      help="EVC: create this experiment as a child of "
                           "another; the parent's completed trials are "
                           "adapted into the (possibly changed) space and "
                           "observed before suggesting")
    hunt.add_argument("--on-conflict", dest="on_conflict", default=None,
                      choices=["adopt", "fail", "branch"],
                      help="what to do when the command's ~priors (or "
                           "--algo) differ from the stored experiment: "
                           "adopt = warn and defer to the stored config "
                           "(the reference's joiner semantics, default); "
                           "fail = stop; branch = EVC auto-resolution — "
                           "create NAME-vN branched from the latest "
                           "version (rerunning the same changed command "
                           "joins the branch it already created)")
    hunt.add_argument("--branch-default", dest="branch_default",
                      action="append", metavar="NAME=VALUE",
                      help="value backfilled into parent trials for a "
                           "dimension the child space added (repeatable)")
    hunt.add_argument("--branch-rename", dest="branch_rename",
                      action="append", metavar="OLD=NEW",
                      help="carry parent dimension OLD into child "
                           "dimension NEW (repeatable)")
    hunt.add_argument("--producer", default=None, choices=["local", "coord"],
                      help="where suggestion runs: 'local' fits the algorithm "
                           "in this worker; 'coord' delegates to the "
                           "coordinator's single hosted instance "
                           "(coord:// ledger only)")
    hunt.add_argument("--profile-dir", default=None,
                      help="capture per-trial jax.profiler traces here "
                           "(scripts opt in with `with client.profiled():`)")
    hunt.add_argument("--ckpt-root", dest="ckpt_root", default=None,
                      help="checkpoint root for PBT weight handoff "
                           "(scripts resolve it via "
                           "client.checkpoint_paths())")
    hunt.add_argument("--jax-cache", dest="jax_cache", default=None,
                      help="persistent XLA compilation cache dir shared by "
                           "all trials: trial N reuses trial 1's compile "
                           "(don't share the dir across heterogeneous "
                           "hosts)")
    hunt.add_argument("--batch-size", dest="batch_size", default=None,
                      help="evaluate pools of this many trials as ONE "
                           "jitted vmap program (needs --vector-objective; "
                           "'auto' sizes pools from the algorithm's "
                           "population cohort)")
    hunt.add_argument("--vector-objective", dest="vector_objective",
                      default=None,
                      help="named vectorized in-process objective for the "
                           "batched hunt: a benchmark task with a batch() "
                           "form (rosenbrock/branin/sphere/rastrigin) or "
                           "'mlp' (the vmapped zoo train objective); "
                           "without a user command the space comes from "
                           "the objective")
    hunt.add_argument("cmd", nargs=argparse.REMAINDER,
                      help="user script and its args with ~priors")

    init = sub.add_parser("init-only", help="create the experiment and exit")
    common(init)
    init.add_argument("--on-conflict", dest="on_conflict", default=None,
                      choices=["adopt", "fail", "branch"])
    init.add_argument("--branch-from", dest="branch_from", default=None)
    init.add_argument("--branch-rename", dest="branch_rename",
                      action="append", metavar="OLD=NEW")
    init.add_argument("--branch-default", dest="branch_default",
                      action="append", metavar="NAME=VALUE")
    init.add_argument("cmd", nargs=argparse.REMAINDER)

    ins = sub.add_parser("insert", help="manually register a trial")
    common(ins)
    ins.add_argument("--params", required=True,
                     help='JSON dict of param values, e.g. \'{"x": 1.5}\'')

    res = sub.add_parser("resume",
                         help="flip parked trials back to new (reservable)")
    common(res)
    res.add_argument("--trial-id", default=None,
                     help="resume one trial (default: all matching)")
    res.add_argument("--statuses", default="suspended",
                     help="comma list of statuses to revive (from "
                          "suspended/interrupted/broken; default "
                          "suspended). Interrupted trials' params stay "
                          "registered, so deterministic algorithms can't "
                          "re-suggest them — reviving is the only retry "
                          "path.")

    ls = sub.add_parser("list", help="list experiments on the ledger")
    ls.add_argument("--config", help="framework config YAML")
    ls.add_argument(
        "--ledger",
        help="ledger spec: 'memory', a dir path (native engine preferred), 'native:<dir>', 'file:<dir>', "
             "or coord://host:port",
    )
    ls.add_argument("--json", action="store_true", dest="as_json")

    tn = sub.add_parser(
        "tenants",
        help="multi-tenant service stats from a coordinator: per-tenant "
             "produce grants/denials and weights, fleet residency "
             "(resident/evicted/hydrations), and optionally per-"
             "experiment status counts — evicted experiments answered "
             "from their stub index, never hydrated",
    )
    tn.add_argument("--config", help="framework config YAML")
    tn.add_argument("--ledger", help="coord://host:port of the deployment")
    tn.add_argument("--experiments", action="store_true",
                    help="include per-experiment status counts")
    tn.add_argument("--json", action="store_true", dest="as_json")

    info = sub.add_parser("info", help="full experiment document + stats")
    common(info)
    info.add_argument("--json", action="store_true", dest="as_json")

    plot = sub.add_parser("plot", help="optimization diagnostics")
    plot.add_argument("kind",
                      choices=["regret", "lcurve", "parallel", "importance",
                               "pdp",
                               "pareto"],
                      help="regret: best-objective-so-far per completed "
                           "trial; lcurve: objective vs fidelity budget per "
                           "lineage (multi-fidelity experiments); parallel: "
                           "parallel-coordinates data (params + objective "
                           "per completed trial, JSON); importance: "
                           "per-parameter importance from a fitted ARD GP "
                           "surrogate (the lineage's LPI role); pdp: 1-D "
                           "partial dependence of each parameter under "
                           "the same surrogate; pareto: "
                           "nondominated front over the trials' objective "
                           "vectors (multi-objective experiments)")
    common(plot)
    plot.add_argument("--json", action="store_true", dest="as_json")

    st = sub.add_parser("status", help="show experiment state")
    common(st)
    st.add_argument("--json", action="store_true", dest="as_json")
    st.add_argument("--rungs", action="store_true",
                    help="rung occupancy for multi-fidelity algorithms "
                         "(replays completed trials into the algorithm)")
    st.add_argument("--workers", action="store_true",
                    help="per-worker liveness derived from trial "
                         "ownership + heartbeats (who holds what, last "
                         "seen when)")

    db = sub.add_parser("db", help="ledger backend utilities")
    db.add_argument("action", choices=["test", "rm", "compact", "dump",
                                       "load", "set", "release"],
                    help="test: drive the full backend contract (create, "
                         "dup-detect, reserve CAS, heartbeat, stale "
                         "release) against the configured ledger; "
                         "rm: delete an experiment and its trials; "
                         "compact: rewrite a native ledger's append-only "
                         "log to its live state (reclaims heartbeat spam), "
                         "or fold a file ledger's index log into its "
                         "snapshot; "
                         "dump: archive experiments + trials to portable "
                         "JSON; load: restore an archive into the "
                         "configured ledger; "
                         "set: edit experiment fields (max_trials=N, "
                         "pool_size=N) or, with --trial, force a trial's "
                         "status; release: free reserved trials back to "
                         "'new' immediately (instead of waiting for the "
                         "stale-heartbeat sweep)")
    db.add_argument("-n", "--name",
                    help="experiment to delete (rm) / archive (dump; "
                         "default all)")
    db.add_argument("--force", action="store_true",
                    help="rm: required to actually delete")
    db.add_argument("-o", "--output",
                    help="dump: write the archive here (default stdout)")
    db.add_argument("--file", help="load: the archive file to restore")
    db.add_argument("--resolve", choices=["fail", "ignore", "overwrite",
                                          "bump"], default="fail",
                    help="load: name-collision policy — fail (default), "
                         "ignore (skip existing), overwrite (replace doc + "
                         "trials), bump (load as NAME-vN with version+1 and "
                         "parent set, the EVC-style sibling)")
    db.add_argument("--trial", dest="trial_id", default=None,
                    help="set/release: act on one trial (id prefix ok)")
    db.add_argument("assignments", nargs="*", metavar="KEY=VALUE",
                    help="set: fields to change")
    db.add_argument("--json", action="store_true", dest="as_json",
                    help="test: emit the check report as JSON")
    db.add_argument("--config", help="framework config YAML")
    db.add_argument("--ledger",
                    help="ledger spec: 'memory', a dir path (native engine preferred), 'native:<dir>', 'file:<dir>', "
                         "or coord://host:port")

    web = sub.add_parser(
        "web", help="read-only REST API over the ledger (dashboards)"
    )
    web.add_argument("--config", help="framework config YAML")
    web.add_argument("--ledger",
                     help="ledger spec: 'memory', a dir path (native engine preferred), 'native:<dir>', 'file:<dir>', "
                          "or coord://host:port")
    web.add_argument("--host", default="127.0.0.1")
    web.add_argument("--port", type=int, default=0,
                     help="0 binds an ephemeral port (printed at startup)")

    bm = sub.add_parser(
        "benchmark",
        help="compare algorithms on standard tasks (benchmark studies)",
    )
    bm.add_argument("--algos", nargs="+", default=["random", "tpe"],
                    help="algorithm names, e.g. --algos random tpe gp")
    bm.add_argument("--task", default="rosenbrock",
                    help="benchmark task (rosenbrock/branin/sphere/"
                         "rastrigin/zdt1)")
    bm.add_argument("--max-trials", type=int, default=25,
                    help="trial budget per repetition")
    bm.add_argument("--repetitions", type=int, default=3)
    bm.add_argument("--assessment", choices=("result", "rank",
                                             "hypervolume", "parallel"),
                    default="result",
                    help="result = mean best-so-far; rank = mean final "
                         "rank; hypervolume = mean dominated hypervolume "
                         "(multi-objective tasks, e.g. zdt1); parallel = "
                         "same trial budget under 1 vs N racing workers "
                         "(async-suggestion quality cost + wall-clock "
                         "speedup)")
    bm.add_argument("--workers", nargs="+", type=int, default=(1, 4),
                    metavar="N",
                    help="parallel assessment: worker counts to compare")
    bm.add_argument("--json", dest="as_json", action="store_true")

    srv = sub.add_parser(
        "serve", help="run the pod coordinator (single-writer ledger service)"
    )
    srv.add_argument("--config", help="framework config YAML")
    srv.add_argument("--host", default=None,
                     help="bind address (default: config coordinator.host)")
    srv.add_argument("--port", type=int, default=None,
                     help="0 binds an ephemeral port (printed at startup)")
    srv.add_argument("--ledger", default=None,
                     help="inner backing store: 'memory' or a directory path")
    srv.add_argument("--snapshot", dest="snapshot_path", default=None,
                     help="snapshot file for crash/resume")
    srv.add_argument("--snapshot-interval-s", type=float, default=30.0)
    srv.add_argument("--snapshot-full", dest="snapshot_full",
                     action="store_true",
                     help="force full (v1) snapshots: every experiment's "
                          "whole doc set reserialized each time, no "
                          "segment files (default: incremental v2 "
                          "manifests — sealed archive segments written "
                          "once under <snapshot>.segments/, only dirty "
                          "experiments re-captured)")
    srv.add_argument("--archive-segment-rows", dest="archive_segment_rows",
                     type=int, default=None, metavar="N",
                     help="completed-trial archive segment size: completed "
                          "trials seal into immutable columnar segments "
                          "of N rows (default 4096) — flat RSS per trial "
                          "and O(dirty) incremental snapshots at "
                          "million-trial scale")
    srv.add_argument("--no-trial-archive", dest="trial_archive",
                     action="store_false", default=True,
                     help="keep completed trials as resident Trial "
                          "objects instead of sealing them into the "
                          "columnar archive (debugging escape hatch; "
                          "RSS grows with every completion)")
    srv.add_argument("--stale-timeout-s", type=float, default=120.0,
                     help="pacemaker: re-free reservations idle this long")
    srv.add_argument("--event-log", dest="event_log_path", default=None,
                     help="JSONL event log path")
    srv.add_argument("--suggest-prefetch-depth", dest="suggest_prefetch_depth",
                     type=int, default=None,
                     help="speculative pools hosted algorithms keep banked "
                          "so produce legs answer from memory (default 1 = "
                          "refill-when-stale)")
    srv.add_argument("--uds", dest="uds_path", default=None, metavar="PATH",
                     help="also listen on a Unix domain socket at PATH — "
                          "the same-host fast path; the ping reply "
                          "advertises it and pod-local clients prefer it "
                          "over TCP automatically")
    srv.add_argument("--shards", type=int, default=None, metavar="N",
                     help="sharded serving: run N coordinator shard "
                          "subprocesses (consistent-hash ownership by "
                          "experiment, one WAL+snapshot each) behind a "
                          "router on the public port; --snapshot then "
                          "names a DIRECTORY (one snapshot+WAL per shard)")
    srv.add_argument("--max-experiments", type=int, default=None,
                     help="admission control: reject register_experiment "
                          "past this fleet-wide count (per shard when "
                          "--shards is set)")
    srv.add_argument("--max-experiments-per-tenant", type=int, default=None,
                     help="admission control: per-tenant experiment quota "
                          "(experiments carry a 'tenant' config key; "
                          "unset = 'default')")
    srv.add_argument("--evict-idle-s", type=float, default=None,
                     help="evict experiments idle this long to crash-"
                          "atomic evict files (stub stays resident: "
                          "status counts served without hydration; first "
                          "touch restores bit-identically)")
    srv.add_argument("--max-resident", type=int, default=None,
                     help="LRU residency budget: keep at most this many "
                          "experiments hydrated (requires --snapshot "
                          "for the evict directory)")
    srv.add_argument("--tenant-weights", default=None, metavar="JSON",
                     help="fair produce scheduling weights, e.g. "
                          '\'{"acme": 3, "batch": 1}\' — deficit '
                          "round-robin shares of produce capacity "
                          "(unlisted tenants weigh 1.0)")
    srv.add_argument("--fuse-suggest", dest="fuse_suggest",
                     action="store_true", default=None,
                     help="fleet-fused suggest plane: batch compatible "
                          "resident experiments' acquisition launches "
                          "into ONE vmapped kernel per shape bucket each "
                          "tick, feeding their prefetch pools off the "
                          "reply path (suggestions stay bit-identical "
                          "to the per-experiment path)")
    srv.add_argument("--fuse-bucket-max", dest="fuse_bucket_max",
                     type=int, default=None, metavar="N",
                     help="max experiments fused into one bucket launch "
                          "(rounded down to a power of two; default 32 "
                          "— bounds worst-case launch latency and "
                          "per-bucket device memory)")

    reb = sub.add_parser(
        "rebalance",
        help="live-migrate one experiment to another coordinator shard "
             "(zero acked-write loss; see ARCHITECTURE.md hand-off "
             "protocol)",
    )
    reb.add_argument("--coord", required=True, metavar="HOST:PORT",
                     help="any address of the sharded deployment (the "
                          "public/router address or any shard) — the "
                          "shard map is learned from its ping")
    reb.add_argument("--experiment", required=True,
                     help="experiment to move")
    reb.add_argument("--dest", required=True, metavar="SHARD_ID",
                     help="destination shard id (e.g. s1)")
    reb.add_argument("--drain-timeout-s", type=float, default=10.0,
                     help="max wait for the experiment's in-flight ops "
                          "to drain on the source")
    reb.add_argument("--window-s", type=float, default=30.0,
                     help="per-step retry window through shard restarts")

    sim = sub.add_parser(
        "simulate",
        help="discrete-event scale certification: drive the real "
             "coordinator (WAL, snapshots, hosted ASHA/hyperband, fair "
             "scheduler) with N simulated workers on a virtual clock and "
             "certify promotion invariants, zero acked-write loss, and "
             "tenant fairness under an injected fault schedule",
    )
    sim.add_argument("--workers", type=int, default=1000,
                     help="simulated worker count (100000 = the pod-scale "
                          "certification run; finishes in ~1 min wall)")
    sim.add_argument("--seed", type=int, default=0,
                     help="master seed: same seed → byte-identical event "
                          "log (the digest is printed for comparison)")
    sim.add_argument("--faults", default=None, metavar="SPEC",
                     help="fault schedule, executor/faults.py syntax: "
                          "deterministic 'kind:times@skip' and seeded "
                          "probabilistic 'kind:p=0.01@seed' rules, comma-"
                          "separated. Kinds: sim_worker_death, "
                          "sim_lost_heartbeat, sim_delay, sim_crash_server. "
                          "Default: light chaos + two coordinator crashes; "
                          "'' (empty) disables faults")
    sim.add_argument("--tenants", type=int, default=4)
    sim.add_argument("--experiments-per-tenant", type=int, default=2)
    sim.add_argument("--algos", nargs="+", default=["asha"],
                     help="algorithms rotated across experiments, e.g. "
                          "--algos asha hyperband tpe")
    sim.add_argument("--task", default="sphere",
                     help="benchmark objective the simulated trials score")
    sim.add_argument("--trials", dest="sim_max_trials", type=int, default=64,
                     help="max_trials per experiment")
    sim.add_argument("--pool-size", dest="sim_pool_size", type=int, default=8)
    sim.add_argument("--stale-timeout-s", dest="sim_stale_timeout_s",
                     type=float, default=45.0,
                     help="coordinator pacemaker for the simulated fleet")
    sim.add_argument("--max-virtual-s", type=float, default=7200.0,
                     help="virtual-time budget before the run is cut off")
    sim.add_argument("--event-log", dest="sim_event_log", default=None,
                     metavar="PATH",
                     help="write the deterministic JSONL event log here")
    sim.add_argument("--json", dest="as_json", action="store_true",
                     help="emit the full report as JSON on stdout")

    lint = sub.add_parser(
        "lint",
        help="repo-invariant static analysis (lock discipline, JAX "
             "hygiene, WAL durability contract)",
    )
    lint.add_argument("paths", nargs="*", default=[],
                      help="files/directories to scan (default: the "
                           "metaopt_tpu package, from any cwd)")
    lint.add_argument("--baseline", default=None,
                      help="grandfathered-findings file (default: the "
                           "checked-in analysis/baseline.json)")
    lint.add_argument("--update-baseline", action="store_true")
    lint.add_argument("--no-baseline", action="store_true",
                      help="report every finding, ignore the baseline")
    lint.add_argument("--format", choices=("text", "json"),
                      default="text", dest="lint_format")

    race = sub.add_parser(
        "race",
        help="hybrid race detection: static shared-attribute check plus "
             "lockset/vector-clock instrumented concurrency suites",
    )
    race.add_argument("--suite", action="append", default=None,
                      choices=("coord", "algo", "wal", "sim", "all"),
                      help="workload(s) to run instrumented (repeatable; "
                           "default: all)")
    race.add_argument("--scale", type=int, default=1,
                      help="iteration multiplier (1 = fast CI run)")
    race.add_argument("--static-only", action="store_true",
                      help="run only the MTR001 static check, no workloads")
    race.add_argument("--baseline", default=None,
                      help="grandfathered-findings file (default: the "
                           "checked-in analysis/race_baseline.json)")
    race.add_argument("--update-baseline", action="store_true")
    race.add_argument("--no-baseline", action="store_true",
                      help="report every finding, ignore the baseline")
    race.add_argument("--format", choices=("text", "json"),
                      default="text", dest="race_format")

    crash = sub.add_parser(
        "crashcheck",
        help="crash-consistency certification: static persistence-order "
             "analysis plus exhaustive crash-point enumeration of every "
             "durable path with real recovery",
    )
    crash.add_argument("--suite", action="append", default=None,
                       choices=("wal", "snapshot", "archive", "evict",
                                "handoff", "all"),
                       help="durable path(s) to enumerate (repeatable; "
                            "default: all)")
    crash.add_argument("--static-only", action="store_true",
                       help="run only the MTP static checks, no "
                            "enumeration")
    crash.add_argument("--baseline", default=None,
                       help="grandfathered-findings file (default: the "
                            "checked-in analysis/crash_baseline.json)")
    crash.add_argument("--update-baseline", action="store_true")
    crash.add_argument("--no-baseline", action="store_true",
                       help="report every finding, ignore the baseline")
    crash.add_argument("--format", choices=("text", "json"),
                       default="text", dest="crash_format")

    analyze = sub.add_parser(
        "analyze",
        help="umbrella static analysis: lint + race --static-only + "
             "crashcheck --static-only, one combined report",
    )
    analyze.add_argument("paths", nargs="*", default=[],
                         help="files/directories to scan (default: the "
                              "metaopt_tpu package, from any cwd)")
    analyze.add_argument("--no-baseline", action="store_true",
                         help="report every finding, ignore the "
                              "baselines")
    analyze.add_argument("--format", choices=("text", "json"),
                         default="text", dest="analyze_format")

    return p


def _make_ledger_from_spec(spec: Optional[str], cfg: Dict[str, Any]):
    from metaopt_tpu.ledger.backends import ledger_from_spec

    if spec is None:
        lcfg = cfg.get("ledger")
        if not lcfg:
            # no spec and no (or an empty) ledger config section: same
            # native-preferred resolution a bare --ledger PATH gets —
            # `ledger: {}` must mean the persistent local default, never
            # a silent in-memory backend (make_ledger's type default)
            from metaopt_tpu.ledger.backends import local_ledger

            return local_ledger(os.path.expanduser("~/.metaopt_tpu/ledger"))
        lcfg = dict(lcfg)
        if lcfg.get("type") == "file" and not lcfg.get("path"):
            lcfg["path"] = os.path.expanduser("~/.metaopt_tpu/ledger")
        return make_ledger(lcfg)
    return ledger_from_spec(spec)


def _strip_remainder(cmd: List[str]) -> List[str]:
    return cmd[1:] if cmd[:1] == ["--"] else cmd


def _family_versions(ledger, name: str):
    """The stored version family of an experiment, plus the free slot.

    Returns ``(members, next_name, next_version)``: ``members`` is the
    ``name`` document followed by the ``name-vN`` siblings that EVC
    auto-resolution (and ``db load --resolve bump``) created, ordered by
    version suffix; ``next_name``/``next_version`` is one past the
    HIGHEST occupied (or squatted) slot — a gap left by ``db rm`` is
    never reused, so surviving later versions keep their lineage intact.
    A ``name-vN`` experiment whose lineage does NOT chain back to the
    family (a user-created name that happens to match the pattern, an
    orphan whose parent version was deleted, or a child created BEFORE
    its claimed parent — i.e. the head was deleted and the name reused)
    is skipped — it blocks its slot but is neither joined nor branched
    from.
    """
    import re

    from metaopt_tpu.ledger.evc import branch_parent

    def created_at(d) -> Optional[str]:
        # UTC isoformat stamped at configure(); lexicographic order is
        # chronological order
        return (d.get("metadata") or {}).get("datetime")

    doc = ledger.load_experiment(name)
    if doc is None:
        return [], name, 1
    out = [(name, doc)]
    family_created = {name: created_at(doc)}
    pat = re.compile(re.escape(name) + r"-v(\d+)$")
    sibs = sorted(
        (int(m.group(1)), n)
        for n in ledger.list_experiments()
        for m in [pat.match(n)] if m
    )
    top = int(doc.get("version", 1))
    for v, n in sibs:
        top = max(top, v)
        cdoc = ledger.load_experiment(n)
        if cdoc is None:
            continue
        parent = branch_parent(cdoc)
        if parent not in family_created:
            continue
        c_at, p_at = created_at(cdoc), family_created[parent]
        if c_at is not None and p_at is not None and c_at < p_at:
            # the child predates the experiment its parent NAME now
            # denotes: a stale orphan of a deleted-and-recreated head
            continue
        out.append((n, cdoc))
        family_created[n] = c_at
    return out, f"{name}-v{top + 1}", top + 1


def _conflict_summary(stored: Dict[str, str], new: Dict[str, str],
                      stored_algo: List[str],
                      requested_algo: Optional[List[str]]) -> str:
    parts = []
    changed = sorted(k for k in stored.keys() & new.keys()
                     if stored[k] != new[k])
    added = sorted(new.keys() - stored.keys())
    removed = sorted(stored.keys() - new.keys())
    for k in changed:
        parts.append(f"{k}: {stored[k]} -> {new[k]}")
    for k in added:
        parts.append(f"+{k}~{new[k]}")
    for k in removed:
        parts.append(f"-{k}~{stored[k]}")
    if requested_algo is not None and stored_algo \
            and requested_algo != stored_algo:
        parts.append(
            f"algorithm: {'/'.join(stored_algo)} -> "
            f"{'/'.join(requested_algo)}"
        )
    return "; ".join(parts)


def _experiment_from_args(args, cfg: Dict[str, Any], need_cmd: bool):
    user_argv = _strip_remainder(getattr(args, "cmd", []) or [])
    name = args.name or cfg.get("name")
    if not name:
        raise SystemExit("an experiment name is required (-n/--name)")
    ledger = _make_ledger_from_spec(args.ledger, cfg)

    space = template = None
    if user_argv:
        space, template = SpaceBuilder().build(user_argv)
        if need_cmd and len(space) == 0:
            raise SystemExit(
                "no ~priors found in the command; mark searchable args like "
                "--lr~'loguniform(1e-5, 1e-1)'"
            )
    metadata = {}
    warm = getattr(args, "warm_start", None) or cfg.get("warm_start")
    if warm:
        metadata["warm_start"] = warm
    version = 1
    branch = getattr(args, "branch_from", None) or cfg.get("branch_from")
    on_conflict = (getattr(args, "on_conflict", None)
                   or cfg.get("on_conflict") or "adopt")
    auto_branch_version: Optional[int] = None
    if not branch:
        from metaopt_tpu.io.resolve_config import DEFAULTS

        requested_algo: Optional[List[str]] = None
        if getattr(args, "algo", None):
            requested_algo = [args.algo]
        elif cfg.get("algorithm") not in (None, DEFAULTS["algorithm"]):
            requested_algo = sorted(cfg["algorithm"].keys())

        def _fits(mdoc) -> bool:
            if space is not None \
                    and (mdoc.get("space") or {}) != space.configuration:
                return False
            if requested_algo is not None and mdoc.get("algorithm") \
                    and sorted(mdoc["algorithm"].keys()) != requested_algo:
                return False
            return True

        if space is not None or requested_algo is not None:
            family, free_name, free_version = _family_versions(ledger, name)
        else:
            family, free_name, free_version = [], name, 1
        match = next(((mn, md) for mn, md in family if _fits(md)), None)
        if family and match is None:
            # diff against the experiment configure() would actually join
            # (the named one), not the newest family version
            base_doc = family[0][1]
            stored_space = base_doc.get("space") or {}
            diff = _conflict_summary(
                stored_space,
                space.configuration if space is not None else stored_space,
                sorted((base_doc.get("algorithm") or {}).keys()),
                requested_algo,
            )
            if on_conflict == "fail":
                raise SystemExit(
                    f"experiment {name!r} exists with a different "
                    f"configuration ({diff}); rerun with --on-conflict "
                    f"branch to version it, or adopt to defer to the "
                    f"stored config"
                )
            if on_conflict == "branch":
                # parent = newest FAMILY member; child name = the first
                # free -vN slot (never an unrelated name-squatter)
                branch = family[-1][0]
                name = free_name
                auto_branch_version = free_version
                log.warning(
                    "EVC: configuration changed (%s); branching %r from %r",
                    diff, name, branch,
                )
            else:
                log.warning(
                    "experiment %r already exists; your command's "
                    "configuration differs (%s) and the STORED config "
                    "wins — pass --on-conflict branch to version the "
                    "change, or fail to stop instead",
                    name, diff,
                )
        elif match is not None and match[0] != name:
            log.warning(
                "EVC: this configuration matches version %d (%r); "
                "joining it", match[1].get("version", 1), match[0],
            )
            name = match[0]
    if branch:
        if branch == name:
            raise SystemExit("--branch-from: the child needs its own name")
        from metaopt_tpu.ledger.evc import BranchConflictError, TrialAdapter
        from metaopt_tpu.space import build_space

        parent_doc = ledger.load_experiment(branch)
        if parent_doc is None:
            raise SystemExit(f"--branch-from: no such experiment {branch!r}")
        existing_child = ledger.load_experiment(name)
        if existing_child is not None:
            from metaopt_tpu.ledger.evc import branch_parent

            if branch_parent(existing_child) != branch:
                # configure() adopts stored config, which would silently drop
                # the requested branch — refuse instead
                raise SystemExit(
                    f"experiment {name!r} already exists and was not "
                    f"branched from {branch!r}; pick a new child name"
                )
        parent_space = build_space(parent_doc["space"])
        defaults: Dict[str, Any] = {}
        for kv in getattr(args, "branch_default", None) or []:
            key, sep, raw = kv.partition("=")
            if not sep:
                raise SystemExit(
                    f"--branch-default wants NAME=VALUE, got {kv!r}"
                )
            try:
                defaults[key] = json.loads(raw)
            except json.JSONDecodeError:
                defaults[key] = raw
        renames: Dict[str, str] = {}
        for kv in getattr(args, "branch_rename", None) or []:
            old, sep, new = kv.partition("=")
            if not sep:
                raise SystemExit(f"--branch-rename wants OLD=NEW, got {kv!r}")
            renames[old] = new
        if space is None:  # same space, new version (config/code change)
            space = parent_space
            user_argv = list(parent_doc.get("user_args", []))
        try:  # fail at branch time, not at first produce
            adapter = TrialAdapter(parent_space, space, defaults, renames)
        except BranchConflictError as err:
            raise SystemExit(f"cannot branch from {branch!r}: {err}")
        metadata["branch"] = {
            "parent": branch,
            "defaults": defaults,
            "renames": renames,
            "adapter": adapter.describe(),
        }
        version = parent_doc.get("version", 1) + 1
        if auto_branch_version is not None:
            # the -vN suffix of an auto-branch child must agree with its
            # document even when a name-squatter forced a later slot
            version = max(version, auto_branch_version)
    from metaopt_tpu.io.resolve_config import DEFAULTS

    algorithm = cfg.get("algorithm")
    if getattr(args, "algo", None):
        explicit = algorithm not in (None, DEFAULTS["algorithm"])
        if explicit and list(algorithm) != [args.algo]:
            raise SystemExit(
                f"--algo {args.algo} conflicts with config algorithm "
                f"{list(algorithm)[0]!r}; pick one"
            )
        algorithm = algorithm if explicit else {args.algo: {}}
    exp = Experiment(
        name,
        ledger,
        space=space,
        algorithm=algorithm,
        max_trials=cfg.get("max_trials", 100),
        pool_size=cfg.get("pool_size", 1),
        metadata=metadata,
        user_args=user_argv,
        version=version,
    ).configure()
    # a joiner (no cmd) reuses the stored user_args to rebuild the template
    if template is None and exp.user_args:
        _, template = SpaceBuilder().build(exp.user_args)
    return exp, template


def _vector_objective(name: str):
    """Resolve a named vectorized objective to (batch_fn, space DSL)."""
    from metaopt_tpu.models import objectives as zoo

    if name == "mlp":
        return zoo.make_mlp_batch_objective(), dict(zoo.MLP_SPACE)
    from metaopt_tpu.benchmark.tasks import task_registry

    try:
        task = task_registry.get(name)()
    except KeyError:
        raise SystemExit(
            f"unknown vectorized objective {name!r} (benchmark task "
            "with a batch() form, or 'mlp')"
        )
    if not task.vectorized:
        raise SystemExit(f"benchmark task {name!r} has no vectorized form")
    return task.batch, dict(task.space)


def _cmd_hunt(args, cfg: Dict[str, Any]) -> int:
    batch_size = getattr(args, "batch_size", None) or cfg.get("batch_size")
    vector_name = (getattr(args, "vector_objective", None)
                   or cfg.get("vector_objective"))
    if batch_size not in (None, 1, "1") and not vector_name:
        raise SystemExit(
            "--batch-size needs --vector-objective NAME: pools evaluate "
            "in-process as one vmap program, subprocess trials can't batch"
        )
    vector_fn = None
    if vector_name:
        vector_fn, vector_space = _vector_objective(vector_name)
        if not _strip_remainder(getattr(args, "cmd", []) or []):
            # no user command: the objective is in-process anyway, so the
            # space comes from its declaration (~prior tokens, never run)
            args.cmd = [f"batched:{vector_name}"] + [
                f"{k}~{v}" for k, v in vector_space.items()
            ]
    exp, template = _experiment_from_args(args, cfg, need_cmd=False)
    if vector_fn is None and (template is None or not exp.user_args):
        raise SystemExit("hunt needs a user command (or an experiment that has one)")

    script = template.argv[0] if template.argv else ""
    interpreter = None
    if script.endswith(".py") and not os.access(script, os.X_OK):
        interpreter = [sys.executable]

    n_chips = args.n_chips if args.n_chips is not None else (
        (cfg.get("executor") or {}).get("n_chips")
    )

    def make_executor(tmpl):
        if vector_fn is not None:
            from metaopt_tpu.executor import BatchedExecutor

            if exp.space is None:
                raise SystemExit(
                    "batched hunt needs a space (none stored or declared)"
                )
            return BatchedExecutor(vector_fn, exp.space)
        kwargs = dict(
            working_dir=args.working_dir or cfg.get("working_dir"),
            interpreter=interpreter,
            timeout_s=args.timeout_s,
            profile_dir=args.profile_dir,
            ckpt_root=args.ckpt_root or cfg.get("ckpt_root"),
            jax_cache_dir=args.jax_cache or cfg.get("jax_cache"),
        )
        if n_chips:
            from metaopt_tpu.executor.tpu import TPUExecutor

            return TPUExecutor(tmpl, n_chips=int(n_chips), **kwargs)
        return SubprocessExecutor(tmpl, **kwargs)

    workon_kwargs = dict(
        worker_trials=(
            args.worker_trials
            if args.worker_trials is not None
            else cfg.get("worker_trials")
        ),
        max_broken=args.exp_max_broken if args.exp_max_broken is not None else 10,
        heartbeat_timeout_s=cfg.get("heartbeat_s", 30.0) * 2,
        producer_mode=args.producer or cfg.get("producer") or "local",
    )
    if vector_fn is not None:
        # in-process vectorized objective: default to cohort-sized pools
        workon_kwargs["batch_size"] = (
            "auto" if batch_size in (None, "auto") else int(batch_size)
        )
    worker_id = args.worker_id or f"{os.uname().nodename}-{os.getpid()}"
    n_workers = max(1, int(getattr(args, "n_workers", 1) or 1))
    if n_workers == 1:
        executor = make_executor(template)
        try:
            all_stats = [workon(exp, executor, worker_id=worker_id,
                                **workon_kwargs)]
        finally:
            executor.close()
    else:
        # N full produce/reserve/execute loops in this process (the
        # lineage's `--n-workers`): trials are subprocesses, so N run
        # concurrently. Each loop gets its own Experiment/ledger handle
        # (coord sockets aren't shared across threads) and its own
        # executor; the ledger's atomic reserve arbitrates exactly as it
        # does between separate worker processes.
        import threading

        from metaopt_tpu.coord.client_backend import CoordLedgerClient

        results: Dict[int, Any] = {}
        errors: Dict[int, str] = {}
        stop = threading.Event()
        shared_ledger = not isinstance(exp.ledger, CoordLedgerClient)

        def run(i: int) -> None:
            try:
                if shared_ledger:
                    # memory/file/native backends are thread-safe: every
                    # worker MUST share one ledger or (memory especially)
                    # each thread would race a private universe
                    w_exp = Experiment(exp.name, exp.ledger).configure()
                    w_template = template
                else:
                    # coord sockets are per-thread: build a fresh client
                    w_exp, w_template = _experiment_from_args(
                        args, cfg, need_cmd=False
                    )
                ex = make_executor(w_template)
                try:
                    results[i] = workon(
                        w_exp, ex, worker_id=f"{worker_id}-w{i}",
                        stop_event=stop, **workon_kwargs
                    )
                finally:
                    ex.close()
            except BaseException as err:  # a dead worker must be REPORTED
                errors[i] = f"{type(err).__name__}: {err}"

        threads = [
            threading.Thread(target=run, args=(i,), daemon=True)
            for i in range(n_workers)
        ]
        for t in threads:
            t.start()
        try:
            for t in threads:
                while t.is_alive():
                    t.join(timeout=0.5)
        except KeyboardInterrupt:
            # wind down: each loop finishes its in-flight trial, marks
            # state, and closes its executor. The wait is bounded by the
            # trial timeout (or 300s when unbounded); anything still
            # running after that is abandoned to the heartbeat stale sweep.
            stop.set()
            grace = (args.timeout_s + 30) if args.timeout_s else 300
            print(f"interrupt: waiting up to {grace:.0f}s for in-flight "
                  "trials...", file=sys.stderr)
            deadline = time.monotonic() + grace
            for t in threads:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
            if any(t.is_alive() for t in threads):
                print("some trials still running — their reservations will "
                      "be re-freed by the stale sweep", file=sys.stderr)
        all_stats = [results[i] for i in sorted(results)]
        if not all_stats:
            raise SystemExit(
                "every worker thread failed: "
                + "; ".join(f"w{i}: {e}" for i, e in sorted(errors.items()))
            )
        for i, e in sorted(errors.items()):
            print(f"worker w{i} died: {e}", file=sys.stderr)

    s = exp.stats
    # element-wise aggregate across workers (counters sum; each worker ran
    # its own producer, so summed seconds = total suggest/observe cost)
    timings: Dict[str, Any] = {}
    for st in all_stats:
        for k, v in st.producer_timings.items():
            timings[k] = timings.get(k, 0) + v if isinstance(v, (int, float)) \
                else v
    timings = {k: round(v, 4) if isinstance(v, float) else v
               for k, v in timings.items()}
    failed = len(all_stats) < n_workers
    print(json.dumps({
        "experiment": exp.name,
        "worker": worker_id,
        "n_workers": n_workers,
        "failed_workers": n_workers - len(all_stats),
        "completed_by_worker": sum(st.completed for st in all_stats),
        "broken_by_worker": sum(st.broken for st in all_stats),
        "pruned_by_worker": sum(st.pruned for st in all_stats),
        "requeued_by_worker": sum(st.requeued for st in all_stats),
        "producer_timings": timings,
        "total": s["by_status"],
        "best": s["best"],
    }, indent=2))
    return 0 if (s["best"] is not None and not failed) else 1


def _cmd_init_only(args, cfg: Dict[str, Any]) -> int:
    exp, _ = _experiment_from_args(args, cfg, need_cmd=True)
    print(f"experiment {exp.name!r} ready: space={exp.space!r} "
          f"algorithm={exp.algorithm}")
    return 0


def _cmd_insert(args, cfg: Dict[str, Any]) -> int:
    exp, _ = _experiment_from_args(args, cfg, need_cmd=False)
    params = json.loads(args.params)
    if params not in exp.space:
        raise SystemExit(f"params {params} not inside {exp.space!r}")
    trial = exp.make_trial(params)
    kept = exp.register_trials([trial])
    if not kept:
        raise SystemExit(f"trial already exists: {trial.id}")
    print(f"registered trial {trial.id}")
    return 0


def _cmd_resume(args, cfg: Dict[str, Any]) -> int:
    """Unpark trials: suspended/interrupted/broken → new, reservable again.

    An interrupted or broken trial's params remain registered (dedup), so
    no algorithm can ever re-suggest that point — reviving the trial is
    the retry path (``--statuses interrupted,broken``).
    """
    revivable = ("suspended", "interrupted", "broken")
    statuses = [s.strip() for s in args.statuses.split(",") if s.strip()]
    if not statuses:
        raise SystemExit(
            f"--statuses is empty; name statuses from {revivable}"
        )
    bad = [s for s in statuses if s not in revivable]
    if bad:
        raise SystemExit(
            f"--statuses must name statuses from {revivable}, got {bad}"
        )
    exp, _ = _experiment_from_args(args, cfg, need_cmd=False)
    parked = [t for s in statuses for t in exp.fetch_trials(s)]
    if args.trial_id:
        parked = [t for t in parked if t.id.startswith(args.trial_id)]
        if not parked:
            raise SystemExit(
                f"no {'/'.join(statuses)} trial matching {args.trial_id!r}"
            )
    resumed = 0
    for t in parked:
        was = t.status
        t.reset_to_new()
        if exp.ledger.update_trial(t, expected_status=was):
            resumed += 1
    print(f"resumed {resumed} trial(s)")
    return 0


def _cmd_list(args, cfg: Dict[str, Any]) -> int:
    """ref: `orion list` in the lineage — enumerate experiments."""
    from metaopt_tpu.io.webapi import _experiment_summary

    ledger = _make_ledger_from_spec(args.ledger, cfg)
    # same summary the web API serves: the two surfaces must agree on "done"
    rows = [_experiment_summary(ledger, name)
            for name in sorted(ledger.list_experiments())]
    if args.as_json:
        print(json.dumps(rows, indent=2))
        return 0
    if not rows:
        print("no experiments")
        return 0
    # EVC families render as a tree: children indent under the version
    # they branched from (ref: the lineage's version-aware `orion list`)
    by_name = {r["name"]: r for r in rows}
    children: Dict[str, List[Dict[str, Any]]] = {}
    roots: List[Dict[str, Any]] = []
    for r in rows:
        p = r.get("parent")
        if p and p in by_name:
            children.setdefault(p, []).append(r)
        else:
            roots.append(r)

    def emit(r: Dict[str, Any], depth: int) -> None:
        flag = " [done]" if r["done"] else ""
        pre = "  " * depth + ("└─ " if depth else "")
        ver = f" (v{r['version']})" if r.get("version", 1) != 1 else ""
        print(f"{pre}{r['name']}{ver}: {r['completed']}/{r['max_trials']} "
              f"completed ({r['trials']} trials, "
              f"{r['algorithm'] or '?'}){flag}")
        for c in sorted(children.get(r["name"], []),
                        key=lambda c: (c.get("version", 1), c["name"])):
            emit(c, depth + 1)

    for r in roots:
        emit(r, 0)
    return 0


def _cmd_tenants(args, cfg: Dict[str, Any]) -> int:
    """``mtpu tenants``: the coordinator's multi-tenant service stats."""
    ledger = _make_ledger_from_spec(args.ledger, cfg)
    stats_fn = getattr(ledger, "tenant_stats", None)
    if stats_fn is None:
        print("tenants needs a coordinator ledger (coord://host:port)",
              file=sys.stderr)
        return 2
    stats = stats_fn(include_experiments=args.experiments)
    if args.as_json:
        print(json.dumps(stats, indent=2))
        return 0
    print(f"residency: {stats.get('resident', 0)} resident, "
          f"{stats.get('evicted', 0)} evicted "
          f"({stats.get('evictions', 0)} evictions, "
          f"{stats.get('hydrations', 0)} hydrations)")
    tenants = stats.get("tenants") or {}
    fuser = stats.get("fuser")
    if fuser:
        print(f"fused suggest: {fuser.get('bucket_launches', 0)} bucket "
              f"launches, {fuser.get('fused_experiments', 0)} fused / "
              f"{fuser.get('fallback_experiments', 0)} fallback; last tick "
              f"{fuser.get('last_buckets', 0)} buckets, occupancy "
              f"{fuser.get('last_occupancy', 0.0):g}")
    for tenant in sorted(tenants):
        row = tenants[tenant]
        line = (f"  {tenant}: {row.get('experiments', 0)} experiments "
                f"({row.get('evicted', 0)} evicted), weight "
                f"{row.get('weight', 1.0):g}, produce "
                f"{row.get('granted', 0)} granted / "
                f"{row.get('denied', 0)} denied")
        if "suggest_hit_rate" in row:
            line += (f", suggest hit rate {row['suggest_hit_rate']:.0%}"
                     f" (fused {row.get('fused_commits', 0)} / discarded "
                     f"{row.get('fused_discards', 0)})")
        print(line)
    if args.experiments:
        per = stats.get("experiments") or {}
        for name in sorted(per):
            row = per[name]
            counts = ", ".join(f"{k}={v}" for k, v in
                               sorted((row.get("counts") or {}).items()))
            tag = " [evicted]" if row.get("evicted") else ""
            print(f"    {name} ({row.get('tenant', 'default')}){tag}: "
                  f"{counts or 'no trials'}")
    return 0


def _cmd_status(args, cfg: Dict[str, Any]) -> int:
    ledger = _make_ledger_from_spec(args.ledger, cfg)
    names = [args.name] if args.name else ledger.list_experiments()
    out = []
    for name in names:
        doc = ledger.load_experiment(name)
        if doc is None:
            raise SystemExit(f"no such experiment: {name}")
        exp = Experiment(name, ledger).configure()
        s = exp.stats
        if args.rungs and exp.algorithm and exp.space.fidelity is not None:
            from metaopt_tpu.algo.base import make_algorithm

            algo = make_algorithm(exp.space, exp.algorithm)
            algo.observe(exp.fetch_completed_trials())
            s["rungs"] = getattr(algo, "rung_table", None)
        if args.workers:
            from metaopt_tpu.io.webapi import worker_table

            s["workers"] = worker_table(ledger, name)
        out.append(s)
    if args.as_json:
        print(json.dumps(out, indent=2))
    else:
        for s in out:
            counts = ", ".join(f"{k}:{v}" for k, v in sorted(s["by_status"].items()))
            print(f"{s['name']}: {s['trials']}/{s['max_trials']} trials ({counts})")
            if s["best"]:
                print(f"  best objective {s['best']['objective']:.6g} "
                      f"at {s['best']['params']}")
            for r in s.get("rungs") or []:
                line = (f"  bracket {r['bracket']} budget {r['budget']:>5}: "
                        f"{r['n'] if 'n' in r else r['completed']} completed")
                if "capacity" in r:
                    line += f", {r['assigned']}/{r['capacity']} assigned"
                if "promoted" in r:
                    line += f", {r['promoted']} promoted"
                print(line)
            for w in s.get("workers") or []:
                age = w["last_seen_age_s"]
                seen = f"last seen {age:.0f}s ago" if age is not None \
                    else "never seen"
                hold = (f", holds {', '.join(t[:8] for t in w['current'])}"
                        if w["current"] else "")
                counts = ", ".join(
                    f"{w[k]} {k}" for k in
                    ("completed", "broken", "interrupted", "suspended",
                     "reserved")
                    if w[k]
                ) or "no trials"
                print(f"  worker {w['worker']}: {counts} ({seen}{hold})")
    return 0


def _cmd_info(args, cfg: Dict[str, Any]) -> int:
    """ref: `orion info` in the lineage — the full experiment document."""
    ledger = _make_ledger_from_spec(args.ledger, cfg)
    if not args.name:
        raise SystemExit("info needs an experiment name (-n/--name)")
    doc = ledger.load_experiment(args.name)
    if doc is None:
        raise SystemExit(f"no such experiment: {args.name}")
    exp = Experiment(args.name, ledger).configure()
    s = exp.stats
    payload = {
        "name": exp.name,
        "version": doc.get("version", 1),
        "algorithm": exp.algorithm,
        "space": {n: d.get_prior_string() for n, d in exp.space.items()},
        "max_trials": exp.max_trials,
        "pool_size": exp.pool_size,
        "metadata": exp.metadata,
        "user_args": exp.user_args,
        "stats": {"by_status": s["by_status"], "best": s["best"]},
    }
    if args.as_json:
        print(json.dumps(payload, indent=2))
        return 0
    print(f"experiment {exp.name} (version {payload['version']})")
    branch = (exp.metadata or {}).get("branch")
    if branch:
        print(f"  branched from: {branch['parent']}")
    algo_name = next(iter(exp.algorithm), "?")
    print(f"  algorithm: {algo_name} {exp.algorithm.get(algo_name) or {}}")
    print("  space:")
    for n, prior in payload["space"].items():
        print(f"    {n}~{prior}")
    print(f"  max_trials: {exp.max_trials}  pool_size: {exp.pool_size}")
    counts = ", ".join(f"{k}:{v}" for k, v in sorted(s["by_status"].items()))
    print(f"  trials: {counts or 'none'}")
    if s["best"]:
        print(f"  best: {s['best']['objective']:.6g} at {s['best']['params']}")
    if exp.user_args:
        print(f"  command: {' '.join(exp.user_args)}")
    return 0


def _cmd_plot(args, cfg: Dict[str, Any]) -> int:
    """ref: the lineage's regret/lcurve plots.

    Emits JSON (--json) or ASCII; no plotting dependency needed.
    """
    from metaopt_tpu.io.webapi import regret_series

    ledger = _make_ledger_from_spec(args.ledger, cfg)
    if not args.name:
        raise SystemExit("plot needs an experiment name (-n/--name)")
    if ledger.load_experiment(args.name) is None:
        raise SystemExit(f"no such experiment: {args.name}")
    if args.kind == "lcurve":
        return _plot_lcurve(args, ledger)
    if args.kind == "parallel":
        return _plot_parallel(args, ledger)
    if args.kind == "importance":
        return _plot_importance(args, ledger)
    if args.kind == "pdp":
        return _plot_pdp(args, ledger)
    if args.kind == "pareto":
        return _plot_pareto(args, ledger)
    points = regret_series(ledger, args.name)
    if args.as_json:
        print(json.dumps({"experiment": args.name, "regret": points},
                         indent=2))
        return 0
    if not points:
        print("no completed trials")
        return 0
    bests = [p["best"] for p in points]
    lo, hi = min(bests), max(bests)
    span = (hi - lo) or 1.0
    height = 8
    rows = [[" "] * len(bests) for _ in range(height)]
    for x, b in enumerate(bests):
        # row 0 is printed first and labelled `hi`, so b == hi maps to row 0
        rows[int((hi - b) / span * (height - 1))][x] = "*"
    print(f"regret ({args.name}): best objective over {len(bests)} "
          "completed trials")
    for r, row in enumerate(rows):
        label = hi - (span * r / (height - 1))
        print(f"{label:>12.4g} |{''.join(row)}")
    print(f"{'':>12} +{'-' * len(bests)}")
    print(f"final best: {bests[-1]:.6g}")
    return 0


def _plot_pareto(args, ledger) -> int:
    """Nondominated front of a multi-objective experiment.

    ASCII scatter for the first two objectives (front points ``*``,
    dominated ``.``) or the full front as JSON; the ranking computation is
    shared with GET /experiments/{name}/pareto and the motpe algorithm.
    """
    from metaopt_tpu.io.webapi import pareto_series

    code, payload = pareto_series(ledger, args.name)
    if code != 200:
        print(payload.get("error", "pareto front unavailable"))
        return 1
    if args.as_json:
        print(json.dumps(payload, indent=2))
        return 0
    front = payload["front"]
    # one consistent snapshot: the payload carries the dominated points
    # too, so the scatter needs no second (racy) ledger read
    all_pts = ([(r["objectives"][0], r["objectives"][1], True)
                for r in front]
               + [(o[0], o[1], False) for o in payload["dominated"]])
    xs = [p[0] for p in all_pts]
    ys = [p[1] for p in all_pts]
    lo_x, hi_x = min(xs), max(xs)
    lo_y, hi_y = min(ys), max(ys)
    sx = (hi_x - lo_x) or 1.0
    sy = (hi_y - lo_y) or 1.0
    width, height = 56, 14
    grid = [[" "] * width for _ in range(height)]
    for x, y, on_front in sorted(all_pts, key=lambda p: p[2]):
        c = int((x - lo_x) / sx * (width - 1))
        r = int((hi_y - y) / sy * (height - 1))  # row 0 = objective-2 max
        grid[r][c] = "*" if on_front else "."
    print(f"pareto front ({args.name}): {len(front)} nondominated of "
          f"{payload['trials']} completed trials, "
          f"{payload['n_objectives']} objectives"
          + (" (showing the first two)" if payload["n_objectives"] > 2
             else ""))
    for r, row in enumerate(grid):
        label = hi_y - sy * r / (height - 1)
        print(f"{label:>12.4g} |{''.join(row)}")
    print(f"{'':>12} +{'-' * width}")
    print(f"{'':>12}  {lo_x:<.4g}{'':>{max(1, width - 16)}}{hi_x:>.4g}")
    return 0


def _plot_importance(args, ledger) -> int:
    """Per-parameter importance from the ARD GP surrogate's lengthscales.

    ref: the lineage's LPI (local parameter importance) plot — the
    computation is shared with GET /experiments/{name}/importance so the
    two surfaces can never disagree.
    """
    from metaopt_tpu.io.webapi import importance_series

    code, payload = importance_series(ledger, args.name)
    if code != 200:
        print(payload.get("error", "importance unavailable"))
        return 1
    if args.as_json:
        print(json.dumps(payload, indent=2))
        return 0
    pairs = sorted(payload["importance"].items(), key=lambda p: -p[1])
    print(f"parameter importance ({args.name}, ARD GP over "
          f"{payload['trials']} completed trials):")
    width = max(len(n) for n, _ in pairs)
    for name, v in pairs:
        bar = "#" * max(1, int(v * 40))
        print(f"  {name:<{width}}  {v:6.1%}  {bar}")
    return 0


def _plot_pdp(args, ledger) -> int:
    """1-D partial dependence per parameter (fitted ARD GP surrogate).

    ref: the lineage's ``plot partial_dependencies`` — shared with
    GET /experiments/{name}/pdp. Text mode renders each parameter's mean
    curve as a sparkline (low objective = tall bar = better region) with
    the minimizing x highlighted.
    """
    from metaopt_tpu.io.webapi import pdp_series

    code, payload = pdp_series(ledger, args.name)
    if code != 200:
        print(payload.get("error", "partial dependence unavailable"))
        return 1
    if args.as_json:
        print(json.dumps(payload, indent=2))
        return 0
    blocks = "▁▂▃▄▅▆▇█"
    print(f"partial dependence ({args.name}, ARD GP over "
          f"{payload['trials']} completed trials; taller = lower "
          f"objective = better):")
    width = max(len(n) for n in payload["pdp"])
    for pname, curve in payload["pdp"].items():
        ys = curve["mean"]
        lo, hi = min(ys), max(ys)
        span = (hi - lo) or 1.0
        spark = "".join(
            blocks[int((hi - v) / span * (len(blocks) - 1))] for v in ys
        )
        bx = curve["x"][ys.index(lo)]
        bxs = f"{bx:.4g}" if isinstance(bx, float) else str(bx)
        print(f"  {pname:<{width}}  {spark}  min {lo:.4g} at {bxs}")
    return 0


def _plot_parallel(args, ledger) -> int:
    """Parallel-coordinates export: one row per completed trial.

    Always JSON (the natural input for any parallel-coordinates renderer);
    without --json a compact table prints instead.
    """
    from metaopt_tpu.io.webapi import parallel_series

    dims, rows = parallel_series(ledger, args.name)
    if args.as_json:
        print(json.dumps({"experiment": args.name, "dimensions": dims,
                          "trials": rows}, indent=2))
        return 0
    if not rows:
        print("no completed trials")
        return 0
    widths = {d: max(len(d), 10) for d in dims}
    header = "  ".join(d.ljust(widths[d]) for d in dims) + "  objective"
    print(header)
    for r in sorted(rows, key=lambda r: r["objective"])[:40]:
        cells = []
        for d in dims:
            v = r[d]
            s = f"{v:.4g}" if isinstance(v, float) else str(v)
            cells.append(s.ljust(widths[d]))
        print("  ".join(cells) + f"  {r['objective']:.6g}")
    if len(rows) > 40:
        print(f"... {len(rows) - 40} more (use --json for all)")
    return 0


def _plot_lcurve(args, ledger) -> int:
    """Objective vs fidelity budget per lineage (ASHA/Hyperband/PBT/DEHB)."""
    from metaopt_tpu.io.webapi import lcurve_series

    fid_name, curves = lcurve_series(ledger, args.name)
    if fid_name is None:
        raise SystemExit(
            f"{args.name!r} has no fidelity dimension — lcurve needs a "
            "multi-fidelity experiment"
        )
    if args.as_json:
        print(json.dumps({"experiment": args.name, "fidelity": fid_name,
                          "lcurves": curves}, indent=2))
        return 0
    if not curves:
        print("no completed trials")
        return 0
    budgets = sorted({p["budget"] for pts in curves.values() for p in pts})
    header = "lineage".ljust(14) + "".join(f"{b:>12}" for b in budgets)
    print(f"learning curves ({args.name}), objective per {fid_name}:")
    print(header)
    # deepest-then-best first; cap the table at 20 lineages
    ranked = sorted(
        curves.items(),
        key=lambda kv: (-len(kv[1]), kv[1][-1]["objective"]),
    )
    for lineage, pts in ranked[:20]:
        by_budget = {p["budget"]: p["objective"] for p in pts}
        cells = "".join(
            f"{by_budget[b]:>12.4g}" if b in by_budget else " " * 12
            for b in budgets
        )
        print(lineage[:12].ljust(14) + cells)
    if len(ranked) > 20:
        print(f"... {len(ranked) - 20} more lineages (use --json for all)")
    return 0


#: the dump/load interchange format marker (ref: the lineage's
#: `orion db dump` / `db load` archive tooling, re-based from a pickled
#: database onto portable JSON so archives move between ANY two ledger
#: backends — memory/file/native/coord — and survive version skew legibly)
_ARCHIVE_FORMAT = "metaopt-tpu-archive"


def _db_dump(args, ledger) -> int:
    """Archive experiments (document + every trial) as one JSON file."""
    names = [args.name] if args.name else sorted(ledger.list_experiments())
    experiments = []
    for name in names:
        doc = ledger.load_experiment(name)
        if doc is None:
            raise SystemExit(f"no such experiment: {name}")
        experiments.append({
            "document": doc,
            "trials": [t.to_dict() for t in ledger.fetch(name)],
        })
    archive = {"format": _ARCHIVE_FORMAT, "version": 1,
               "experiments": experiments}
    text = json.dumps(archive, indent=2)
    if args.output:
        tmp = args.output + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, args.output)  # atomic AND durable: never a torn
        fsync_dir(args.output)        # archive, even across power loss
        n_trials = sum(len(e["trials"]) for e in experiments)
        print(f"dumped {len(experiments)} experiment(s), {n_trials} "
              f"trial(s) to {args.output}")
    else:
        print(text)
    return 0


def _db_load(args, ledger) -> int:
    """Restore a dump archive into the configured ledger.

    Collision policy per --resolve: fail | ignore | overwrite | bump
    (bump loads as ``NAME-vN`` with version+1 and ``parent`` set — the
    ledger keys experiments by name, so a version bump is an EVC-style
    sibling, not an in-place rewrite).
    """
    from metaopt_tpu.ledger.backends import DuplicateTrialError
    from metaopt_tpu.ledger.trial import Trial

    if not args.file:
        raise SystemExit("db load needs --file ARCHIVE")
    with open(args.file) as f:
        archive = json.load(f)
    if archive.get("format") != _ARCHIVE_FORMAT:
        raise SystemExit(
            f"{args.file}: not a {_ARCHIVE_FORMAT} file "
            f"(format={archive.get('format')!r})"
        )
    if archive.get("version") != 1:
        # a future format revision must fail loudly here, not "succeed"
        # with silently-dropped fields
        raise SystemExit(
            f"{args.file}: archive version {archive.get('version')!r} "
            "is not supported by this release (expected 1)"
        )
    for entry in archive.get("experiments", []):
        doc = dict(entry["document"])
        name = doc.get("name")
        if not name:
            raise SystemExit(f"{args.file}: experiment entry without a name")
        existing = ledger.load_experiment(name)
        if existing is not None:
            if args.resolve == "fail":
                raise SystemExit(
                    f"experiment {name!r} already exists; re-run with "
                    "--resolve ignore|overwrite|bump"
                )
            if args.resolve == "ignore":
                print(f"{name}: exists, skipped")
                continue
            if args.resolve == "overwrite":
                if not ledger.delete_experiment(name):
                    raise SystemExit(
                        f"backend {type(ledger).__name__} cannot overwrite "
                        f"{name!r} (no deletion support)"
                    )
            elif args.resolve == "bump":
                version = int(existing.get("version", 1)) + 1
                bumped = f"{name}-v{version}"
                if ledger.load_experiment(bumped) is not None:
                    raise SystemExit(
                        f"bump target {bumped!r} already exists; "
                        "rm it or dump/load under another name"
                    )
                doc.update(name=bumped, version=version, parent=name)
                name = bumped
        ledger.create_experiment(doc)
        loaded = dups = 0
        for tdoc in entry.get("trials", []):
            t = Trial.from_dict({**tdoc, "experiment": name})
            try:
                ledger.register(t)
                loaded += 1
            except DuplicateTrialError:
                dups += 1  # partially-loaded archive re-applied: idempotent
        note = f" ({dups} already present)" if dups else ""
        print(f"{name}: loaded document + {loaded} trial(s){note}")
    return 0


#: experiment-document fields `db set` may edit, with their coercions.
#: ref: the lineage's `orion db set` (post-v0 admin surface) — mutating
#: anything else (space, algorithm) would invalidate registered trials;
#: that path is EVC branching, not an in-place edit.
_SETTABLE_EXP_FIELDS = {"max_trials": int, "pool_size": int}


def _resolve_trial_prefix(trials, prefix: str, what: str):
    """Exactly one trial whose id starts with ``prefix``, or SystemExit."""
    matches = [t for t in trials if t.id.startswith(prefix)]
    if not matches:
        raise SystemExit(f"no {what} matching {prefix!r}")
    if len(matches) > 1:
        raise SystemExit(
            f"{prefix!r} is ambiguous ({len(matches)} trials); "
            f"use a longer prefix"
        )
    return matches[0]


def _db_set(args, ledger) -> int:
    """Edit experiment fields, or force a trial's status (admin override)."""
    from metaopt_tpu.ledger.trial import STATUSES

    if not args.name:
        raise SystemExit("db set needs an experiment name (-n/--name)")
    if ledger.load_experiment(args.name) is None:
        raise SystemExit(f"no such experiment: {args.name}")
    assignments: Dict[str, str] = {}
    for kv in args.assignments or []:
        key, sep, raw = kv.partition("=")
        if not sep:
            raise SystemExit(f"db set wants KEY=VALUE, got {kv!r}")
        assignments[key] = raw
    if not assignments:
        raise SystemExit("db set: nothing to change (pass KEY=VALUE)")

    if args.trial_id:
        if list(assignments) != ["status"]:
            raise SystemExit(
                "db set --trial supports exactly one assignment: status=…"
            )
        status = assignments["status"]
        if status not in STATUSES:
            raise SystemExit(
                f"unknown status {status!r}; one of {sorted(STATUSES)}"
            )
        t = _resolve_trial_prefix(ledger.fetch(args.name), args.trial_id,
                                  "trial")
        was = t.status
        # admin override: bypass lifecycle legality but keep the
        # bookkeeping consistent with where the trial lands
        if status == "new":
            t.reset_to_new()
        else:
            t.status = status
            now = time.time()
            if status == "reserved":
                # a reservation without a heartbeat would be invisible to
                # the stale sweep (release_stale skips heartbeat=None) —
                # stamp it like transition() would
                t.start_time = t.start_time or now
                t.heartbeat = now
            elif status in ("completed", "broken", "interrupted") \
                    and t.end_time is None:
                t.end_time = now
        if not ledger.update_trial(t, expected_status=was):
            raise SystemExit(
                f"trial {t.id} changed state concurrently; re-run"
            )
        print(f"trial {t.id}: {was} -> {status}")
        return 0

    patch: Dict[str, Any] = {}
    for key, raw in assignments.items():
        coerce = _SETTABLE_EXP_FIELDS.get(key)
        if coerce is None:
            raise SystemExit(
                f"db set: field {key!r} is not editable (only "
                f"{sorted(_SETTABLE_EXP_FIELDS)}; space/algorithm changes "
                f"are EVC branches — see hunt --on-conflict branch)"
            )
        try:
            patch[key] = coerce(raw)
        except ValueError:
            raise SystemExit(f"db set: {key} wants {coerce.__name__}, "
                             f"got {raw!r}")
        if patch[key] < 1:
            # a stored 0 stalls the producer (pool) or instantly finishes
            # the experiment (max_trials) with no error anywhere
            raise SystemExit(f"db set: {key} must be >= 1, got {patch[key]}")
    ledger.update_experiment(args.name, patch)
    print(f"{args.name}: set " +
          ", ".join(f"{k}={v}" for k, v in patch.items()))
    return 0


def _db_release(args, ledger) -> int:
    """Force reserved trials back to 'new' without waiting for staleness.

    The CAS (`expected_status="reserved"` on the write, and the executor's
    `expected_worker` guard on the old owner's next write) keeps a racing
    live worker safe: whichever side loses the CAS abandons its claim.
    """
    if not args.name:
        raise SystemExit("db release needs an experiment name (-n/--name)")
    if ledger.load_experiment(args.name) is None:
        raise SystemExit(f"no such experiment: {args.name}")
    reserved = ledger.fetch(args.name, status="reserved")
    if args.trial_id:
        reserved = [_resolve_trial_prefix(reserved, args.trial_id,
                                          "reserved trial")]
    released = 0
    for t in reserved:
        t.reset_to_new()
        if ledger.update_trial(t, expected_status="reserved"):
            released += 1
    print(f"released {released} trial(s)")
    return 0


def _cmd_db(args, cfg: Dict[str, Any]) -> int:
    """ref: the lineage's `db test` — validate a live backend end-to-end.

    Drives the coordination contract against the *configured* ledger (the
    one production would use), with a throwaway experiment name. Exit 0
    iff every check passed.
    """
    import time as _time

    from metaopt_tpu.ledger.backends import (
        DuplicateExperimentError,
        DuplicateTrialError,
    )

    if args.action != "set" and getattr(args, "assignments", None):
        # a stray positional silently ignored is how `db release -n exp
        # TRIALID` (forgot --trial) would release EVERY reservation
        raise SystemExit(
            f"db {args.action} takes no KEY=VALUE arguments, got "
            f"{args.assignments!r}"
        )
    ledger = _make_ledger_from_spec(args.ledger, cfg)
    if args.action == "dump":
        return _db_dump(args, ledger)
    if args.action == "load":
        return _db_load(args, ledger)
    if args.action == "set":
        return _db_set(args, ledger)
    if args.action == "release":
        return _db_release(args, ledger)
    if args.action == "compact":
        if not hasattr(ledger, "compact"):
            raise SystemExit(
                f"backend {type(ledger).__name__} has no compaction "
                "(native and file ledgers keep append-only logs; memory "
                "and coord stores have nothing on disk to fold)"
            )
        names = ([args.name] if args.name
                 else sorted(ledger.list_experiments()))
        total = 0
        for name in names:
            freed = ledger.compact(name)
            total += freed
            print(f"{name}: reclaimed {freed} bytes")
        print(f"total reclaimed: {total} bytes")
        return 0
    if args.action == "rm":
        # ref: `orion db rm` in the lineage — destructive, so --force gates
        if not args.name:
            raise SystemExit("db rm needs an experiment name (-n/--name)")
        doc = ledger.load_experiment(args.name)
        if doc is None:
            raise SystemExit(f"no such experiment: {args.name}")
        n = ledger.count(args.name)
        if not args.force:
            raise SystemExit(
                f"would delete experiment {args.name!r} and its {n} "
                "trial(s); re-run with --force"
            )
        if not ledger.delete_experiment(args.name):
            raise SystemExit(
                f"backend {type(ledger).__name__} does not support deletion"
            )
        print(f"deleted experiment {args.name!r} ({n} trials)")
        return 0

    name = f"_dbtest-{os.getpid()}-{int(os.times().elapsed * 1000)}"
    results: List[tuple] = []

    def check(desc, fn):
        try:
            ok = fn()
            results.append((desc, bool(ok), None))
        except Exception as err:  # a failing backend must not stop the scan
            results.append((desc, False, f"{type(err).__name__}: {err}"))

    doc = {"name": name, "space": {"x": "uniform(0, 1)"},
           "algorithm": {"random": {}}, "max_trials": 1, "version": 1}
    check("create experiment", lambda: ledger.create_experiment(doc) or True)

    def dup_exp():
        try:
            ledger.create_experiment(doc)
            return False
        except DuplicateExperimentError:
            return True
    check("duplicate experiment rejected", dup_exp)
    check("load round-trips", lambda: ledger.load_experiment(name)["name"] == name)
    check("listed", lambda: name in ledger.list_experiments())

    trial = Trial(params={"x": 0.5}, experiment=name)
    check("register trial", lambda: ledger.register(trial) or True)

    def dup_trial():
        try:
            ledger.register(Trial(params={"x": 0.5}, experiment=name,
                                  id=trial.id))
            return False
        except DuplicateTrialError:
            return True
    check("duplicate trial rejected", dup_trial)

    got = {}
    def do_reserve():
        got["t"] = ledger.reserve(name, "dbtest-w1")
        return got["t"] is not None and got["t"].id == trial.id
    check("reserve wins", do_reserve)
    check("second reserve starves", lambda: ledger.reserve(name, "w2") is None)
    check("owner heartbeat", lambda: ledger.heartbeat(name, trial.id, "dbtest-w1"))
    check("foreign heartbeat rejected",
          lambda: not ledger.heartbeat(name, trial.id, "intruder"))

    def stale_cycle():
        t = got["t"]
        t.heartbeat = _time.time() - 10_000
        ledger.update_trial(t)
        released = ledger.release_stale(name, 60.0)
        if not any(r.id == t.id for r in released):
            return False
        again = ledger.reserve(name, "dbtest-w2")
        return again is not None and again.id == t.id
    check("stale release + re-reserve", stale_cycle)

    def push():
        t = ledger.get(name, trial.id)
        t.attach_results([{"name": "o", "type": "objective", "value": 0.25}])
        t.transition("completed")
        return ledger.update_trial(
            t, expected_status="reserved", expected_worker="dbtest-w2"
        )
    check("CAS result push", push)
    check("count by status", lambda: ledger.count(name, "completed") == 1)
    check("fetch filter",
          lambda: [t.objective for t in ledger.fetch(name, "completed")] == [0.25])

    try:
        cleaned = ledger.delete_experiment(name)
    except Exception:
        cleaned = False
    failed = [r for r in results if not r[1]]
    if args.as_json:
        print(json.dumps({
            "backend": type(ledger).__name__,
            "passed": len(results) - len(failed),
            "total": len(results),
            "cleaned": bool(cleaned),
            # name the leftover so a JSON consumer can remove it later
            **({} if cleaned else {"scratch": name}),
            "checks": [{"check": d, "ok": ok, **({"error": e} if e else {})}
                       for d, ok, e in results],
        }, indent=2))
        return 0 if not failed else 1
    for desc, ok, err in results:
        mark = "ok " if ok else "FAIL"
        print(f"  [{mark}] {desc}" + (f" — {err}" if err else ""))
    scratch = ("scratch experiment removed" if cleaned
               else f"scratch experiment {name!r} left on ledger "
                    "(backend has no delete)")
    print(f"{len(results) - len(failed)}/{len(results)} checks passed "
          f"({type(ledger).__name__}; {scratch})")
    return 0 if not failed else 1


def _cmd_web(args, cfg: Dict[str, Any]) -> int:
    from metaopt_tpu.io.webapi import make_server, serve_forever

    ledger = _make_ledger_from_spec(args.ledger, cfg)
    serve_forever(make_server(ledger, host=args.host, port=args.port))
    return 0


def _cmd_serve(args, cfg: Dict[str, Any]) -> int:
    from metaopt_tpu.coord.server import CoordServer, serve_forever

    coord_cfg_early = cfg.get("coordinator") or {}
    shards = (args.shards if args.shards is not None
              else coord_cfg_early.get("shards"))
    if shards:
        if getattr(args, "uds_path", None):
            print("--uds applies to single-process serving; sharded "
                  "deployments route by TCP shard map", file=sys.stderr)
            return 2
        return _serve_sharded(args, coord_cfg_early, int(shards))
    # CLI flags > config file (`ledger:`/`coordinator:` sections) > defaults
    inner = None
    inner_spec = args.ledger
    if inner_spec is None:
        lcfg = cfg.get("ledger") or {}
        if lcfg.get("type", "memory") == "file":
            inner_spec = lcfg.get("path") or os.path.expanduser(
                "~/.metaopt_tpu/ledger"
            )
    if inner_spec and inner_spec != "memory":
        from metaopt_tpu.ledger.backends import make_ledger as _ml

        inner = _ml({"type": "file", "path": inner_spec})
    coord_cfg = cfg.get("coordinator") or {}
    server = CoordServer(
        inner=inner,
        host=args.host if args.host is not None
        else coord_cfg.get("host", "127.0.0.1"),
        port=args.port if args.port is not None else coord_cfg.get("port", 0),
        snapshot_path=args.snapshot_path,
        snapshot_interval_s=args.snapshot_interval_s,
        snapshot_incremental=not getattr(args, "snapshot_full", False),
        archive_segment_rows=(
            args.archive_segment_rows
            if getattr(args, "archive_segment_rows", None) is not None
            else coord_cfg.get("archive_segment_rows")),
        archive_completed=getattr(args, "trial_archive", True),
        stale_timeout_s=args.stale_timeout_s,
        event_log_path=args.event_log_path,
        suggest_prefetch_depth=(
            args.suggest_prefetch_depth
            if args.suggest_prefetch_depth is not None
            else coord_cfg.get("suggest_prefetch_depth", 1)),
        uds_path=args.uds_path or coord_cfg.get("uds_path"),
        max_experiments=(args.max_experiments
                         if args.max_experiments is not None
                         else coord_cfg.get("max_experiments")),
        max_experiments_per_tenant=(
            args.max_experiments_per_tenant
            if args.max_experiments_per_tenant is not None
            else coord_cfg.get("max_experiments_per_tenant")),
        evict_idle_s=(args.evict_idle_s if args.evict_idle_s is not None
                      else coord_cfg.get("evict_idle_s")),
        max_resident=(args.max_resident if args.max_resident is not None
                      else coord_cfg.get("max_resident")),
        tenant_weights=_tenant_weights(args, coord_cfg),
        fuse_suggest=(args.fuse_suggest
                      if args.fuse_suggest is not None
                      else bool(coord_cfg.get("fuse_suggest", False))),
        fuse_bucket_max=(args.fuse_bucket_max
                         if args.fuse_bucket_max is not None
                         else coord_cfg.get("fuse_bucket_max", 32)),
    )
    serve_forever(server)
    return 0


def _tenant_weights(args, coord_cfg: Dict[str, Any]):
    """--tenant-weights JSON > the config file's coordinator section."""
    if getattr(args, "tenant_weights", None):
        import json as _json

        weights = _json.loads(args.tenant_weights)
        if not isinstance(weights, dict):
            raise SystemExit("--tenant-weights must be a JSON object "
                             "mapping tenant -> weight")
        return {str(k): float(v) for k, v in weights.items()}
    return coord_cfg.get("tenant_weights")


def _serve_sharded(args, coord_cfg: Dict[str, Any], n_shards: int) -> int:
    """``mtpu serve --shards N``: supervisor + router until SIGINT/SIGTERM.

    Each shard is a subprocess CoordServer with its own snapshot + WAL
    under the ``--snapshot`` DIRECTORY; the public port serves old
    clients through the router while new clients learn the shard map
    from any ping and route directly.
    """
    import signal
    import threading

    from metaopt_tpu.coord.shards import ShardSupervisor

    if args.ledger and args.ledger != "memory":
        print("--shards serves the in-memory inner ledger only; per-shard "
              "durability comes from the --snapshot directory (one "
              "snapshot+WAL per shard), not a shared file ledger",
              file=sys.stderr)
        return 2
    sup = ShardSupervisor(
        n_shards,
        host=args.host if args.host is not None
        else coord_cfg.get("host", "127.0.0.1"),
        port=args.port if args.port is not None
        else coord_cfg.get("port", 0),
        snapshot_dir=args.snapshot_path,
        snapshot_interval_s=args.snapshot_interval_s,
        stale_timeout_s=args.stale_timeout_s,
        suggest_prefetch_depth=(
            args.suggest_prefetch_depth
            if args.suggest_prefetch_depth is not None
            else coord_cfg.get("suggest_prefetch_depth", 1)),
        event_log_dir=args.event_log_path,
        max_experiments=(args.max_experiments
                         if args.max_experiments is not None
                         else coord_cfg.get("max_experiments")),
        max_experiments_per_tenant=(
            args.max_experiments_per_tenant
            if args.max_experiments_per_tenant is not None
            else coord_cfg.get("max_experiments_per_tenant")),
        evict_idle_s=(args.evict_idle_s if args.evict_idle_s is not None
                      else coord_cfg.get("evict_idle_s")),
        max_resident=(args.max_resident if args.max_resident is not None
                      else coord_cfg.get("max_resident")),
        tenant_weights=_tenant_weights(args, coord_cfg),
        fuse_suggest=(args.fuse_suggest
                      if args.fuse_suggest is not None
                      else bool(coord_cfg.get("fuse_suggest", False))),
        fuse_bucket_max=(args.fuse_bucket_max
                         if args.fuse_bucket_max is not None
                         else coord_cfg.get("fuse_bucket_max")),
    )
    stop = threading.Event()
    prev = signal.signal(signal.SIGTERM, lambda *_: stop.set())
    sup.start()
    host, port = sup.address
    members = ", ".join(f"{sid}=coord://{h}:{p}"
                        for sid, (h, p) in sup.shard_addresses().items())
    print(f"coordinator ready at coord://{host}:{port} "
          f"({n_shards} shards: {members})", flush=True)
    try:
        while not stop.wait(1.0):
            pass
    except KeyboardInterrupt:
        pass
    finally:
        sup.stop()
        signal.signal(signal.SIGTERM, prev)
    return 0


def _cmd_rebalance(args, cfg: Dict[str, Any]) -> int:
    """``mtpu rebalance``: live-migrate one experiment between shards.

    Learns the shard map from any address's ping, computes the
    version-bumped map pinning the experiment to ``--dest``, and drives
    the prepare→ship→apply→commit protocol from this process — the same
    primitive supervisor failover uses (ARCHITECTURE.md "Hand-off &
    failover").
    """
    from metaopt_tpu.coord.handoff import (
        HandoffError, call_admin, migrate_experiment,
    )
    from metaopt_tpu.coord.shards import RoutingTable, with_override

    host, _, port = args.coord.rpartition(":")
    if not host or not port.isdigit():
        print(f"--coord must be HOST:PORT, got {args.coord!r}",
              file=sys.stderr)
        return 2
    seed = (host, int(port))
    try:
        reply = call_admin(seed, "ping", {}, window_s=args.window_s)
    except HandoffError as err:
        print(err, file=sys.stderr)
        return 1
    smap = (reply.get("result") or {}).get("shard_map") \
        if reply.get("ok") else None
    if not smap:
        print(f"{args.coord} does not advertise a shard map — not a "
              "sharded deployment?", file=sys.stderr)
        return 2
    table = RoutingTable(smap)
    if args.dest not in table.addrs:
        print(f"unknown destination shard {args.dest!r}; map has: "
              f"{', '.join(sorted(table.addrs))}", file=sys.stderr)
        return 2
    source = table.owner(args.experiment)
    if source == args.dest:
        print(f"{args.experiment} already lives on {args.dest}; nothing "
              "to do")
        return 0
    new_map = with_override(smap, args.experiment, args.dest)
    try:
        result = migrate_experiment(
            args.experiment, table.addrs[source], table.addrs[args.dest],
            args.dest, new_map,
            other_addrs=[a for sid, a in table.addrs.items()
                         if sid not in (source, args.dest)],
            drain_timeout_s=args.drain_timeout_s, window_s=args.window_s)
    except HandoffError as err:
        print(f"rebalance failed: {err}", file=sys.stderr)
        return 1
    print(f"{args.experiment}: {source} -> {args.dest} "
          f"({result.get('trials', 0)} trials, "
          f"{result.get('replies', 0)} cached replies, "
          f"map v{result.get('map_version')})")
    return 0


def _cmd_benchmark(args, cfg) -> int:
    """Run one study (task × assessment) across the requested algorithms."""
    from metaopt_tpu.benchmark import (
        AverageRank, AverageResult, Benchmark, Hypervolume,
        ParallelAssessment, task_registry,
    )

    try:
        task_cls = task_registry.get(args.task)
    except KeyError:
        print(f"unknown task {args.task!r}; have: "
              f"{', '.join(sorted(task_registry))}", file=sys.stderr)
        return 2
    if args.assessment == "parallel":
        try:
            assess = ParallelAssessment(args.repetitions,
                                        worker_counts=args.workers)
        except ValueError as err:
            print(err, file=sys.stderr)
            return 2
    else:
        assess = {"rank": AverageRank, "hypervolume": Hypervolume}.get(
            args.assessment, AverageResult)(args.repetitions)
    task = task_cls(args.max_trials)
    if isinstance(assess, Hypervolume):
        try:  # detectable BEFORE any trial runs — don't waste a study
            assess.resolve_reference(task)
        except ValueError as err:
            print(err, file=sys.stderr)
            return 2
    bench = Benchmark(
        "cli",
        algorithms=list(args.algos),
        targets=[{"assess": [assess], "task": [task]}],
    )
    bench.process()
    (report,) = bench.analysis()
    if args.as_json:
        print(json.dumps(report, indent=2))
        return 0
    print(f"task: {report['task']}  assessment: {report['assessment']}  "
          f"repetitions: {report['repetitions']}")
    def _num(v):  # an algorithm with zero completed trials prints n/a
        return f"{v:.6g}" if v is not None else "n/a"

    if "final_best" in report:
        width = max(len(a) for a in args.algos)
        finals = report["final_best"]
        for algo in sorted(finals,
                           key=lambda a: (finals[a] is None,
                                          finals[a] or 0.0)):
            print(f"  {algo:<{width}}  final best = {_num(finals[algo])}")
    if "ranks" in report:
        width = max(len(a) for a in args.algos)
        for algo in sorted(report["ranks"], key=lambda a: report["ranks"][a]):
            print(f"  {algo:<{width}}  mean rank = {report['ranks'][algo]:.2f}")
    if "final_hypervolume" in report:
        width = max(len(a) for a in args.algos)
        finals = report["final_hypervolume"]
        for algo in sorted(finals, key=lambda a: (finals[a] is None,
                                                  -(finals[a] or 0.0))):
            print(f"  {algo:<{width}}  final hypervolume = "
                  f"{_num(finals[algo])}")
    if "algorithms" in report:  # parallel assessment table
        for algo, rows in sorted(report["algorithms"].items()):
            print(f"  {algo}:")
            for wkey, row in sorted(
                    rows.items(), key=lambda kv: int(kv[0][1:])):
                line = (f"    {wkey:<4} final best = "
                        f"{_num(row['final_best'])}")
                if row.get("mean_wall_s") is not None:
                    line += f", wall {row['mean_wall_s']:.2f}s"
                if "speedup_vs_1w" in row:
                    line += (f", speedup {row['speedup_vs_1w']}x "
                             f"(eff {row['efficiency']})")
                if "regret_penalty_vs_1w" in row:
                    line += (f", regret penalty "
                             f"{_num(row['regret_penalty_vs_1w'])}")
                print(line)
    print(f"winner: {report['winner']}")
    return 0


def _cmd_lint(args: argparse.Namespace, cfg: Dict[str, Any]) -> int:
    from metaopt_tpu.analysis.runner import lint_main

    argv: List[str] = list(args.paths or [])
    if args.baseline:
        argv += ["--baseline", args.baseline]
    if args.update_baseline:
        argv.append("--update-baseline")
    if args.no_baseline:
        argv.append("--no-baseline")
    argv += ["--format", args.lint_format]
    return lint_main(argv)


def _cmd_simulate(args: argparse.Namespace, cfg: Dict[str, Any]) -> int:
    """``mtpu simulate``: run one scale-certification scenario.

    Exit code 0 = certified (no promotion violations, no acked-write
    loss, no exactly-once violations); 1 = certification failed.
    """
    from metaopt_tpu.sim.engine import (
        DEFAULT_FAULTS, SimConfig, Simulation,
    )

    sim_cfg = SimConfig(
        workers=args.workers,
        tenants=args.tenants,
        experiments_per_tenant=args.experiments_per_tenant,
        algos=tuple(args.algos),
        task=args.task,
        max_trials=args.sim_max_trials,
        pool_size=args.sim_pool_size,
        seed=args.seed,
        faults=DEFAULT_FAULTS if args.faults is None else args.faults,
        stale_timeout_s=args.sim_stale_timeout_s,
        max_virtual_s=args.max_virtual_s,
        event_log=args.sim_event_log,
    )
    report = Simulation(sim_cfg).run()
    if args.as_json:
        print(json.dumps(report.to_dict(), indent=2, sort_keys=True))
        return 0 if report.ok else 1
    r = report
    print(f"simulated {r.config['workers']} workers / {r.experiments} "
          f"experiments / {r.config['tenants']} tenants "
          f"({'+'.join(r.config['algos'])})")
    print(f"  virtual {r.virtual_s:.0f}s in wall {r.wall_s:.1f}s — "
          f"{r.dispatches} coordinator dispatches")
    print(f"  completed {r.acked_completions} trials "
          f"({r.cas_rejected_completions} delayed completions rejected, "
          f"{r.stale_released} stale released, {r.worker_deaths} worker "
          f"deaths, {r.crashes} coordinator crashes)")
    print(f"  fairness: jain={r.jain} over {r.completed_by_tenant}")
    if r.recoveries:
        print(f"  recovery: {r.recovery_s_per_10k_wal}s/10k WAL records "
              f"across {len(r.recoveries)} crash(es)")
    for name in sorted(r.best_by_experiment):
        print(f"  best {name}: {r.best_by_experiment[name]:.6f}")
    print(f"  event log: {r.event_lines} events "
          f"sha256={r.event_log_sha256[:16]}…")
    problems = (r.promotion_violations + r.acked_write_losses
                + r.exactly_once_violations)
    if problems:
        print(f"CERTIFICATION FAILED ({len(problems)} violation(s)):")
        for p in problems:
            print(f"  ✗ {p}")
        return 1
    print("certified: promotion invariants, zero acked-write loss, "
          "exactly-once replies")
    return 0


def _cmd_race(args: argparse.Namespace, cfg: Dict[str, Any]) -> int:
    from metaopt_tpu.analysis.runner import race_main

    argv: List[str] = []
    for s in args.suite or []:
        argv += ["--suite", s]
    if args.scale != 1:
        argv += ["--scale", str(args.scale)]
    if args.static_only:
        argv.append("--static-only")
    if args.baseline:
        argv += ["--baseline", args.baseline]
    if args.update_baseline:
        argv.append("--update-baseline")
    if args.no_baseline:
        argv.append("--no-baseline")
    argv += ["--format", args.race_format]
    return race_main(argv)


def _cmd_crashcheck(args: argparse.Namespace, cfg: Dict[str, Any]) -> int:
    from metaopt_tpu.analysis.runner import crashcheck_main

    argv: List[str] = []
    for s in args.suite or []:
        argv += ["--suite", s]
    if args.static_only:
        argv.append("--static-only")
    if args.baseline:
        argv += ["--baseline", args.baseline]
    if args.update_baseline:
        argv.append("--update-baseline")
    if args.no_baseline:
        argv.append("--no-baseline")
    argv += ["--format", args.crash_format]
    return crashcheck_main(argv)


def _cmd_analyze(args: argparse.Namespace, cfg: Dict[str, Any]) -> int:
    from metaopt_tpu.analysis.runner import analyze_main

    argv: List[str] = list(args.paths or [])
    if args.no_baseline:
        argv.append("--no-baseline")
    argv += ["--format", args.analyze_format]
    return analyze_main(argv)


_COMMANDS = {
    "hunt": _cmd_hunt,
    "lint": _cmd_lint,
    "race": _cmd_race,
    "crashcheck": _cmd_crashcheck,
    "analyze": _cmd_analyze,
    "benchmark": _cmd_benchmark,
    "init-only": _cmd_init_only,
    "insert": _cmd_insert,
    "db": _cmd_db,
    "info": _cmd_info,
    "list": _cmd_list,
    "tenants": _cmd_tenants,
    "plot": _cmd_plot,
    "resume": _cmd_resume,
    "status": _cmd_status,
    "rebalance": _cmd_rebalance,
    "serve": _cmd_serve,
    "simulate": _cmd_simulate,
    "web": _cmd_web,
}


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args, extras = parser.parse_known_args(argv)
    if extras:
        # Python 3.10 argparse leaves trailing positionals unmatched when an
        # (empty) nargs="*" positional precedes the optionals, as in
        # `db set -n exp max_trials=50`; reclaim them for the db KEY=VALUE
        # tail and reject anything else as argparse would.
        if getattr(args, "command", None) == "db" and all(
            not e.startswith("-") for e in extras
        ):
            args.assignments = list(getattr(args, "assignments", None) or [])
            args.assignments += extras
        elif getattr(args, "command", None) in ("lint", "analyze") and all(
            not e.startswith("-") for e in extras
        ):
            # same 3.10 nargs="*" quirk for `lint --format json PATH`
            args.paths = list(getattr(args, "paths", None) or []) + extras
        else:
            parser.error("unrecognized arguments: %s" % " ".join(extras))
    level = [logging.WARNING, logging.INFO, logging.DEBUG][min(args.verbose, 2)]
    logging.basicConfig(
        level=level, format="%(asctime)s %(name)s %(levelname)s %(message)s"
    )
    cfg = resolve_config(
        {
            "name": getattr(args, "name", None),
            "max_trials": getattr(args, "max_trials", None),
            "pool_size": getattr(args, "pool_size", None),
        },
        getattr(args, "config", None),
    )
    try:
        return _COMMANDS[args.command](args, cfg)
    except KeyboardInterrupt:
        print("interrupted", file=sys.stderr)
        return 130
    except BrokenPipeError:
        # `mtpu status | head` closing stdout early is not an error; die
        # quietly the way POSIX tools do (devnull swap: the interpreter
        # would otherwise warn while flushing the dead stdout at exit)
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
        return 0
