"""Command-line interface.

ref: src/metaopt/core/cli/ (SURVEY.md §2.5) — the hunt-style invocation is
the product's signature UX and is preserved:

    mtpu hunt -n exp ./train.py --lr~'loguniform(1e-5, 1e-1)'

Subcommands: hunt, init-only, insert, status (the lineage's early set plus
the status reader the lineage grew later; SURVEY.md §5 observability).
"""

from metaopt_tpu.cli.main import build_parser, main

__all__ = ["main", "build_parser"]
