"""Declared invariants the checkers enforce — the repo's "lockdep map".

Everything here is *declaration*, not detection: which attribute is
guarded by which lock, which locks must never be held across blocking
calls, which dotted names count as blocking / ambient / host-sync, and
how the durability registry in ``coord/protocol.py`` maps onto the
server's op sets. Tests build small configs of the same shape for their
fixture modules, so the checkers stay config-driven and hermetic.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from metaopt_tpu.analysis.core import LintModule

#: pseudo-lock for the server's per-experiment RLock family — every
#: ``_exp_lock(name)`` / ``_op_lock(op, a)`` result is one node, since
#: ordering hazards are against the family, not one instance
EXP_LOCK = "EXP"


@dataclass
class LintConfig:
    """Knobs + declarations for one lint run."""

    # -- lock discipline ---------------------------------------------------
    #: {ClassName: {attr_name}} — attributes that ARE locks; ``with
    #: self.<attr>:`` acquires node "ClassName.<attr>". Classes not listed
    #: fall back to a name heuristic (suffix lock/guard/cv/mutex).
    lock_attrs: Dict[str, Set[str]] = field(default_factory=dict)
    #: {method_name: (returned_lock_node, [locks taken inside the call])}
    #: for lock *factories*: ``with self._exp_lock(n):`` acquires EXP and
    #: briefly takes _exp_locks_guard internally.
    lock_factories: Dict[str, Tuple[str, List[str]]] = field(
        default_factory=dict)
    #: lock nodes that must never be held across a blocking call
    no_block_locks: Set[str] = field(default_factory=set)
    #: dotted-name suffixes that count as blocking (matched against the
    #: call's dotted name tail)
    blocking_calls: Set[str] = field(default_factory=lambda: {
        "os.fsync", "fsync_dir", "time.sleep", "sleep",
        "sendall", "recv", "recv_into", "accept", "connect",
        "recv_msg", "send_msg", "send_payload",
        "subprocess.run", "subprocess.check_call",
        "subprocess.check_output", "communicate",
    })
    #: {ClassName: {attr: guard_lock_node}} — shared state and its guard.
    #: Writes (assign / augassign / del / mutating method call) outside a
    #: ``with <guard>`` block (or a ``holds(<guard>)`` pragma) are MTL003.
    guarded_attrs: Dict[str, Dict[str, str]] = field(default_factory=dict)
    #: methods where unguarded writes are allowed (single-threaded phases)
    init_methods: Set[str] = field(
        default_factory=lambda: {"__init__", "__new__",
                                 "_init_suggest_ahead"})
    #: receiver-name roles for cross-class call resolution:
    #: "proxy" = the server's sharded-ledger proxy (mutators acquire EXP
    #: and journal to the WAL buffer), "wal" = WriteAheadLog, "backend" =
    #: the in-memory ledger backend class.
    receiver_roles: Dict[str, str] = field(default_factory=dict)
    #: class names backing each role (resolution targets)
    wal_class: str = "WriteAheadLog"
    backend_class: str = "MemoryLedger"
    #: ledger proxy method sets (mirror _ShardedLedger; overridable)
    proxy_lock_free: FrozenSet[str] = frozenset({
        "get", "fetch", "count", "fetch_completed_since",
        "load_experiment", "list_experiments", "export_docs",
    })
    proxy_mutators: FrozenSet[str] = frozenset({
        "create_experiment", "update_experiment", "delete_experiment",
        "register", "reserve", "update_trial", "release_stale",
    })
    #: classes the bare-name fallback must never resolve into — the RPC
    #: client mirrors the LedgerBackend API by design, and resolving a
    #: server-internal backend call to the client's socket methods would
    #: manufacture phantom blocking edges
    no_fallback_classes: Set[str] = field(default_factory=set)
    #: container/stdlib method names never resolved to scanned functions
    #: (avoids ``self._pending.append`` aliasing WriteAheadLog.append)
    never_resolve: Set[str] = field(default_factory=lambda: {
        "append", "add", "get", "pop", "popleft", "update", "setdefault",
        "extend", "remove", "discard", "clear", "items", "keys",
        "values", "join", "split", "strip", "put", "get_nowait",
        "encode", "decode", "close", "copy", "sort", "insert", "count",
        "wait", "notify", "notify_all", "acquire", "release", "set",
        "is_set", "todict", "to_dict", "from_dict", "write", "read",
        "flush", "fileno",
    })

    # -- JAX hygiene -------------------------------------------------------
    #: dotted-name tails that read ambient mutable context (MTJ002 inside
    #: jit-traced code)
    ambient_getters: Set[str] = field(default_factory=lambda: {
        "active_mesh", "os.environ.get", "os.getenv", "environ.get",
        "time.time", "time.monotonic", "datetime.now", "faults.fire",
    })
    #: dotted-name tails that synchronize device->host (MTJ003 inside
    #: ``# mtpu: hotpath`` functions)
    host_sync_calls: Set[str] = field(default_factory=lambda: {
        "np.asarray", "np.array", "numpy.asarray", "numpy.array",
        "jax.device_get", "device_get", "block_until_ready", "item",
        "float", "int", "bool",
    })
    #: functions treated as hot even without a pragma ("Class.fn" or "fn")
    hotpath_registry: Set[str] = field(default_factory=set)

    # -- durability contract ----------------------------------------------
    #: ops whose dispatch branch must reach a journal call (None = read
    #: the registry from the scanned protocol module)
    journaled_ops: Optional[FrozenSet[str]] = None
    reply_journaled_ops: Optional[FrozenSet[str]] = None
    nested_journaled_ops: Optional[FrozenSet[str]] = None
    #: module basename holding the registry declarations
    protocol_module: str = "protocol.py"
    #: dispatch/handler structure in the server class
    dispatch_function: str = "_dispatch"
    dispatch_op_var: str = "op"
    journal_call_names: Set[str] = field(default_factory=lambda: {
        "_journal_mutation", "_journal_reply", "append",
    })
    journal_receivers: Set[str] = field(default_factory=lambda: {
        "_wal", "wal",
    })
    #: binary-wire op→opcode table for MTD004 (None = parse WIRE_OPCODES
    #: from whichever scanned module declares it; a scan with no
    #: declaration skips the check)
    wire_opcodes: Optional[Dict[str, int]] = None


def registry_frozensets(mod: LintModule, names: Set[str]
                        ) -> Dict[str, FrozenSet[str]]:
    """Extract ``NAME = frozenset({...})`` string-set declarations (module
    or class level) from a parsed module — used to read the protocol
    registry and the server's op sets without importing anything."""
    out: Dict[str, FrozenSet[str]] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name) or tgt.id not in names:
            continue
        try:
            val = ast.literal_eval(ast.Expression(body=_strip_frozenset(
                node.value)))
        except (ValueError, SyntaxError):
            continue
        if isinstance(val, (set, frozenset, tuple, list)) and all(
                isinstance(v, str) for v in val):
            out[tgt.id] = frozenset(val)
    return out


def _strip_frozenset(node: ast.AST) -> ast.AST:
    """``frozenset({...})`` / ``frozenset((...))`` -> the inner literal."""
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == "frozenset" and node.args):
        return node.args[0]
    return node


@dataclass
class RaceConfig:
    """Declarations specific to ``mtpu race`` (the dynamic detector and
    the MTR001 shared-attribute check). Kept separate from
    :class:`LintConfig` because the race side needs *imports* (live
    classes to hook) where lint needs only ASTs."""

    #: {ClassName: "module.path"} — classes whose instances get
    #: ``__setattr__``/``__getattribute__`` hooks under instrumentation.
    #: Monitored attrs = guarded_attrs merged down the MRO (a mixin's
    #: declarations apply to every adopter) plus ``extra_monitored``.
    monitor_modules: Dict[str, str] = field(default_factory=dict)
    #: {ClassName: {attr}} — monitored dynamically without a lint guard
    #: declaration (e.g. attrs protected by happens-before, not a lock)
    extra_monitored: Dict[str, Set[str]] = field(default_factory=dict)
    #: {(ClassName, attr)} — excluded from dynamic monitoring AND from
    #: MTR001, with the doctrine recorded here. Use sparingly.
    race_exempt: Set[Tuple[str, str]] = field(default_factory=set)
    #: extra thread-entry-point qualnames for the static shared-attribute
    #: computation, beyond the ``Thread(target=...)``/``_spawn`` targets
    #: found in the AST (RPC handlers run on connection threads; client
    #: methods run on arbitrary caller threads).
    entry_points: Set[str] = field(default_factory=set)


def default_race_config() -> RaceConfig:
    """Checked-in race-detection declarations for this repository.

    Exemption doctrine (each entry is a *deliberate* lock-free pattern,
    not an oversight):

    * ``CoordServer._mut`` — per-experiment mutation counters. Written
      under EXP (``_mutated`` holds the experiment lock); the delta-read
      fast path polls it lock-free as a freshness hint, tolerating a
      stale value by design (a stale read serves a slightly old delta,
      never a wrong one). GIL-atomic int store; declared for MTL003 but
      exempt from the dynamic read/write check.
    * ``WriteAheadLog._appended`` — monotone telemetry counter written
      under ``_buf_lock`` and read lock-free by ``stats()``/tests as a
      progress probe; same stale-tolerant doctrine.
    * ``WriteAheadLog._failed`` — sticky degradation flag. Writes are
      fenced under ``_cv`` (MTL003 enforces this); the ``append()`` hot
      path reads it lock-free because a stale False merely buffers one
      more record that the next sync() will reject.
    * ``WriteAheadLog._f`` — the file handle. Mutual exclusion is the
      ``_syncing`` leader flag elected under ``_cv`` (exactly one thread
      does I/O at a time); open()/close() are lifecycle phases.
    * ``CoordServer._ops`` — ops-served telemetry snapshot returned by
      ping; GIL-atomic int store, stale reads are the contract.
    * ``CoordServer._sock`` / ``_uds_sock`` / ``_threads`` /
      ``_prev_switchinterval`` / ``_wal`` — start()/stop()/recovery
      lifecycle attrs, written before serving threads exist or after
      they are joined. The static check
      accuses them because the bare-name call graph resolves any
      ``x.start()`` into ``CoordServer.start`` (and ``self._wal.append``
      counts as a container write to ``_wal``).
    * ``ShardRouter._sock`` / ``_threads`` and ``ShardSupervisor
      ._shard_ports`` / ``router`` / ``_watcher`` — the same
      start()/stop() lifecycle pattern: written before the accept /
      watcher threads exist or after they are joined; accused only via
      the bare-name ``start()`` call-graph collapse. (``ShardSupervisor
      .shard_map`` left this list when hand-off/failover started
      rewriting it from watcher/failover threads — it is now guarded by
      ``_procs_lock`` and declared so.)
    """
    rc = RaceConfig()
    rc.monitor_modules = {
        "CoordServer": "metaopt_tpu.coord.server",
        "WriteAheadLog": "metaopt_tpu.coord.wal",
        "CoordLedgerClient": "metaopt_tpu.coord.client_backend",
        "MemoryLedger": "metaopt_tpu.ledger.backends",
        "ExperimentArchive": "metaopt_tpu.ledger.archive",
        "CMAES": "metaopt_tpu.algo.cmaes",
        "ShardRouter": "metaopt_tpu.coord.shards",
        "ShardSupervisor": "metaopt_tpu.coord.shards",
        "BatchedExecutor": "metaopt_tpu.executor.batched",
        "VirtualClock": "metaopt_tpu.sim.clock",
        "SuggestFuser": "metaopt_tpu.coord.fuser",
    }
    rc.race_exempt = {
        ("CoordServer", "_mut"),
        ("CoordServer", "_ops"),
        ("CoordServer", "_sock"),
        ("CoordServer", "_uds_sock"),
        ("CoordServer", "_threads"),
        ("CoordServer", "_prev_switchinterval"),
        ("CoordServer", "_wal"),
        ("WriteAheadLog", "_appended"),
        ("WriteAheadLog", "_failed"),
        ("WriteAheadLog", "_f"),
        ("ShardRouter", "_sock"),
        ("ShardRouter", "_threads"),
        ("ShardSupervisor", "_shard_ports"),
        ("ShardSupervisor", "router"),
        ("ShardSupervisor", "_watcher"),
    }
    rc.entry_points = {
        # every RPC runs on a per-connection thread
        "CoordServer._handle",
        # WAL group commit runs on caller threads (no background thread)
        "WriteAheadLog.append", "WriteAheadLog.sync",
        # client methods run on arbitrary worker threads
        "CoordLedgerClient.worker_cycle",
        # router relays run on per-connection threads; the supervisor's
        # watcher and per-shard drain threads touch the proc bookkeeping
        "ShardRouter._serve_conn",
        "ShardSupervisor._watch",
        "ShardSupervisor._drain",
        # failover redistribution runs on its own per-dead-shard thread
        "ShardSupervisor._failover_shard",
        # a shared executor's pool evaluations run on worker threads
        "BatchedExecutor.execute_batch",
        # the fused suggest sweep runs on the server housekeeping thread,
        # racing per-experiment suggest/observe on RPC threads
        "SuggestFuser.tick",
    }
    return rc


def default_config() -> LintConfig:
    """The checked-in declarations for this repository.

    Lock nodes are "ClassName.attr" (so ``MemoryLedger._lock`` and the
    server's global ``_lock`` stay distinct) plus the EXP pseudo-node for
    the per-experiment RLock family.

    Deliberately NOT in ``no_block_locks``:

    * ``CoordServer._snap_lock`` — exists to serialize snapshot file
      writes; fsync under it is its whole job.
    * ``CoordLedgerClient._lock`` — serializes RPCs on the shared socket;
      send/recv under it is the design.
    * ``WriteAheadLog._cv`` — a Condition; ``wait`` releases it, and the
      group-commit leader does its I/O under the ``_syncing`` flag, not
      under the cv.
    """
    cfg = LintConfig()
    cfg.lock_attrs = {
        "CoordServer": {
            "_lock", "_exp_locks_guard", "_snap_lock", "_sig_lock",
            "_replies_lock", "_inflight_lock", "_enc_lock",
            "_producers_guard", "_map_cv", "_tenant_lock", "_evict_lock",
        },
        "WriteAheadLog": {"_buf_lock", "_cv"},
        "CoordLedgerClient": {"_lock", "_caps_lock", "_live_lock",
                              "_io_lock"},
        "MemoryLedger": {"_lock"},
        "ExperimentArchive": {"_seg_lock"},
        "_ProduceCoalescer": {"_guard"},
        "SuggestAhead": {"_ahead_lock"},
        "ShardRouter": {"_conns_lock", "_map_lock"},
        "ShardSupervisor": {"_procs_lock"},
        "BatchedExecutor": {"_tel_lock"},
        "VirtualClock": {"_lock"},
        "SuggestFuser": {"_lock"},
    }
    cfg.lock_factories = {
        "_exp_lock": (EXP_LOCK, ["CoordServer._exp_locks_guard"]),
        "_op_lock": (EXP_LOCK, ["CoordServer._exp_locks_guard"]),
    }
    cfg.no_block_locks = {
        EXP_LOCK,
        "CoordServer._lock",
        "CoordServer._exp_locks_guard",
        "CoordServer._sig_lock",
        "CoordServer._replies_lock",
        "CoordServer._inflight_lock",
        "CoordServer._enc_lock",
        "CoordServer._producers_guard",
        "WriteAheadLog._buf_lock",
        "MemoryLedger._lock",
        # columnar seal/decode only — pure in-memory work, no I/O under it
        "ExperimentArchive._seg_lock",
        "CoordLedgerClient._caps_lock",
        "CoordLedgerClient._live_lock",
        # both guard only in-memory container snapshots; socket shutdown /
        # proc wait / spawn all happen outside the lock
        "ShardRouter._conns_lock",
        "ShardSupervisor._procs_lock",
        # routing-table swap only; connect() happens after the snapshot
        # read releases it. (CoordServer._map_cv deliberately absent:
        # handoff_prepare WAITS on it for the in-flight drain.)
        "ShardRouter._map_lock",
        # telemetry counter increments only; the vmap launch itself runs
        # outside the lock
        "BatchedExecutor._tel_lock",
        # wire-byte counter increments only; the socket send/recv happen
        # under _lock, not under this one
        "CoordLedgerClient._io_lock",
        # tenancy map + scheduler arithmetic only (the scheduler is
        # lock-free by design and serialized entirely under this lock)
        "CoordServer._tenant_lock",
        # residency bookkeeping dicts only; evict-file I/O and the WAL
        # sync happen between acquisitions, never under it
        "CoordServer._evict_lock",
        # pure float arithmetic on the virtual "now"; a threaded server
        # on a virtual clock takes it on every time()/monotonic() read
        "VirtualClock._lock",
        # telemetry counter rollup only; snapshots, bucket launches, and
        # commits all run BEFORE the lock is taken (fuse() holds member
        # launch locks during the sweep, never the fuser's own lock)
        "SuggestFuser._lock",
    }
    cfg.guarded_attrs = {
        "CoordServer": {
            # reply cache (exactly-once): request-id -> reply
            "_replies": "CoordServer._replies_lock",
            # reply→experiment attribution (shipped with a hand-off)
            "_reply_exps": "CoordServer._replies_lock",
            "_exp_locks": "CoordServer._exp_locks_guard",
            "_signals": "CoordServer._sig_lock",
            "_inflight": "CoordServer._inflight_lock",
            "_enc_cache": "CoordServer._enc_lock",
            "_enc_hits": "CoordServer._enc_lock",
            "_producers": "CoordServer._producers_guard",
            "_coalescers": "CoordServer._producers_guard",
            # per-experiment mutation counters for the delta-read path;
            # written only while holding the experiment's lock
            "_mut": EXP_LOCK,
            # hand-off plane: the migration fence, the per-experiment
            # in-flight counts the drain waits on, and the shard map /
            # routing table the ownership commit swaps
            "_migrating": "CoordServer._map_cv",
            "_exp_inflight": "CoordServer._map_cv",
            "shard_map": "CoordServer._map_cv",
            "_ring": "CoordServer._map_cv",
            # multi-tenant service plane: experiment→tenant map + the
            # fair-produce scheduler (lock-free internally, serialized
            # here), and the residency stubs/touch-stamps/counters
            "_tenant_of": "CoordServer._tenant_lock",
            "_sched": "CoordServer._tenant_lock",
            "_evicted": "CoordServer._evict_lock",
            "_exp_last_touch": "CoordServer._evict_lock",
            "_evictions": "CoordServer._evict_lock",
            "_hydrations": "CoordServer._evict_lock",
            # incremental-snapshot state: the per-experiment section cache
            # and the segment-id → on-disk-file dedup map, touched by the
            # housekeeping snapshot and on-demand snapshot RPCs alike
            "_snap_sections": "CoordServer._snap_lock",
            "_seg_on_disk": "CoordServer._snap_lock",
        },
        "WriteAheadLog": {
            "_pending": "WriteAheadLog._buf_lock",
            "_next_seq": "WriteAheadLog._buf_lock",
            "_appended": "WriteAheadLog._buf_lock",
            "_durable": "WriteAheadLog._cv",
            "_syncing": "WriteAheadLog._cv",
            # sticky failure flag: latecomers poll it under the cv, so
            # every publication must be fenced the same way as _durable
            "_failed": "WriteAheadLog._cv",
            # batch/record telemetry incremented per group commit
            "batches": "WriteAheadLog._buf_lock",
            "records": "WriteAheadLog._buf_lock",
            # open compaction fences (hand-off tail extraction): compact()
            # polls it under the cv exactly like _syncing
            "_fence": "WriteAheadLog._cv",
            # per-thread fence depths (re-entrancy: a fence holder's own
            # compact() must not deadlock on its own fence)
            "_fence_owners": "WriteAheadLog._cv",
        },
        "CoordLedgerClient": {
            "_caps": "CoordLedgerClient._caps_lock",
            "_incarnation": "CoordLedgerClient._caps_lock",
            "_live": "CoordLedgerClient._live_lock",
            # shard-routing state learned from ping caps: the map/ring and
            # per-address incarnations are read by every routed call and
            # rewritten by ping/_after_reconnect on any thread
            "_shard_map": "CoordLedgerClient._caps_lock",
            "_ring": "CoordLedgerClient._caps_lock",
            "_shard_addrs": "CoordLedgerClient._caps_lock",
            "_incarnations": "CoordLedgerClient._caps_lock",
            # monotonic map-adoption watermark: a stale lower-version
            # ping can never roll the routing back
            "_map_version": "CoordLedgerClient._caps_lock",
            # wire-v2 telemetry: bytes on the wire including the 4-byte
            # length header, incremented per exchange from worker threads
            "bytes_sent": "CoordLedgerClient._io_lock",
            "bytes_recv": "CoordLedgerClient._io_lock",
        },
        "ShardRouter": {
            # live relay connections: accept thread adds, per-conn threads
            # remove, stop() snapshots for shutdown
            "_conns": "ShardRouter._conns_lock",
            # routing state swapped whole by update_map() (hand-off /
            # failover commits race the per-connection relay threads)
            "shard_map": "ShardRouter._map_lock",
            "_table": "ShardRouter._map_lock",
            "_addrs": "ShardRouter._map_lock",
            "_first_sid": "ShardRouter._map_lock",
        },
        "ShardSupervisor": {
            # shard bookkeeping: watcher respawns, drain threads record
            # recovery times, chaos hooks read — all cross-thread
            "_shards": "ShardSupervisor._procs_lock",
            "_all_procs": "ShardSupervisor._procs_lock",
            "recovery_times": "ShardSupervisor._procs_lock",
            # hand-off/failover: the committed map and the failover
            # telemetry are rewritten from failover threads
            "shard_map": "ShardSupervisor._procs_lock",
            "failover_times": "ShardSupervisor._procs_lock",
            "_failover_threads": "ShardSupervisor._procs_lock",
        },
        "MemoryLedger": {
            # ledger dicts + the O(1) status-count index
            "_experiments": "MemoryLedger._lock",
            "_trials": "MemoryLedger._lock",
            "_status_ids": "MemoryLedger._lock",
            "_new_heap": "MemoryLedger._lock",
            "_completed_log": "MemoryLedger._lock",
            "_exp_gen": "MemoryLedger._lock",
            # per-experiment columnar archives (completed-trial storage)
            "_archives": "MemoryLedger._lock",
        },
        "ExperimentArchive": {
            # sealed segments + mutable head + the id→position liveness
            # index: appends/seals from the ledger's write path race
            # snapshot exports and fetch materialization
            "_segments": "ExperimentArchive._seg_lock",
            "_head": "ExperimentArchive._seg_lock",
            "_head_live": "ExperimentArchive._seg_lock",
            "_head_pos": "ExperimentArchive._seg_lock",
            "_skeys": "ExperimentArchive._seg_lock",
            "_svals": "ExperimentArchive._seg_lock",
            "_odd": "ExperimentArchive._seg_lock",
            "_live_sealed": "ExperimentArchive._seg_lock",
            "_seg_seq": "ExperimentArchive._seg_lock",
        },
        "SuggestAhead": {
            # speculative-refill pool bookkeeping: the spawn decision and
            # the hit/miss/launch telemetry are touched from the caller
            # thread AND the refill thread
            "_refill_thread": "SuggestAhead._ahead_lock",
            "_ahead_hits": "SuggestAhead._ahead_lock",
            "_ahead_misses": "SuggestAhead._ahead_lock",
            "_ahead_launches": "SuggestAhead._ahead_lock",
        },
        "BatchedExecutor": {
            # launch/row/pool telemetry: one executor may be shared by
            # several batched workers, and telemetry() reads cross-thread
            "_launches": "BatchedExecutor._tel_lock",
            "_rows": "BatchedExecutor._tel_lock",
            "_pools": "BatchedExecutor._tel_lock",
        },
        "VirtualClock": {
            # the virtual "now": every server/WAL/trial time read takes
            # the lock, and a test's advance()/advance_to() races them
            # when the clock is shared with a live threaded server
            "_now": "VirtualClock._lock",
        },
        "SuggestFuser": {
            # sweep/launch/commit telemetry: the housekeeping tick thread
            # writes, tenant_stats/bench readers snapshot cross-thread
            "_ticks": "SuggestFuser._lock",
            "_bucket_launches": "SuggestFuser._lock",
            "_fused_experiments": "SuggestFuser._lock",
            "_fallback_experiments": "SuggestFuser._lock",
            "_last_buckets": "SuggestFuser._lock",
            "_last_fused": "SuggestFuser._lock",
            "_last_occupancy": "SuggestFuser._lock",
        },
    }
    cfg.receiver_roles = {
        "ledger": "proxy", "_ledger": "proxy",
        "_wal": "wal", "wal": "wal",
        "_inner": "backend", "inner": "backend",
    }
    cfg.no_fallback_classes = {"CoordLedgerClient"}
    cfg.hotpath_registry = set()
    return cfg


@dataclass
class CrashConfig:
    """Declarations specific to ``mtpu crashcheck`` (the MTP persistence-
    order checkers and the crash-state enumeration suites). Same doctrine
    as :class:`LintConfig`: tests build small configs of this shape for
    fixture modules, so the checkers stay config-driven and hermetic."""

    #: module basename whose ``DURABLE_SEQUENCES`` dict literal declares
    #: the ordered-step protocols MTP003 enforces
    protocol_module: str = "protocol.py"
    #: explicit registry override (tests); None = parse the module
    durable_sequences: Optional[Dict[str, Dict[str, object]]] = None
    #: qualname prefixes of ack-publishing functions: every network send
    #: inside one must be preceded (in source order) by a WAL sync —
    #: MTP002. Prefix-matched so nested sender closures are covered.
    ack_publishers: Set[str] = field(default_factory=lambda: {
        "CoordServer._serve_conn",
    })
    #: receiver names whose ``.append``/``.sync`` are WAL journal effects
    wal_receivers: Set[str] = field(default_factory=lambda: {
        "_wal", "wal", "self._wal", "self.wal",
    })
    #: call-name tails that put ack bytes on the wire
    ack_calls: Set[str] = field(default_factory=lambda: {
        "send_payload", "send_msg", "sendall",
    })
    #: fault-arming indirection for MTP004: module-level string-constant
    #: assignments whose target name contains one of these markers count
    #: as arming every ``kind:`` spec they embed, provided the constant's
    #: NAME appears in the tests tree (tests import the spec wholesale —
    #: e.g. sim/engine.py's DEFAULT_FAULTS in test_sim_scale.py)
    fault_const_markers: Set[str] = field(default_factory=lambda: {
        "FAULTS",
    })
    #: directory scanned for fault-kind arming (None = <repo>/tests)
    tests_dir: Optional[str] = None


def default_crash_config() -> CrashConfig:
    """The checked-in crashcheck declarations for this repository."""
    return CrashConfig()
