"""Lint driver: run all checker families, diff against the baseline.

The baseline (``metaopt_tpu/analysis/baseline.json``) grandfathers
pre-existing findings by *fingerprint* — ``rule::file::symbol::detail``,
deliberately excluding line numbers so unrelated edits don't churn it.
The count per fingerprint is kept: introducing a SECOND instance of a
grandfathered pattern in the same function is still a regression.

Exit codes: 0 clean, 1 new findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

from metaopt_tpu.analysis.core import Finding, load_paths
from metaopt_tpu.analysis.durability import check_durability
from metaopt_tpu.analysis.jax_hygiene import check_jax
from metaopt_tpu.analysis.locks import check_locks
from metaopt_tpu.analysis.registry import LintConfig, default_config

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")
#: fingerprints embed paths relative to the REPO root (the directory
#: holding the metaopt_tpu package), never the caller's cwd — the
#: checked-in baseline must match from anywhere `mtpu lint` is invoked
REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_lint(paths: Sequence[str], cfg: Optional[LintConfig] = None,
             root: Optional[str] = None) -> List[Finding]:
    cfg = cfg or default_config()
    modules = load_paths(paths, root=root)
    findings: List[Finding] = []
    findings += check_locks(modules, cfg)
    findings += check_jax(modules, cfg)
    findings += check_durability(modules, cfg)
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.detail))
    return findings


def load_baseline(path: str) -> Counter:
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return Counter()
    return Counter({e["fingerprint"]: int(e.get("count", 1))
                    for e in data.get("findings", [])})


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    counts = Counter(f.fingerprint() for f in findings)
    lines: Dict[str, int] = {}
    msgs: Dict[str, str] = {}
    for f in findings:
        fp = f.fingerprint()
        lines.setdefault(fp, f.line)
        msgs.setdefault(fp, f.message)
    entries = [{"fingerprint": fp, "count": n,
                "line_at_capture": lines[fp], "message": msgs[fp]}
               for fp, n in sorted(counts.items())]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "findings": entries}, f, indent=1,
                  sort_keys=True)
        f.write("\n")


def diff_baseline(findings: Sequence[Finding], baseline: Counter
                  ) -> List[Finding]:
    """Findings beyond the grandfathered per-fingerprint counts."""
    budget = Counter(baseline)
    new: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget[fp] > 0:
            budget[fp] -= 1
        else:
            new.append(f)
    return new


def lint_main(argv: Optional[Sequence[str]] = None,
              cfg: Optional[LintConfig] = None) -> int:
    """CLI body shared by ``mtpu lint`` and the tier-1 gate test."""
    ap = argparse.ArgumentParser(
        prog="mtpu lint",
        description="repo-invariant static analysis (lock discipline, "
                    "JAX hygiene, WAL durability contract)")
    ap.add_argument("paths", nargs="*", default=[PKG_DIR],
                    help="files/directories to scan (default: the "
                         "metaopt_tpu package)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="grandfathered-findings file (default: the "
                         "checked-in analysis/baseline.json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from this run's findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    try:
        findings = run_lint(args.paths, cfg=cfg, root=REPO_ROOT)
    except (OSError, SyntaxError) as e:
        print(f"mtpu lint: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        save_baseline(args.baseline, findings)
        print(f"baseline updated: {len(findings)} finding(s) -> "
              f"{args.baseline}")
        return 0

    baseline = Counter() if args.no_baseline else load_baseline(
        args.baseline)
    new = diff_baseline(findings, baseline)
    grandfathered = len(findings) - len(new)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.__dict__ for f in new],
            "grandfathered": grandfathered,
            "total": len(findings),
        }, indent=1, sort_keys=True))
    else:
        for f in new:
            print(f.render())
        note = (f"{len(new)} new finding(s), "
                f"{grandfathered} grandfathered by baseline")
        print(("FAIL: " if new else "clean: ") + note)
    return 1 if new else 0
