"""Lint driver: run all checker families, diff against the baseline.

The baseline (``metaopt_tpu/analysis/baseline.json``) grandfathers
pre-existing findings by *fingerprint* — ``rule::file::symbol::detail``,
deliberately excluding line numbers so unrelated edits don't churn it.
The count per fingerprint is kept: introducing a SECOND instance of a
grandfathered pattern in the same function is still a regression.

Exit codes: 0 clean, 1 new findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

from metaopt_tpu.analysis.core import Finding, load_paths
from metaopt_tpu.analysis.durability import check_durability
from metaopt_tpu.analysis.jax_hygiene import check_jax
from metaopt_tpu.analysis.locks import LockChecker
from metaopt_tpu.analysis.registry import (CrashConfig, LintConfig,
                                           RaceConfig, default_config,
                                           default_race_config)

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")
DEFAULT_RACE_BASELINE = os.path.join(os.path.dirname(__file__),
                                     "race_baseline.json")
DEFAULT_CRASH_BASELINE = os.path.join(os.path.dirname(__file__),
                                      "crash_baseline.json")
#: fingerprints embed paths relative to the REPO root (the directory
#: holding the metaopt_tpu package), never the caller's cwd — the
#: checked-in baseline must match from anywhere `mtpu lint` is invoked
REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sort_key(f: Finding) -> Tuple[str, int, str, str, str]:
    """(path, line, code, detail, symbol): total order, so repeated runs
    and ``--update-baseline`` produce byte-identical output."""
    return (f.file, f.line, f.rule, f.detail, f.symbol)


def run_lint(paths: Sequence[str], cfg: Optional[LintConfig] = None,
             root: Optional[str] = None,
             race_cfg: Optional[RaceConfig] = None) -> List[Finding]:
    """All static families over ONE parse: ``load_paths`` reads+parses
    each file once, and the lock-graph summaries (the expensive pass)
    are built once and shared between the MTL checks and — when a
    ``race_cfg`` is given — the MTR001 shared-attribute check."""
    cfg = cfg or default_config()
    modules = load_paths(paths, root=root)
    checker = LockChecker(modules, cfg)
    findings: List[Finding] = []
    findings += checker.run()
    findings += check_jax(modules, cfg)
    findings += check_durability(modules, cfg)
    if race_cfg is not None:
        from metaopt_tpu.analysis.dynrace import check_shared

        findings += check_shared(modules, cfg, race_cfg, checker=checker)
    findings.sort(key=_sort_key)
    return findings


def load_baseline(path: str) -> Counter:
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return Counter()
    return Counter({e["fingerprint"]: int(e.get("count", 1))
                    for e in data.get("findings", [])})


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    counts = Counter(f.fingerprint() for f in findings)
    lines: Dict[str, int] = {}
    msgs: Dict[str, str] = {}
    for f in findings:
        fp = f.fingerprint()
        lines.setdefault(fp, f.line)
        msgs.setdefault(fp, f.message)
    entries = [{"fingerprint": fp, "count": n,
                "line_at_capture": lines[fp], "message": msgs[fp]}
               for fp, n in sorted(counts.items())]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "findings": entries}, f, indent=1,
                  sort_keys=True)
        f.write("\n")


def diff_baseline(findings: Sequence[Finding], baseline: Counter
                  ) -> List[Finding]:
    """Findings beyond the grandfathered per-fingerprint counts."""
    budget = Counter(baseline)
    new: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget[fp] > 0:
            budget[fp] -= 1
        else:
            new.append(f)
    return new


def lint_main(argv: Optional[Sequence[str]] = None,
              cfg: Optional[LintConfig] = None) -> int:
    """CLI body shared by ``mtpu lint`` and the tier-1 gate test."""
    ap = argparse.ArgumentParser(
        prog="mtpu lint",
        description="repo-invariant static analysis (lock discipline, "
                    "JAX hygiene, WAL durability contract)")
    ap.add_argument("paths", nargs="*", default=[PKG_DIR],
                    help="files/directories to scan (default: the "
                         "metaopt_tpu package)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="grandfathered-findings file (default: the "
                         "checked-in analysis/baseline.json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from this run's findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    t0 = time.monotonic()
    try:
        findings = run_lint(args.paths, cfg=cfg, root=REPO_ROOT,
                            race_cfg=default_race_config())
    except (OSError, SyntaxError) as e:
        print(f"mtpu lint: {e}", file=sys.stderr)
        return 2
    runtime_s = time.monotonic() - t0

    if args.update_baseline:
        save_baseline(args.baseline, findings)
        print(f"baseline updated: {len(findings)} finding(s) -> "
              f"{args.baseline}")
        return 0

    baseline = Counter() if args.no_baseline else load_baseline(
        args.baseline)
    new = diff_baseline(findings, baseline)
    grandfathered = len(findings) - len(new)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.__dict__ for f in new],
            "grandfathered": grandfathered,
            "lint_runtime_s": round(runtime_s, 3),
            "total": len(findings),
        }, indent=1, sort_keys=True))
    else:
        for f in new:
            print(f.render())
        note = (f"{len(new)} new finding(s), "
                f"{grandfathered} grandfathered by baseline")
        print(("FAIL: " if new else "clean: ") + note)
    return 1 if new else 0


def run_race(suites: Sequence[str], cfg: Optional[LintConfig] = None,
             race_cfg: Optional[RaceConfig] = None,
             scale: int = 1, static: bool = True,
             paths: Optional[Sequence[str]] = None
             ) -> Tuple[List[Finding], Dict[str, float]]:
    """Static MTR001 + the instrumented dynamic suites.

    Returns (findings, stats). Suites run sequentially, each under its
    own :class:`~metaopt_tpu.analysis.dynrace.RaceRuntime` so one
    suite's access history can't alias another's recycled object ids.
    """
    from metaopt_tpu.analysis import dynrace
    from metaopt_tpu.analysis.suites import SUITES

    cfg = cfg or default_config()
    race_cfg = race_cfg or default_race_config()
    findings: List[Finding] = []
    stats: Dict[str, float] = {}
    t0 = time.monotonic()
    if static:
        modules = load_paths(paths or [PKG_DIR], root=REPO_ROOT)
        checker = LockChecker(modules, cfg)
        findings += dynrace.check_shared(modules, cfg, race_cfg,
                                         checker=checker)
        stats["static_runtime_s"] = round(time.monotonic() - t0, 3)
    monitor = dynrace.monitored_classes(cfg, race_cfg)
    events = 0
    for name in suites:
        if name not in SUITES:
            raise ValueError(f"unknown race suite {name!r} "
                             f"(have: {', '.join(sorted(SUITES))})")
        t1 = time.monotonic()
        rt = dynrace.RaceRuntime(monitor, root=REPO_ROOT)
        with dynrace.instrument(rt):
            SUITES[name](scale)
        findings += rt.findings()
        events += rt.events
        stats[f"suite_{name}_s"] = round(time.monotonic() - t1, 3)
    stats["events"] = events
    stats["runtime_s"] = round(time.monotonic() - t0, 3)
    findings.sort(key=_sort_key)
    return findings, stats


def race_main(argv: Optional[Sequence[str]] = None,
              cfg: Optional[LintConfig] = None,
              race_cfg: Optional[RaceConfig] = None) -> int:
    """CLI body shared by ``mtpu race`` and the tier-1 gate test."""
    ap = argparse.ArgumentParser(
        prog="mtpu race",
        description="hybrid race detection: static shared-attribute "
                    "check (MTR001) + lockset/vector-clock instrumented "
                    "concurrency suites (MTR101 data races, MTR102 "
                    "lock-order inversions)")
    ap.add_argument("--suite", action="append", default=None,
                    choices=("coord", "algo", "wal", "sim", "all"),
                    help="workload(s) to run instrumented (repeatable; "
                         "default: all)")
    ap.add_argument("--scale", type=int, default=1,
                    help="iteration multiplier (1 = fast CI run)")
    ap.add_argument("--static-only", action="store_true",
                    help="run only the MTR001 static check, no workloads")
    ap.add_argument("--baseline", default=DEFAULT_RACE_BASELINE,
                    help="grandfathered-findings file (default: the "
                         "checked-in analysis/race_baseline.json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from this run's findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    suites = args.suite or ["all"]
    if "all" in suites:
        suites = ["coord", "algo", "wal", "sim"]
    if args.static_only:
        suites = []

    try:
        findings, stats = run_race(suites, cfg=cfg, race_cfg=race_cfg,
                                   scale=max(1, args.scale))
    except (OSError, SyntaxError) as e:
        print(f"mtpu race: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        save_baseline(args.baseline, findings)
        print(f"baseline updated: {len(findings)} finding(s) -> "
              f"{args.baseline}")
        return 0

    baseline = Counter() if args.no_baseline else load_baseline(
        args.baseline)
    new = diff_baseline(findings, baseline)
    grandfathered = len(findings) - len(new)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.__dict__ for f in new],
            "grandfathered": grandfathered,
            "stats": stats,
            "suites": suites,
            "total": len(findings),
        }, indent=1, sort_keys=True))
    else:
        for f in new:
            print(f.render())
        note = (f"{len(new)} new finding(s), "
                f"{grandfathered} grandfathered by baseline "
                f"[suites: {', '.join(suites) or 'none'}; "
                f"{int(stats.get('events', 0))} events in "
                f"{stats.get('runtime_s', 0.0):.1f}s]")
        print(("FAIL: " if new else "clean: ") + note)
    return 1 if new else 0


def run_crashcheck(suites: Sequence[str],
                   cfg: Optional[CrashConfig] = None,
                   static: bool = True,
                   paths: Optional[Sequence[str]] = None,
                   tests_dir: Optional[str] = None
                   ) -> Tuple[List[Finding], Dict[str, float]]:
    """Static persistence-order checks (MTP001-MTP004) + exhaustive
    crash-point enumeration of the durable paths (MTP1xx).

    Returns (findings, stats). Each dynamic suite drives a real durable
    path under the fsjournal seam, enumerates every legal crash state of
    its trace, and certifies real recovery against the acked prefix.
    """
    from metaopt_tpu.analysis import crashcheck
    from metaopt_tpu.analysis.registry import default_crash_config

    cfg = cfg or default_crash_config()
    findings: List[Finding] = []
    stats: Dict[str, float] = {}
    t0 = time.monotonic()
    if static:
        modules = load_paths(paths or [PKG_DIR], root=REPO_ROOT)
        findings += crashcheck.check_crash(
            modules, cfg,
            tests_dir=tests_dir or os.path.join(REPO_ROOT, "tests"))
        stats["static_runtime_s"] = round(time.monotonic() - t0, 3)
    states = 0
    for name in suites:
        if name not in crashcheck.SUITES:
            raise ValueError(
                f"unknown crashcheck suite {name!r} "
                f"(have: {', '.join(crashcheck.SUITES)})")
        suite_findings, suite_stats = crashcheck.run_suite(name)
        findings += suite_findings
        states += int(suite_stats.get("crash_states", 0))
        stats[f"suite_{name}_s"] = suite_stats.get("runtime_s", 0.0)
    stats["crash_states"] = states
    stats["runtime_s"] = round(time.monotonic() - t0, 3)
    findings.sort(key=_sort_key)
    return findings, stats


def crashcheck_main(argv: Optional[Sequence[str]] = None,
                    cfg: Optional[CrashConfig] = None) -> int:
    """CLI body shared by ``mtpu crashcheck`` and the tier-1 gate test."""
    from metaopt_tpu.analysis.crashcheck import SUITES as CRASH_SUITES

    ap = argparse.ArgumentParser(
        prog="mtpu crashcheck",
        description="crash-consistency certification: static "
                    "persistence-order analysis (MTP001 publish order, "
                    "MTP002 WAL-before-ack, MTP003 durable sequences, "
                    "MTP004 dead barriers) + exhaustive crash-point "
                    "enumeration of every durable path with real "
                    "recovery (MTP1xx)")
    ap.add_argument("--suite", action="append", default=None,
                    choices=tuple(CRASH_SUITES) + ("all",),
                    help="durable path(s) to enumerate (repeatable; "
                         "default: all)")
    ap.add_argument("--static-only", action="store_true",
                    help="run only the MTP static checks, no enumeration")
    ap.add_argument("--baseline", default=DEFAULT_CRASH_BASELINE,
                    help="grandfathered-findings file (default: the "
                         "checked-in analysis/crash_baseline.json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from this run's findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    suites = args.suite or ["all"]
    if "all" in suites:
        suites = list(CRASH_SUITES)
    if args.static_only:
        suites = []

    try:
        findings, stats = run_crashcheck(suites, cfg=cfg)
    except (OSError, SyntaxError) as e:
        print(f"mtpu crashcheck: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        # dynamic findings (MTP1xx) are never grandfathered: a
        # reproducible lost acked write is a bug, not a baseline entry
        static_only = [f for f in findings
                       if not f.rule.startswith("MTP1")]
        save_baseline(args.baseline, static_only)
        print(f"baseline updated: {len(static_only)} finding(s) -> "
              f"{args.baseline}")
        return 1 if len(static_only) != len(findings) else 0

    baseline = Counter() if args.no_baseline else load_baseline(
        args.baseline)
    new = diff_baseline(findings, baseline)
    grandfathered = len(findings) - len(new)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.__dict__ for f in new],
            "grandfathered": grandfathered,
            "stats": stats,
            "suites": suites,
            "total": len(findings),
        }, indent=1, sort_keys=True))
    else:
        for f in new:
            print(f.render())
        note = (f"{len(new)} new finding(s), "
                f"{grandfathered} grandfathered by baseline "
                f"[suites: {', '.join(suites) or 'none'}; "
                f"{int(stats.get('crash_states', 0))} crash states in "
                f"{stats.get('runtime_s', 0.0):.1f}s]")
        print(("FAIL: " if new else "clean: ") + note)
    return 1 if new else 0


def analyze_main(argv: Optional[Sequence[str]] = None) -> int:
    """``mtpu analyze``: every static family in one run — lint (MTL lock
    discipline, MTJ JAX hygiene, MTD durability), race MTR001, and
    crashcheck MTP001-MTP004 — diffed against the union of the three
    checked-in baselines, with one combined report."""
    ap = argparse.ArgumentParser(
        prog="mtpu analyze",
        description="umbrella static analysis: lint + race --static-only "
                    "+ crashcheck --static-only, one combined report")
    ap.add_argument("paths", nargs="*", default=[PKG_DIR],
                    help="files/directories to scan (default: the "
                         "metaopt_tpu package)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baselines")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    t0 = time.monotonic()
    try:
        # one run_lint call covers MTL/MTJ/MTD and (via race_cfg) MTR001
        # over a single parse; crashcheck reuses its own parse because
        # its effect summaries are package-global
        lint_findings = run_lint(args.paths, root=REPO_ROOT,
                                 race_cfg=default_race_config())
        lint_s = round(time.monotonic() - t0, 3)
        crash_findings, crash_stats = run_crashcheck(
            [], paths=args.paths)
    except (OSError, SyntaxError) as e:
        print(f"mtpu analyze: {e}", file=sys.stderr)
        return 2

    findings = sorted(lint_findings + crash_findings, key=_sort_key)
    baseline: Counter = Counter()
    if not args.no_baseline:
        for p in (DEFAULT_BASELINE, DEFAULT_RACE_BASELINE,
                  DEFAULT_CRASH_BASELINE):
            baseline += load_baseline(p)
    new = diff_baseline(findings, baseline)
    grandfathered = len(findings) - len(new)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.__dict__ for f in new],
            "grandfathered": grandfathered,
            "lint_runtime_s": lint_s,
            "crashcheck_runtime_s": crash_stats.get("runtime_s", 0.0),
            "total": len(findings),
        }, indent=1, sort_keys=True))
    else:
        for f in new:
            print(f.render())
        note = (f"{len(new)} new finding(s), "
                f"{grandfathered} grandfathered across "
                "lint+race+crashcheck baselines")
        print(("FAIL: " if new else "clean: ") + note)
    return 1 if new else 0
