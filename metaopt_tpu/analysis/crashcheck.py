"""Crash-consistency analysis: static persistence-order checkers (the
``MTP`` rule family) plus exhaustive crash-point enumeration of the real
durable paths (the dynamic suites behind ``mtpu crashcheck``).

Static side — four checkers over the same parsed-module set the lint
framework uses, with PR-4-style call summaries so publish helpers are
seen through one level of indirection:

``MTP001`` crash-atomic publish order.  Every rename-publish of a
    ``*.tmp`` staging file (``os.replace`` / ``os.rename`` / the
    ``fsjournal`` seam equivalents) must be preceded by an fsync-carrying
    write and followed by a directory fsync, in source order within the
    publishing function (or via a callee whose effect summary carries
    the missing half).  Without the fsync the rename can be reordered
    before the data blocks by the filesystem; without the dir fsync the
    rename itself may not survive a crash.

``MTP002`` WAL-before-ack.  In functions under an ack-publisher scope
    (``CrashConfig.ack_publishers``, default ``CoordServer._serve_conn``)
    every reply-send call must be preceded by a ``wal.sync(...)`` call —
    the zero-acked-write-loss invariant reduced to source order.

``MTP003`` ordered durable sequences.  ``protocol.DURABLE_SEQUENCES``
    (read via ``ast.literal_eval``, never imported — same doctrine as
    ``JOURNALED_OPS``) declares multi-step protocols such as evict's
    ``publish file -> journal record -> drop state``.  The checker
    enumerates acyclic control-flow paths through the declared function
    (if: both arms; loops: zero or one iteration; return/raise ends the
    path; except handlers ignored; capped at ``_PATH_CAP`` paths) and
    flags any path where a later step executes before an earlier
    non-``optional`` step has.  Aborting after a prefix is LEGAL — each
    step is a crash barrier the recovery protocol tolerates; running a
    step without its prerequisites is the bug class (reorder or skip).

``MTP004`` dead crash barriers.  Every ``faults.fire("<kind>")`` site
    must be armed by at least one test: the kind string appears in the
    tests tree, either literally or via a module-level ``*FAULTS*``
    string constant that a test imports (``sim/engine.py:DEFAULT_FAULTS``
    arms ``sim_delay`` that way).  An unarmed barrier is dead chaos code
    — it can rot without any signal.

Dynamic side — each suite drives a REAL durable path (bare WAL, v1
snapshot, v2 incremental archive, evict/hydrate, hand-off apply) under
``fsjournal.recording``, then for every legal crash state of the trace
(every event prefix, plus torn tails of the interrupted write — see
``fsjournal.enumerate_crash_states`` for the bound) materializes the
state into the same directory tree and runs the real offline recovery
(``read_records`` / ``recover_shard_state``).  Certified invariants:

* zero acked-write loss: every effect acked (``fsj.mark("acked")``)
  before the crash point is present after recovery;
* exactly-once replies: the journaled reply cache is bit-identical for
  every ack not compacted away before the crash point;
* recovery idempotence: recovering the recovered state is a no-op.

Violations surface as ``MTP1xx`` findings (``MTP101`` lost acked write,
``MTP102`` reply-cache divergence, ``MTP103`` recovery crash) so the
baseline/grandfathering machinery treats both sides uniformly — though
dynamic findings are never baselined: a reproducible lost write is a bug
to fix, not to grandfather.
"""

from __future__ import annotations

import ast
import json
import os
import shutil
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from metaopt_tpu.analysis.core import Finding, LintModule, dotted_name
from metaopt_tpu.analysis.registry import CrashConfig, default_crash_config

__all__ = [
    "check_crash",
    "run_suite",
    "SUITES",
    "load_durable_sequences",
]

# ---------------------------------------------------------------------------
# effect extraction
# ---------------------------------------------------------------------------

_SEAM_MODULE = "metaopt_tpu.utils.fsjournal"
_SEAM_PARENT = "metaopt_tpu.utils"
_SEAM_FUNCS = frozenset(
    {"write_file", "append", "replace", "unlink", "truncate",
     "fsync_dir", "mark"})

#: event kinds an effect stream may contain (the static twin of the
#: journal's trace vocabulary)
_K_FSYNCED_WRITE = "fsynced_write"   # write guaranteed durable in order
_K_REPLACE = "replace"               # rename-publish; info = src expr text
_K_DIR_FSYNC = "dir_fsync"
_K_UNLINK = "unlink"
_K_WAL_APPEND = "wal_append"         # info = op literal (or None)
_K_WAL_SYNC = "wal_sync"
_K_ACK_SEND = "ack_send"             # info = callee tail
_K_CALL = "call"                     # info = dotted name

_PATH_CAP = 512          # MTP003: max enumerated paths per function
_SUMMARY_DEPTH = 3       # call-summary recursion bound


def _seam_names(tree: ast.AST) -> Tuple[Set[str], Dict[str, str]]:
    """Names bound to the fsjournal seam in this module: a set of module
    aliases (``fsj`` in ``import ... as fsj``) and a map of directly
    imported function names (``{"replace": "replace"}``)."""
    aliases: Set[str] = set()
    funcs: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == _SEAM_MODULE and a.asname:
                    aliases.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module == _SEAM_MODULE:
                for a in node.names:
                    if a.name in _SEAM_FUNCS:
                        funcs[a.asname or a.name] = a.name
            elif node.module == _SEAM_PARENT:
                for a in node.names:
                    if a.name == "fsjournal":
                        aliases.add(a.asname or "fsjournal")
    return aliases, funcs


def _seam_func(d: str, aliases: Set[str], funcs: Dict[str, str]
               ) -> Optional[str]:
    """Canonical seam function name for dotted callee ``d``, or None."""
    if d in funcs:
        return funcs[d]
    head, _, tail = d.rpartition(".")
    if tail in _SEAM_FUNCS and (head in aliases or head == _SEAM_MODULE):
        return tail
    return None


def _own_statements(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a def's body WITHOUT descending into nested defs/lambdas —
    a nested def's effects belong to the nested function, which the
    framework yields separately."""
    stack = list(getattr(fn, "body", []))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _assignments(fn: ast.AST) -> Dict[str, str]:
    """Simple ``name = <expr>`` bindings in a def (own statements only),
    used to resolve a rename's src argument back to its staging
    expression (``tmp`` -> ``path + ".tmp"``)."""
    out: Dict[str, str] = {}
    for node in _own_statements(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            try:
                out[node.targets[0].id] = ast.unparse(node.value)
            except Exception:  # pragma: no cover - unparse is total on 3.9+
                pass
    return out


def _arg_text(node: Optional[ast.AST], assigns: Dict[str, str]) -> str:
    if node is None:
        return ""
    if isinstance(node, ast.Name) and node.id in assigns:
        return assigns[node.id]
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return ""


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _wal_append_op(call: ast.Call) -> Optional[str]:
    """The ``op`` literal of a journaled record, when the record is a
    dict literal at the append site."""
    if not call.args:
        return None
    rec = call.args[0]
    if isinstance(rec, ast.Dict):
        for k, v in zip(rec.keys, rec.values):
            if (isinstance(k, ast.Constant) and k.value == "op"
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                return v.value
    return None


def _classify_call(call: ast.Call, assigns: Dict[str, str],
                   aliases: Set[str], funcs: Dict[str, str],
                   cfg: CrashConfig) -> Optional[Tuple[str, Any]]:
    """Map one Call node to an effect-stream event, or None."""
    d = dotted_name(call.func)
    if d is None:
        return None
    head, _, tail = d.rpartition(".")
    seam = _seam_func(d, aliases, funcs)
    if seam is not None:
        if seam in ("write_file", "append", "truncate"):
            fsync = _kw(call, "fsync")
            if isinstance(fsync, ast.Constant) and fsync.value is False:
                return None  # explicitly unfsynced: carries no ordering
            return (_K_FSYNCED_WRITE, seam)
        if seam == "replace":
            src = call.args[0] if call.args else _kw(call, "src")
            return (_K_REPLACE, _arg_text(src, assigns))
        if seam == "unlink":
            return (_K_UNLINK, None)
        if seam == "fsync_dir":
            return (_K_DIR_FSYNC, None)
        return None  # mark: a logical label, not a persistence effect
    if d == "os.fsync" or tail == "fsync" and head not in cfg.wal_receivers:
        return (_K_FSYNCED_WRITE, "fsync")
    if tail == "fsync_dir":
        return (_K_DIR_FSYNC, None)
    if head == "os" and tail in ("replace", "rename"):
        src = call.args[0] if call.args else None
        return (_K_REPLACE, _arg_text(src, assigns))
    if head == "os" and tail in ("unlink", "remove"):
        return (_K_UNLINK, None)
    if head in cfg.wal_receivers:
        if tail == "append":
            return (_K_WAL_APPEND, _wal_append_op(call))
        if tail == "sync":
            return (_K_WAL_SYNC, None)
    if tail in cfg.ack_calls:
        return (_K_ACK_SEND, tail)
    return (_K_CALL, d)


def _effects(fn: ast.AST, mod: LintModule, aliases: Set[str],
             funcs: Dict[str, str], cfg: CrashConfig
             ) -> List[Tuple[int, str, Any]]:
    """The def's persistence-effect stream in source order (own
    statements only; nested defs excluded)."""
    assigns = _assignments(fn)
    out: List[Tuple[int, str, Any]] = []
    for node in _own_statements(fn):
        if isinstance(node, ast.Call):
            ev = _classify_call(node, assigns, aliases, funcs, cfg)
            if ev is not None:
                out.append((node.lineno, ev[0], ev[1]))
    out.sort(key=lambda e: e[0])
    return out


class _Summaries:
    """Interprocedural effect-kind summaries: which effect kinds a
    function (transitively, to a small depth) performs.  Used so MTP001
    sees an fsync or dir-fsync done by a local helper the publisher
    calls, without re-attributing the helper's findings to the caller."""

    def __init__(self, cfg: CrashConfig) -> None:
        self.cfg = cfg
        #: qualname -> (fn node, module, aliases, funcs)
        self.defs: Dict[str, Tuple[ast.AST, LintModule, Set[str],
                                   Dict[str, str]]] = {}
        self._memo: Dict[str, Set[str]] = {}

    def add_module(self, mod: LintModule) -> None:
        aliases, funcs = _seam_names(mod.tree)
        for fn, _cls in mod.functions():
            self.defs.setdefault(mod.qualname(fn), (fn, mod, aliases, funcs))

    def resolve(self, caller_qual: str, callee: str) -> Optional[str]:
        """Resolve a dotted callee to a known qualname: ``self.x`` /
        ``cls.x`` within the caller's class, bare names at module level
        (best effort; analysis summaries only need local helpers)."""
        head, _, tail = callee.rpartition(".")
        if head in ("self", "cls"):
            cls = caller_qual.rsplit(".", 2)[0] if "." in caller_qual else ""
            cand = f"{cls}.{tail}" if cls else tail
            if cand in self.defs:
                return cand
        if not head and tail in self.defs:
            return tail
        return None

    def kinds(self, qual: str, _depth: int = 0) -> Set[str]:
        if qual in self._memo:
            return self._memo[qual]
        if _depth >= _SUMMARY_DEPTH or qual not in self.defs:
            return set()
        self._memo[qual] = set()  # cycle guard
        fn, mod, aliases, funcs = self.defs[qual]
        kinds: Set[str] = set()
        for _ln, kind, info in _effects(fn, mod, aliases, funcs, self.cfg):
            if kind == _K_CALL:
                target = self.resolve(qual, info)
                if target:
                    kinds |= self.kinds(target, _depth + 1)
            else:
                kinds.add(kind)
        self._memo[qual] = kinds
        return kinds


# ---------------------------------------------------------------------------
# MTP001 — crash-atomic publish order
# ---------------------------------------------------------------------------

def _short(text: str, limit: int = 48) -> str:
    text = " ".join(text.split())
    return text if len(text) <= limit else text[:limit - 3] + "..."


def _check_publish_order(mod: LintModule, summaries: _Summaries,
                         cfg: CrashConfig) -> List[Finding]:
    findings: List[Finding] = []
    aliases, funcs = _seam_names(mod.tree)
    for fn, _cls in mod.functions():
        qual = mod.qualname(fn)
        evs = _effects(fn, mod, aliases, funcs, cfg)
        for i, (ln, kind, src) in enumerate(evs):
            if kind != _K_REPLACE or ".tmp" not in (src or ""):
                continue

            def _has(kinds_wanted: Set[str], window) -> bool:
                for _l, k, info in window:
                    if k in kinds_wanted:
                        return True
                    if k == _K_CALL:
                        target = summaries.resolve(qual, info)
                        if target and (kinds_wanted
                                       & summaries.kinds(target)):
                            return True
                return False

            if not _has({_K_FSYNCED_WRITE}, evs[:i]):
                findings.append(Finding(
                    rule="MTP001", file=mod.relpath, line=ln,
                    message=(f"rename-publish of {_short(src)} without a "
                             "preceding fsync'd write: the rename can hit "
                             "disk before the data it publishes"),
                    symbol=qual, detail=f"nofsync|{_short(src)}"))
            if not _has({_K_DIR_FSYNC}, evs[i + 1:]):
                findings.append(Finding(
                    rule="MTP001", file=mod.relpath, line=ln,
                    message=(f"rename-publish of {_short(src)} without a "
                             "following directory fsync: the rename itself "
                             "may not survive a crash"),
                    symbol=qual, detail=f"nodirfsync|{_short(src)}"))
    return findings


# ---------------------------------------------------------------------------
# MTP002 — WAL durable before ack leaves
# ---------------------------------------------------------------------------

def _check_wal_before_ack(mod: LintModule, cfg: CrashConfig
                          ) -> List[Finding]:
    findings: List[Finding] = []
    aliases, funcs = _seam_names(mod.tree)
    for fn, _cls in mod.functions():
        qual = mod.qualname(fn)
        if not any(qual == p or qual.startswith(p + ".")
                   for p in cfg.ack_publishers):
            continue
        synced = False
        for ln, kind, info in _effects(fn, mod, aliases, funcs, cfg):
            if kind == _K_WAL_SYNC:
                synced = True
            elif kind == _K_ACK_SEND and not synced:
                findings.append(Finding(
                    rule="MTP002", file=mod.relpath, line=ln,
                    message=(f"reply leaves via {info}() before any "
                             "wal.sync() in this sender: an acked write "
                             "may not be durable"),
                    symbol=qual, detail=f"unsynced|{info}"))
    return findings


# ---------------------------------------------------------------------------
# MTP003 — DURABLE_SEQUENCES path analysis
# ---------------------------------------------------------------------------

def load_durable_sequences(modules: Sequence[LintModule], cfg: CrashConfig
                           ) -> Dict[str, Dict[str, Any]]:
    """Read ``DURABLE_SEQUENCES`` out of the protocol module as a literal
    (never imported — the registry must stay readable by tooling that
    cannot import the package)."""
    if cfg.durable_sequences is not None:
        return dict(cfg.durable_sequences)
    for mod in modules:
        if not mod.relpath.endswith(cfg.protocol_module):
            continue
        for node in mod.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "DURABLE_SEQUENCES"):
                try:
                    val = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    return {}
                return val if isinstance(val, dict) else {}
    return {}


class _TooManyPaths(Exception):
    pass


def _is_wal_guard(test: ast.AST, cfg: CrashConfig) -> bool:
    """``if wal is not None:`` (and friends) — the no-WAL configuration
    legitimately skips journaling steps; treating the guard as always
    true keeps MTP003 about ORDER, not about optional journaling."""
    try:
        text = ast.unparse(test)
    except Exception:  # pragma: no cover
        return False
    receivers = set(cfg.wal_receivers) | {
        r[5:] for r in cfg.wal_receivers if r.startswith("self.")}
    for r in receivers:
        if text in (r, f"self.{r}", f"{r} is not None",
                    f"self.{r} is not None"):
            return True
    return False


def _stmt_tokens(stmt: ast.AST, match) -> List[int]:
    """Step indices matched by calls inside one non-control statement,
    in source order."""
    hits: List[Tuple[int, int, int]] = []
    stack = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            for idx in match(node):
                hits.append((node.lineno, node.col_offset, idx))
        stack.extend(ast.iter_child_nodes(node))
    hits.sort()
    return [idx for _l, _c, idx in hits]


# path termination levels: how far up the control stack a path unwinds
_FALL = 0    # falls through to the next statement
_LOOP = 1    # break/continue: ends the enclosing loop body only
_FUNC = 2    # return/raise: terminates the whole function


def _paths_through(body: Sequence[ast.AST], match, cfg: CrashConfig
                   ) -> List[Tuple[List[int], int]]:
    """Acyclic ``(tokens, termination)`` paths through a statement list.
    If: both arms (wal-None guards: body only); loops: zero or one
    iteration, break/continue unwind to the loop only; try: body +
    orelse + finally, handlers ignored; return/raise end the path."""
    paths: List[Tuple[List[int], int]] = [([], _FALL)]

    def _extend(stmt_paths: List[Tuple[List[int], int]]) -> None:
        nonlocal paths
        new: List[Tuple[List[int], int]] = []
        for toks, done in paths:
            if done != _FALL:
                new.append((toks, done))
                continue
            for st, sdone in stmt_paths:
                new.append((toks + st, sdone))
        if len(new) > _PATH_CAP:
            raise _TooManyPaths()
        paths = new

    for stmt in body:
        if isinstance(stmt, ast.If):
            pre = _stmt_tokens(stmt.test, match)
            arm_paths = [(pre + p, d)
                         for p, d in _paths_through(stmt.body, match, cfg)]
            if not _is_wal_guard(stmt.test, cfg):
                if stmt.orelse:
                    arm_paths += [(pre + p, d)
                                  for p, d in _paths_through(
                                      stmt.orelse, match, cfg)]
                else:
                    arm_paths.append((pre, _FALL))
            _extend(arm_paths)
        elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            pre = _stmt_tokens(
                stmt.iter if isinstance(stmt, (ast.For, ast.AsyncFor))
                else stmt.test, match)
            once = _paths_through(stmt.body, match, cfg)
            # break/continue end the iteration; execution resumes after
            # the loop, so _LOOP demotes to _FALL at this level
            arm_paths = [(pre, _FALL)] + [
                (pre + p, _FALL if d == _LOOP else d) for p, d in once]
            _extend(arm_paths)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            pre = []
            for item in stmt.items:
                pre += _stmt_tokens(item.context_expr, match)
            _extend([(pre + p, d)
                     for p, d in _paths_through(stmt.body, match, cfg)])
        elif isinstance(stmt, ast.Try):
            inner = _paths_through(
                list(stmt.body) + list(stmt.orelse) + list(stmt.finalbody),
                match, cfg)
            _extend(inner)
        elif isinstance(stmt, (ast.Return, ast.Raise)):
            toks: List[int] = []
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                toks = _stmt_tokens(stmt.value, match)
            elif isinstance(stmt, ast.Raise) and stmt.exc is not None:
                toks = _stmt_tokens(stmt.exc, match)
            _extend([(toks, _FUNC)])
        elif isinstance(stmt, (ast.Break, ast.Continue)):
            _extend([([], _LOOP)])
        else:
            toks = _stmt_tokens(stmt, match)
            if toks:
                _extend([(toks, _FALL)])
    return paths


def _enumerate_paths(body: Sequence[ast.AST], match, cfg: CrashConfig
                     ) -> List[List[int]]:
    return [toks for toks, _done in _paths_through(body, match, cfg)]


def _step_matcher(steps: Sequence[str], assigns: Dict[str, str],
                  aliases: Set[str], funcs: Dict[str, str],
                  cfg: CrashConfig):
    """Compile a registry entry's step list into a Call -> [step index]
    matcher.  Vocabulary: ``publish:<suffix>`` / ``wal.append:<op>`` /
    ``wal.sync`` / ``call:<name>``."""

    def match(call: ast.Call) -> List[int]:
        ev = _classify_call(call, assigns, aliases, funcs, cfg)
        out: List[int] = []
        for idx, step in enumerate(steps):
            verb, _, arg = step.partition(":")
            if verb == "publish":
                if (ev is not None and ev[0] == _K_REPLACE
                        and arg in (ev[1] or "")):
                    out.append(idx)
            elif verb == "wal.append":
                if (ev is not None and ev[0] == _K_WAL_APPEND
                        and (not arg or ev[1] == arg)):
                    out.append(idx)
            elif verb == "wal.sync":
                if ev is not None and ev[0] == _K_WAL_SYNC:
                    out.append(idx)
            elif verb == "call":
                d = dotted_name(call.func)
                if d is not None and d.rpartition(".")[2] == arg:
                    out.append(idx)
        return out

    return match


def _check_durable_sequences(modules: Sequence[LintModule],
                             cfg: CrashConfig) -> List[Finding]:
    registry = load_durable_sequences(modules, cfg)
    findings: List[Finding] = []
    for name, entry in sorted(registry.items()):
        target = str(entry.get("function", ""))
        steps = [str(s) for s in entry.get("steps", [])]
        optional = {int(i) for i in entry.get("optional", [])}
        if not target or not steps:
            continue
        found = False
        for mod in modules:
            for fn, _cls in mod.functions():
                if mod.qualname(fn) != target:
                    continue
                found = True
                aliases, funcs = _seam_names(mod.tree)
                match = _step_matcher(steps, _assignments(fn), aliases,
                                      funcs, cfg)
                try:
                    paths = _enumerate_paths(fn.body, match, cfg)
                except _TooManyPaths:
                    findings.append(Finding(
                        rule="MTP003", file=mod.relpath, line=fn.lineno,
                        message=(f"durable sequence '{name}': control "
                                 f"flow exceeds {_PATH_CAP} paths — "
                                 "refactor or split the protocol body"),
                        symbol=target, detail=f"{name}|toowide"))
                    continue
                seen: Set[str] = set()
                for toks in paths:
                    state = 0
                    for idx in toks:
                        if idx < state:
                            continue  # an already-done step repeated: fine
                        missing = [j for j in range(state, idx)
                                   if j not in optional]
                        if missing:
                            key = f"{name}|{steps[idx]}"
                            if key not in seen:
                                seen.add(key)
                                findings.append(Finding(
                                    rule="MTP003", file=mod.relpath,
                                    line=fn.lineno,
                                    message=(
                                        f"durable sequence '{name}': a "
                                        f"path runs step '{steps[idx]}' "
                                        f"before required step "
                                        f"'{steps[missing[0]]}' — crash "
                                        "between them loses the ordering "
                                        "the recovery protocol assumes"),
                                    symbol=target, detail=key))
                            state = idx + 1
                        else:
                            state = idx + 1
        if not found:
            findings.append(Finding(
                rule="MTP003", file=modules[0].relpath if modules else "?",
                line=1,
                message=(f"durable sequence '{name}' names unknown "
                         f"function '{target}' — registry and code have "
                         "drifted"),
                symbol=target, detail=f"{name}|missing"))
    return findings


# ---------------------------------------------------------------------------
# MTP004 — dead crash barriers
# ---------------------------------------------------------------------------

def _fire_sites(mod: LintModule) -> List[Tuple[int, str, str]]:
    """(line, qualname, kind) for every ``faults.fire("<kind>")`` with a
    string-literal kind."""
    out: List[Tuple[int, str, str]] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func)
        if d is None:
            continue
        head, _, tail = d.rpartition(".")
        if tail != "fire" or "faults" not in head:
            continue
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            out.append((node.lineno, mod.qualname(node), node.args[0].value))
    return out


def _fault_constants(modules: Sequence[LintModule], cfg: CrashConfig
                     ) -> Dict[str, str]:
    """Module-level ``*FAULTS*`` string constants (name -> spec text):
    a test importing the NAME arms every kind the spec mentions."""
    out: Dict[str, str] = {}
    for mod in modules:
        for node in mod.tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            if not any(m in name for m in cfg.fault_const_markers):
                continue
            try:
                val = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                continue
            if isinstance(val, str):
                out[name] = val
            elif isinstance(val, (list, tuple)) and all(
                    isinstance(v, str) for v in val):
                out[name] = ",".join(val)
    return out


def _tests_text(tests_dir: str) -> str:
    chunks: List[str] = []
    for dirpath, _dirs, files in os.walk(tests_dir):
        for fname in sorted(files):
            if fname.endswith(".py"):
                try:
                    with open(os.path.join(dirpath, fname),
                              encoding="utf-8") as f:
                        chunks.append(f.read())
                except OSError:
                    pass
    return "\n".join(chunks)


def _check_dead_barriers(modules: Sequence[LintModule], cfg: CrashConfig,
                         tests_dir: Optional[str]) -> List[Finding]:
    if not tests_dir or not os.path.isdir(tests_dir):
        return []
    text = _tests_text(tests_dir)
    consts = _fault_constants(modules, cfg)
    armed_via_const: Set[str] = set()
    for cname, spec in consts.items():
        if cname in text:
            for part in spec.split(","):
                kind = part.split(":", 1)[0].strip()
                if kind:
                    armed_via_const.add(kind)
    findings: List[Finding] = []
    for mod in modules:
        for ln, qual, kind in _fire_sites(mod):
            if kind in text or kind in armed_via_const:
                continue
            findings.append(Finding(
                rule="MTP004", file=mod.relpath, line=ln,
                message=(f"crash barrier '{kind}' is armed by no test "
                         "(not named in tests/ and not reachable through "
                         "an imported *FAULTS* constant) — dead chaos "
                         "code rots silently"),
                symbol=qual, detail=kind))
    return findings


# ---------------------------------------------------------------------------
# static entry point
# ---------------------------------------------------------------------------

def check_crash(modules: Sequence[LintModule],
                cfg: Optional[CrashConfig] = None,
                tests_dir: Optional[str] = None) -> List[Finding]:
    """Run MTP001-MTP004 over parsed modules; pragma-suppressed findings
    (``# mtpu: lint-ok MTP00x reason``) are dropped here, like every
    other checker family."""
    cfg = cfg or default_crash_config()
    summaries = _Summaries(cfg)
    for mod in modules:
        summaries.add_module(mod)
    findings: List[Finding] = []
    per_mod: Dict[str, LintModule] = {m.relpath: m for m in modules}
    for mod in modules:
        findings.extend(_check_publish_order(mod, summaries, cfg))
        findings.extend(_check_wal_before_ack(mod, cfg))
    findings.extend(_check_durable_sequences(list(modules), cfg))
    findings.extend(_check_dead_barriers(list(modules), cfg, tests_dir))
    out: List[Finding] = []
    for f in findings:
        mod = per_mod.get(f.file)
        if mod is not None and mod.suppressed(f.line, f.rule):
            continue
        out.append(f)
    return out


# ---------------------------------------------------------------------------
# dynamic suites: drive a real durable path, enumerate its crash states,
# recover each one with the real offline recovery, certify the invariants
# ---------------------------------------------------------------------------

def _reset_tree(root: str) -> None:
    shutil.rmtree(root, ignore_errors=True)
    os.makedirs(root, exist_ok=True)


class _Expect:
    """What the acked prefix of a trace promises recovery will rebuild."""

    def __init__(self) -> None:
        self.trials: Set[Tuple[str, str]] = set()            # (exp, tid)
        self.signals: Set[Tuple[str, str, str]] = set()      # exp, tid, sig
        self.deleted: Set[str] = set()
        self.replies: Dict[str, Tuple[int, str]] = {}        # req -> (seq, js)
        self.compacted_upto = 0

    def apply_mark(self, meta: Dict[str, Any]) -> None:
        label = meta.get("label")
        if label == "acked":
            op = meta.get("x_op")
            exp = meta.get("x_exp")
            if op == "register":
                self.trials.add((exp, meta["x_tid"]))
                self.deleted.discard(exp)
            elif op == "set_signal":
                self.signals.add((exp, meta["x_tid"], meta["x_sig"]))
            elif op == "delete_experiment":
                self.deleted.add(exp)
                self.trials = {t for t in self.trials if t[0] != exp}
                self.signals = {s for s in self.signals if s[0] != exp}
            if meta.get("x_req"):
                self.replies[meta["x_req"]] = (
                    int(meta.get("x_seq") or 0), meta.get("x_reply") or "")
        elif label == "wal_compacted":
            self.compacted_upto = max(self.compacted_upto,
                                      int(meta.get("upto") or 0))


def _expect_at(events: Sequence[Dict[str, Any]], upto: int) -> _Expect:
    exp = _Expect()
    for e in events[:upto]:
        if e.get("kind") == "mark":
            exp.apply_mark(e)
    return exp


def _certify_state(label: str, expect: _Expect,
                   state: Dict[str, Dict[str, Any]],
                   findings: List[Finding], suite: str) -> None:
    """Compare one recovered state against the acked-prefix promises."""

    def _fail(rule: str, msg: str, detail: str) -> None:
        findings.append(Finding(
            rule=rule, file=f"<suite:{suite}>", line=0,
            message=f"crash state {label}: {msg}",
            symbol=label, detail=detail))

    recovered_replies: Dict[str, Dict[str, Any]] = {}
    trial_ids: Dict[str, Set[str]] = {}
    sig_map: Dict[Tuple[str, str], str] = {}
    for exp, st in state.items():
        trial_ids[exp] = {d["id"] for d in (st.get("trials") or [])}
        for s in st.get("signals") or []:
            sig_map[(exp, s["trial_id"])] = s["signal"]
        for r in st.get("replies") or []:
            recovered_replies[r["req"]] = r["reply"]
    for (exp, tid) in sorted(expect.trials):
        if tid not in trial_ids.get(exp, set()):
            _fail("MTP101",
                  f"acked trial {exp}/{tid} lost after recovery",
                  f"trial|{exp}|{tid}")
    for (exp, tid, sig) in sorted(expect.signals):
        got = sig_map.get((exp, tid))
        if got != sig:
            _fail("MTP101",
                  f"acked signal {exp}/{tid}={sig!r} lost after recovery "
                  f"(got {got!r})", f"signal|{exp}|{tid}")
    for exp in sorted(expect.deleted):
        if trial_ids.get(exp):
            _fail("MTP101",
                  f"acked delete of {exp} resurrected "
                  f"{len(trial_ids[exp])} trial(s)", f"delete|{exp}")
    for req, (seq, reply_js) in sorted(expect.replies.items()):
        if seq and seq <= expect.compacted_upto:
            continue  # compaction legitimately retires journaled replies
        got = recovered_replies.get(req)
        if got is None:
            _fail("MTP102", f"acked reply {req} missing from the "
                  "recovered reply cache (retry would re-execute)",
                  f"reply|{req}")
        elif json.dumps(got, sort_keys=True, default=str) != reply_js:
            _fail("MTP102", f"acked reply {req} not bit-identical after "
                  "recovery (exactly-once broken)", f"replydiff|{req}")


def _recover_files(root: str) -> Dict[str, Dict[str, Any]]:
    from metaopt_tpu.coord.handoff import recover_shard_state
    snap = os.path.join(root, "snap.json")
    return recover_shard_state(snap, snap + ".wal")


def _enumerate_and_certify(root: str, events: List[Dict[str, Any]],
                           suite: str, torn_cuts: Optional[int],
                           findings: List[Finding]) -> int:
    """Materialize every crash state into ``root`` (original absolute
    paths, so evict-file references recorded in the WAL resolve) and run
    the real recovery + certifier.  Returns the state count."""
    from metaopt_tpu.utils import fsjournal as fsj
    states = 0
    for label, upto, files in fsj.enumerate_crash_states(
            events, torn_cuts=torn_cuts):
        states += 1
        _reset_tree(root)
        fsj.write_tree(files, root)
        expect = _expect_at(events, upto)
        try:
            state = _recover_files(root)
            again = _recover_files(root)
        except Exception as exc:  # noqa: BLE001 - any crash is the finding
            findings.append(Finding(
                rule="MTP103", file=f"<suite:{suite}>", line=0,
                message=f"crash state {label}: recovery raised "
                        f"{type(exc).__name__}: {exc}",
                symbol=label, detail=f"raise|{type(exc).__name__}"))
            continue
        _certify_state(label, expect, state, findings, suite)
        if json.dumps(state, sort_keys=True, default=str) != \
                json.dumps(again, sort_keys=True, default=str):
            findings.append(Finding(
                rule="MTP103", file=f"<suite:{suite}>", line=0,
                message=f"crash state {label}: recovery is not "
                        "idempotent (second pass differs)",
                symbol=label, detail="nonidempotent"))
    return states


def _offline_server(root: str, **kw: Any):
    """A CoordServer used as a library: no sockets, no threads, requests
    driven straight through ``_handle`` with the sender's durability
    barrier emulated inline — deterministic by construction."""
    from metaopt_tpu.coord.server import CoordServer
    server = CoordServer(
        snapshot_path=os.path.join(root, "snap.json"),
        snapshot_interval_s=3600.0,
        host_algorithms=False,
        wal_group_ms=0.0,
        **kw)
    server._recover()
    return server


def _call(server: Any, op: str, args: Dict[str, Any],
          req: Optional[str] = None, **mark_extra: Any) -> Dict[str, Any]:
    from metaopt_tpu.utils import fsjournal as fsj
    msg: Dict[str, Any] = {"op": op, "args": args}
    if req is not None:
        msg["req"] = req
    reply = server._handle(msg)
    if not (isinstance(reply, dict) and reply.get("ok")):
        raise RuntimeError(f"{op} failed: {reply!r}")
    barrier = server._barrier_seq(op)
    if barrier and server._wal is not None:
        server._wal.sync(barrier)  # what the live _sender does pre-send
    fsj.mark("acked", x_op=op, x_req=req, x_seq=barrier,
             x_reply=json.dumps(reply, sort_keys=True, default=str),
             **mark_extra)
    return reply


def _trial_doc(exp: str, tid: str, x: float) -> Dict[str, Any]:
    from metaopt_tpu.ledger.trial import Trial
    return Trial(params={"x": x}, experiment=exp, id=tid).to_dict()


def _drive_server_suite(root: str, incremental: bool,
                        evict: bool = False) -> List[Dict[str, Any]]:
    """The shared snapshot/archive/evict scenario: mutate, snapshot,
    mutate past the snapshot, optionally evict + touch, close."""
    from metaopt_tpu.utils import fsjournal as fsj
    kw: Dict[str, Any] = {"snapshot_incremental": incremental}
    if incremental:
        kw["archive_segment_rows"] = 2
    if evict:
        kw["evict_dir"] = os.path.join(root, "evicted")
    server = _offline_server(root, **kw)
    try:
        for name in ("exp_a", "exp_b"):
            _call(server, "create_experiment",
                  {"config": {"name": name}}, req=f"c-{name}",
                  x_exp=name)
        for i in range(3):
            _call(server, "register",
                  {"trial": _trial_doc("exp_a", f"a{i}", float(i))},
                  req=f"r-a{i}", x_exp="exp_a", x_tid=f"a{i}")
        _call(server, "register",
              {"trial": _trial_doc("exp_b", "b0", 7.0)},
              req="r-b0", x_exp="exp_b", x_tid="b0")
        done = _trial_doc("exp_a", "a0", 0.0)
        done["status"] = "reserved"
        _call(server, "update_trial", {"trial": done}, req="u-a0-r",
              x_exp="exp_a", x_tid="a0")
        _call(server, "set_signal",
              {"experiment": "exp_a", "trial_id": "a1", "signal": "pause"},
              req="s-a1", x_exp="exp_a", x_tid="a1", x_sig="pause")
        server.snapshot(server.snapshot_path)
        fsj.mark("snapshot")
        # past-snapshot tail: must come back from the WAL alone
        _call(server, "register",
              {"trial": _trial_doc("exp_a", "a3", 3.0)},
              req="r-a3", x_exp="exp_a", x_tid="a3")
        if evict:
            assert server.evict_experiment("exp_b"), "evict refused"
            fsj.mark("evicted", x_exp="exp_b")
            # touching an evicted experiment hydrates it back
            _call(server, "set_signal",
                  {"experiment": "exp_b", "trial_id": "b0",
                   "signal": "pause"},
                  req="s-b0", x_exp="exp_b", x_tid="b0", x_sig="pause")
        if incremental:
            # a second snapshot: seals new segments, GCs dead ones
            _call(server, "register",
                  {"trial": _trial_doc("exp_b", "b1", 8.0)},
                  req="r-b1", x_exp="exp_b", x_tid="b1")
            server.snapshot(server.snapshot_path)
            fsj.mark("snapshot")
        _call(server, "register",
              {"trial": _trial_doc("exp_a", "a4", 4.0)},
              req="r-a4", x_exp="exp_a", x_tid="a4")
    finally:
        if server._wal is not None:
            server._wal.close()
    journal = fsj.installed()
    assert journal is not None
    return journal.snapshot()


def _suite_wal(root: str, findings: List[Finding]) -> Tuple[int, int]:
    """Bare WriteAheadLog: group commit, a v1-fallback record inside a
    v2 log (a >64-bit int defeats msgpack), compaction mid-stream.
    Every byte cut of every append is enumerated (torn_cuts=None)."""
    from metaopt_tpu.coord.wal import WriteAheadLog, read_records
    from metaopt_tpu.utils import fsjournal as fsj

    path = os.path.join(root, "snap.json.wal")
    with fsj.recording(root) as journal:
        wal = WriteAheadLog(path, group_window_s=0.0).open()
        acked: List[int] = []
        for i in range(4):
            seq = wal.append({"op": "set_signal", "experiment": "e",
                              "trial_id": f"t{i}", "signal": "pause"})
            wal.sync(seq)
            fsj.mark("acked_seq", seq=seq)
            acked.append(seq)
        # v1 fallback inside a v2 log: msgpack cannot carry 1 << 70
        seq = wal.append({"op": "x_mixed", "n": 1 << 70})
        wal.sync(seq)
        fsj.mark("acked_seq", seq=seq)
        wal.compact(acked[1])  # rewrite: drops seqs 1..2, keeps the tail
        for i in range(2):
            seq = wal.append({"op": "set_signal", "experiment": "e",
                              "trial_id": f"u{i}", "signal": "resume"})
            wal.sync(seq)
            fsj.mark("acked_seq", seq=seq)
        wal.close()
        events = journal.snapshot()

    states = 0
    for label, upto, files in fsj.enumerate_crash_states(events,
                                                         torn_cuts=None):
        states += 1
        _reset_tree(root)
        fsj.write_tree(files, root)
        acked_seqs: List[int] = []
        compacted = 0
        for e in events[:upto]:
            if e.get("kind") != "mark":
                continue
            if e.get("label") == "acked_seq":
                acked_seqs.append(int(e["seq"]))
            elif e.get("label") == "wal_compacted":
                compacted = max(compacted, int(e.get("upto") or 0))
        try:
            recs, torn = read_records(path, truncate_torn=True)
            recs2, torn2 = read_records(path, truncate_torn=True)
        except Exception as exc:  # noqa: BLE001
            findings.append(Finding(
                rule="MTP103", file="<suite:wal>", line=0,
                message=f"crash state {label}: read_records raised "
                        f"{type(exc).__name__}: {exc}",
                symbol=label, detail=f"raise|{type(exc).__name__}"))
            continue
        got = {r.get("seq") for r in recs}
        for seq in acked_seqs:
            if seq not in got and seq > compacted:
                findings.append(Finding(
                    rule="MTP101", file="<suite:wal>", line=0,
                    message=f"crash state {label}: acked record seq={seq} "
                            "lost after torn-tail recovery",
                    symbol=label, detail=f"seq|{seq}"))
        if torn2 != 0 or [r.get("seq") for r in recs2] != \
                [r.get("seq") for r in recs]:
            findings.append(Finding(
                rule="MTP103", file="<suite:wal>", line=0,
                message=f"crash state {label}: torn-tail truncation is "
                        "not idempotent",
                symbol=label, detail="nonidempotent"))
    return states, len(events)


def _suite_server(root: str, findings: List[Finding], suite: str,
                  incremental: bool, evict: bool) -> Tuple[int, int]:
    from metaopt_tpu.utils import fsjournal as fsj
    with fsj.recording(root):
        events = _drive_server_suite(root, incremental=incremental,
                                     evict=evict)
    states = _enumerate_and_certify(root, events, suite, torn_cuts=3,
                                    findings=findings)
    return states, len(events)


def _suite_handoff(root: str, findings: List[Finding]) -> Tuple[int, int]:
    """Destination side of a shard hand-off: apply a shipped state twice
    (the retry path), certify every crash state of the dest's disk and
    end-to-end idempotence."""
    from metaopt_tpu.utils import fsjournal as fsj

    tids = [f"h{i}" for i in range(3)]
    shipped = {
        "experiment": {"name": "exp_h"},
        "trials": [_trial_doc("exp_h", t, float(i))
                   for i, t in enumerate(tids)],
        "signals": [{"trial_id": "h1", "signal": "pause"}],
        "replies": [{"req": "ship-1",
                     "reply": {"ok": True, "result": {"id": "h2"}}}],
        "wal_tail": [],
    }
    with fsj.recording(root) as journal:
        server = _offline_server(root)
        try:
            for attempt in (1, 2):  # the retry after a lost ack
                out = server._handoff_apply({
                    "experiment": "exp_h",
                    "state": json.loads(json.dumps(shipped)),
                })
                if not out.get("ok"):
                    raise RuntimeError(f"handoff_apply failed: {out!r}")
                barrier = server._barrier_seq("handoff_apply")
                if barrier and server._wal is not None:
                    server._wal.sync(barrier)
                fsj.mark("acked", x_op="handoff_apply", x_seq=barrier,
                         x_exp="exp_h")
                for tid in tids:
                    fsj.mark("acked", x_op="register", x_exp="exp_h",
                             x_tid=tid)
                fsj.mark("acked", x_op="set_signal", x_exp="exp_h",
                         x_tid="h1", x_sig="pause")
        finally:
            if server._wal is not None:
                server._wal.close()
        events = journal.snapshot()

    states = _enumerate_and_certify(root, events, "handoff", torn_cuts=3,
                                    findings=findings)
    # end-to-end: the double apply must not duplicate or drop anything,
    # and the SHIPPED reply must be re-journaled on the dest
    _reset_tree(root)
    fsj.write_tree(fsj.materialize(events, len(events)), root)
    final = _recover_files(root)
    got = {d["id"] for d in (final.get("exp_h") or {}).get("trials") or []}
    if got != set(tids):
        findings.append(Finding(
            rule="MTP101", file="<suite:handoff>", line=0,
            message=f"double handoff_apply diverged: recovered trials "
                    f"{sorted(got)} != shipped {tids}",
            symbol="final", detail="applydiff"))
    if "ship-1" not in {r["req"] for st in final.values()
                        for r in (st.get("replies") or [])}:
        findings.append(Finding(
            rule="MTP102", file="<suite:handoff>", line=0,
            message="shipped reply 'ship-1' not re-journaled by "
                    "handoff_apply (retry on the survivor re-executes)",
            symbol="final", detail="reply|ship-1"))
    return states, len(events)


def _run_one(name: str) -> Tuple[List[Finding], Dict[str, Any]]:
    import tempfile
    findings: List[Finding] = []
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix=f"crashcheck-{name}-") as root:
        if name == "wal":
            states, events = _suite_wal(root, findings)
        elif name == "snapshot":
            states, events = _suite_server(root, findings, "snapshot",
                                           incremental=False, evict=False)
        elif name == "archive":
            states, events = _suite_server(root, findings, "archive",
                                           incremental=True, evict=False)
        elif name == "evict":
            states, events = _suite_server(root, findings, "evict",
                                           incremental=False, evict=True)
        elif name == "handoff":
            states, events = _suite_handoff(root, findings)
        else:
            raise ValueError(f"unknown crashcheck suite: {name!r}")
    stats = {"suite": name, "crash_states": states, "events": events,
             "runtime_s": round(time.monotonic() - t0, 3)}
    return findings, stats


SUITES = ("wal", "snapshot", "archive", "evict", "handoff")


def run_suite(name: str) -> Tuple[List[Finding], Dict[str, Any]]:
    """Run one dynamic suite; returns (findings, stats).  ``name`` must
    be one of ``SUITES``."""
    return _run_one(name)
