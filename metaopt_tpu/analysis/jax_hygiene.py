"""JAX hygiene checkers (MTJ001-MTJ004).

**Traced set.** A function is jit-traced when it is (a) decorated with
``jax.jit`` / ``functools.partial(jax.jit, ...)``, (b) passed to a
``jax.jit(...)`` call anywhere in the scanned set, or (c) defined inside
a factory whose *result* is jitted (``jax.jit(make_train_step(...))``
marks the closures ``make_train_step`` defines — the repo's train-step
builder idiom). The set then closes transitively over bare-name calls,
so ``train_step -> loss_fn -> blocked_xent_enabled`` is all traced.

* **MTJ001** — a buffer passed in a donated position (``donate_argnums``)
  is read later in the same function without being reassigned first.
  Reassignment in the statement that makes the call (``x, y = f(x, y)``)
  is the sanctioned idiom and is clean.
* **MTJ002** — a traced function calls an ambient mutable-context getter
  (``active_mesh()``, ``os.environ.get``, ``time.time`` ...): the value
  is frozen at trace time and silently stale on cache hits — the
  ADVICE round-5 ``blocked_xent_enabled()`` bug class.
* **MTJ003** — a host-sync call (``np.asarray``, ``.item()``,
  ``.block_until_ready()``, ``float()`` ...) inside a function marked hot
  via the ``# mtpu: hotpath`` pragma or the config registry.
* **MTJ004** — ``static_argnames`` declarations that are not literal
  strings, or call sites binding an unhashable literal (list/dict/set)
  to a declared-static parameter.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from metaopt_tpu.analysis.core import (
    Finding, LintModule, dotted_name, is_hashable_literal)
from metaopt_tpu.analysis.registry import LintConfig

_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp, ast.GeneratorExp)


def _is_jit_name(node: ast.AST) -> bool:
    dn = dotted_name(node)
    return dn is not None and (dn == "jit" or dn.endswith(".jit"))


@dataclass
class _JitSpec:
    donate: Tuple[int, ...] = ()
    static_names: Tuple[str, ...] = ()
    bad_static_decl: Optional[int] = None  # line of a non-literal decl


def _jit_kwargs(call: ast.Call) -> _JitSpec:
    spec = _JitSpec()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            try:
                v = ast.literal_eval(kw.value)
                spec.donate = tuple(v) if isinstance(
                    v, (tuple, list)) else (int(v),)
            except (ValueError, TypeError, SyntaxError):
                pass
        elif kw.arg in ("static_argnames", "static_argnums"):
            try:
                v = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                spec.bad_static_decl = kw.value.lineno
                continue
            if kw.arg == "static_argnames":
                names = (v,) if isinstance(v, str) else tuple(v)
                if all(isinstance(n, str) for n in names):
                    spec.static_names = names
                else:
                    spec.bad_static_decl = kw.value.lineno
    return spec


class JaxChecker:
    def __init__(self, modules: List[LintModule], cfg: LintConfig) -> None:
        self.modules = modules
        self.cfg = cfg
        # function-name -> (module, def node); bare-name call graph
        self.defs: Dict[str, List[Tuple[LintModule, ast.FunctionDef]]] = {}
        for mod in modules:
            for fn, _cls in mod.functions():
                self.defs.setdefault(fn.name, []).append((mod, fn))
        #: name -> _JitSpec for functions jitted with donation/statics
        self.jitted: Dict[str, _JitSpec] = {}
        self.traced: Set[str] = set()
        self._find_jitted()
        self._close_traced()

    # -- traced-set construction ------------------------------------------
    def _find_jitted(self) -> None:
        for mod in self.modules:
            for fn, _cls in mod.functions():
                for dec in fn.decorator_list:
                    spec = self._spec_of(dec)
                    if spec is not None:
                        self.jitted.setdefault(fn.name, spec)
                        self.traced.add(fn.name)
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and _is_jit_name(node.func) and node.args):
                    continue
                spec = _jit_kwargs(node)
                tgt = node.args[0]
                if isinstance(tgt, ast.Name):
                    self.traced.add(tgt.id)
                    name = tgt.id
                elif isinstance(tgt, ast.Call) and isinstance(
                        tgt.func, ast.Name):
                    # jax.jit(make_train_step(...)): the factory's nested
                    # defs are the traced bodies
                    name = None
                    for fmod, fdef in self.defs.get(tgt.func.id, ()):
                        for sub in ast.walk(fdef):
                            if isinstance(sub, ast.FunctionDef
                                          ) and sub is not fdef:
                                self.traced.add(sub.name)
                                name = sub.name
                else:
                    continue
                # bind the spec to the jitted value's assigned name too,
                # so call sites through that name are checked
                if name:
                    self.jitted.setdefault(name, spec)
                parent = mod.parents.get(node)
                if isinstance(parent, ast.Assign) and len(
                        parent.targets) == 1 and isinstance(
                        parent.targets[0], ast.Name):
                    self.jitted.setdefault(parent.targets[0].id, spec)

    def _spec_of(self, dec: ast.AST) -> Optional[_JitSpec]:
        """A decorator that jits: ``@jax.jit`` or
        ``@functools.partial(jax.jit, ...)`` (or a jit(...) call)."""
        if _is_jit_name(dec):
            return _JitSpec()
        if isinstance(dec, ast.Call):
            if _is_jit_name(dec.func):
                return _jit_kwargs(dec)
            dn = dotted_name(dec.func)
            if dn and dn.split(".")[-1] == "partial" and dec.args and \
                    _is_jit_name(dec.args[0]):
                return _jit_kwargs(dec)
        return None

    def _close_traced(self) -> None:
        work = list(self.traced)
        while work:
            name = work.pop()
            for mod, fn in self.defs.get(name, ()):
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        dn = dotted_name(node.func)
                        if dn and "." not in dn and dn in self.defs \
                                and dn not in self.traced:
                            self.traced.add(dn)
                            work.append(dn)

    # -- findings ----------------------------------------------------------
    def run(self) -> List[Finding]:
        out: List[Finding] = []
        for mod in self.modules:
            for fn, cls in mod.functions():
                qn = mod.qualname(fn)
                if fn.name in self.traced:
                    out.extend(self._ambient(mod, fn, qn))
                if self._is_hot(mod, fn, cls):
                    out.extend(self._host_sync(mod, fn, qn))
                out.extend(self._donation_sites(mod, fn, qn))
                out.extend(self._static_args(mod, fn, qn))
        for name, spec in sorted(self.jitted.items()):
            if spec.bad_static_decl is not None:
                for mod, fn in self.defs.get(name, ()):
                    out.append(Finding(
                        "MTJ004", mod.relpath, spec.bad_static_decl,
                        f"static_argnames of {name} is not a literal "
                        f"str/tuple of str", symbol=name,
                        detail=f"{name}|decl"))
        return [f for f in out if not self._suppressed(f)]

    def _is_hot(self, mod: LintModule, fn: ast.FunctionDef,
                cls) -> bool:
        if mod.is_hotpath(fn):
            return True
        qn = f"{cls.name}.{fn.name}" if cls is not None else fn.name
        reg = self.cfg.hotpath_registry
        return fn.name in reg or qn in reg

    def _ambient(self, mod: LintModule, fn: ast.FunctionDef,
                 qn: str) -> List[Finding]:
        out = []
        for node in ast.walk(fn):
            dn: Optional[str] = None
            if isinstance(node, ast.Call):
                dn = dotted_name(node.func)
            elif isinstance(node, ast.Subscript):
                base = dotted_name(node.value)
                if base and base.split(".")[-1] == "environ":
                    dn = base + ".get"
            if dn is None:
                continue
            for pat in self.cfg.ambient_getters:
                if dn == pat or dn.endswith("." + pat):
                    out.append(Finding(
                        "MTJ002", mod.relpath, node.lineno,
                        f"{qn} is jit-traced but reads ambient context "
                        f"via {dn}() — the value freezes at trace time",
                        symbol=qn, detail=pat))
                    break
        return out

    def _host_sync(self, mod: LintModule, fn: ast.FunctionDef,
                   qn: str) -> List[Finding]:
        out = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if dn is None:
                continue
            last = dn.split(".")[-1]
            for pat in self.cfg.host_sync_calls:
                hit = (dn == pat or dn.endswith("." + pat)) if "." in pat \
                    else last == pat
                if not hit:
                    continue
                if last in ("float", "int", "bool") and (
                        not node.args
                        or isinstance(node.args[0], ast.Constant)):
                    continue
                out.append(Finding(
                    "MTJ003", mod.relpath, node.lineno,
                    f"host-sync call {dn}() inside hotpath {qn}",
                    symbol=qn, detail=pat))
                break
        return out

    # -- donation ----------------------------------------------------------
    def _donation_sites(self, mod: LintModule, fn: ast.FunctionDef,
                        qn: str) -> List[Finding]:
        """Linear scan of ``fn``'s statements: after a call to a
        donated-jit function, a donated argument read again before being
        reassigned is MTJ001."""
        out = []
        stmts = list(ast.walk(fn))
        calls: List[Tuple[ast.Call, _JitSpec, Set[str]]] = []
        for node in stmts:
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if dn is None:
                continue
            spec = self.jitted.get(dn.split(".")[-1])
            if spec is None or not spec.donate:
                continue
            donated: Set[str] = set()
            for idx in spec.donate:
                if idx < len(node.args):
                    adn = dotted_name(node.args[idx])
                    if adn:
                        donated.add(adn)
            if donated:
                calls.append((node, spec, donated))
        for call, spec, donated in calls:
            parent = mod.parents.get(call)
            reassigned: Set[str] = set()
            while parent is not None and not isinstance(
                    parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if isinstance(parent, ast.Assign):
                    for t in parent.targets:
                        reassigned |= self._target_names(t)
                parent = mod.parents.get(parent)
            live = donated - reassigned
            if not live:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Name, ast.Attribute)) \
                        or node.lineno <= call.lineno:
                    continue
                dn = dotted_name(node)
                if dn in live and isinstance(
                        getattr(node, "ctx", None), ast.Load):
                    out.append(Finding(
                        "MTJ001", mod.relpath, node.lineno,
                        f"{dn} was donated at line {call.lineno} and is "
                        f"read again without reassignment "
                        f"(use-after-donation)", symbol=qn, detail=dn))
                    live.discard(dn)
                elif dn in live and isinstance(
                        getattr(node, "ctx", None), ast.Store):
                    live.discard(dn)
        return out

    def _target_names(self, tgt: ast.AST) -> Set[str]:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            out: Set[str] = set()
            for e in tgt.elts:
                out |= self._target_names(e)
            return out
        dn = dotted_name(tgt)
        return {dn} if dn else set()

    # -- static_argnames at call sites -------------------------------------
    def _static_args(self, mod: LintModule, fn: ast.FunctionDef,
                     qn: str) -> List[Finding]:
        out = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if dn is None:
                continue
            spec = self.jitted.get(dn.split(".")[-1])
            if spec is None or not spec.static_names:
                continue
            for kw in node.keywords:
                if kw.arg in spec.static_names and isinstance(
                        kw.value, _UNHASHABLE):
                    out.append(Finding(
                        "MTJ004", mod.relpath, kw.value.lineno,
                        f"unhashable literal bound to static arg "
                        f"{kw.arg!r} of {dn}", symbol=qn,
                        detail=f"{dn.split('.')[-1]}|{kw.arg}"))
        return out

    def _suppressed(self, f: Finding) -> bool:
        for mod in self.modules:
            if mod.relpath == f.file:
                return mod.suppressed(f.line, f.rule)
        return False


def check_jax(modules: List[LintModule], cfg: LintConfig
              ) -> List[Finding]:
    return JaxChecker(modules, cfg).run()
