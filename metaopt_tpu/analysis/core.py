"""Shared plumbing for the ``mtpu lint`` checkers.

One :class:`LintModule` per source file: the parsed AST, a parent map
(for enclosing-function/class lookup), the raw source lines, and every
``# mtpu:`` pragma found in the file, indexed by line. Checkers never
re-read files — they get the loaded modules and a
:class:`~metaopt_tpu.analysis.registry.LintConfig`.

Pragma grammar (one per comment; the comment may trail code)::

    # mtpu: hotpath
    # mtpu: holds(<lock>[, <lock>...])
    # mtpu: lint-ok <RULE> [free-text reason]

``hotpath`` and ``holds`` attach to the ``def`` they annotate (same line
as the ``def``, or the line directly above it). ``lint-ok`` suppresses
one rule on exactly the line it sits on.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

_PRAGMA_RE = re.compile(r"#\s*mtpu:\s*(.+?)\s*$")
_HOLDS_RE = re.compile(r"holds\(([^)]*)\)")
_LINT_OK_RE = re.compile(r"lint-ok\s+([A-Z]{3}\d{3})")


@dataclass(frozen=True)
class Finding:
    """One lint finding: rule + location + a stable identity.

    ``symbol`` is the enclosing ``Class.function`` qualname and ``detail``
    a short rule-specific key (attr/op/lock names) — together with the
    rule and file they form the baseline fingerprint, which survives
    line-number drift from unrelated edits.
    """

    rule: str
    file: str
    line: int
    message: str
    symbol: str = ""
    detail: str = ""

    def fingerprint(self) -> str:
        return "::".join((self.rule, self.file, self.symbol, self.detail))

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} {self.message}"


class LintModule:
    """A parsed source file plus the lookup tables checkers need."""

    def __init__(self, path: str, relpath: str, source: str) -> None:
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        # line -> raw pragma payloads ("hotpath", "holds(_lock)", ...)
        self.pragmas: Dict[int, List[str]] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(text)
            if m:
                self.pragmas.setdefault(i, []).append(m.group(1))

    # -- pragma queries ----------------------------------------------------
    def _def_pragmas(self, fn: ast.AST) -> List[str]:
        """Pragmas attached to a def: on its line or the line above
        (above any decorators)."""
        first = min([fn.lineno] + [d.lineno for d in fn.decorator_list])
        out: List[str] = []
        for ln in (first - 1, fn.lineno):
            out.extend(self.pragmas.get(ln, ()))
        return out

    def is_hotpath(self, fn: ast.AST) -> bool:
        return any(p.startswith("hotpath") for p in self._def_pragmas(fn))

    def holds_locks(self, fn: ast.AST) -> Set[str]:
        """Lock names a ``holds(...)`` pragma asserts the caller owns."""
        out: Set[str] = set()
        for p in self._def_pragmas(fn):
            m = _HOLDS_RE.search(p)
            if m:
                out.update(s.strip() for s in m.group(1).split(",")
                           if s.strip())
        return out

    def suppressed(self, line: int, rule: str) -> bool:
        for p in self.pragmas.get(line, ()):
            m = _LINT_OK_RE.search(p)
            if m and m.group(1) == rule:
                return True
        return False

    # -- structure queries -------------------------------------------------
    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.parents.get(cur)
        return None

    def qualname(self, node: ast.AST) -> str:
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts)) or "<module>"

    def functions(self) -> Iterable[Tuple[ast.FunctionDef,
                                          Optional[ast.ClassDef]]]:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node, self.enclosing_class(node)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``self._wal.append`` -> "self._wal.append"; None when the callee is
    not a plain name/attribute chain (subscripts, calls, lambdas)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_hashable_literal(node: ast.AST) -> bool:
    """Conservative: literals that are certainly hashable."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Tuple):
        return all(is_hashable_literal(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp) and isinstance(node.operand,
                                                    ast.Constant):
        return True
    return False


def load_paths(paths: Iterable[str], root: Optional[str] = None
               ) -> List[LintModule]:
    """Load every ``.py`` under the given files/directories (sorted,
    deterministic). ``relpath`` is relative to ``root`` (default: cwd)."""
    root = os.path.abspath(root or os.getcwd())
    files: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git"))
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames)
                             if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    modules: List[LintModule] = []
    for f in sorted(set(files)):
        rel = os.path.relpath(f, root)
        with open(f, "r", encoding="utf-8") as fh:
            src = fh.read()
        try:
            modules.append(LintModule(f, rel, src))
        except SyntaxError as e:  # pragma: no cover - repo parses clean
            raise SyntaxError(f"lint: cannot parse {rel}: {e}") from e
    return modules
