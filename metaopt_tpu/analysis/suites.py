"""Designated concurrency workloads for ``mtpu race``.

Each suite is a short, deterministic-in-shape (not in interleaving)
workload chosen to push the repo's real thread families through their
shared state while instrumented:

* ``coord`` — a live :class:`CoordServer` with WAL + snapshots enabled
  and aggressive housekeeping, under 8 client threads running the fused
  ``worker_cycle`` loop with deferred ``complete`` legs. Exercises
  accept/conn/sender threads, the sharded per-experiment locks, the
  reply cache, group commit, and the sweep/snapshot loop. A second
  phase drives a 2-shard :class:`ShardSupervisor`: the shard processes
  themselves are outside the instrumented interpreter, so the surface
  under test is the in-process client routing state (`_ring`,
  `_shard_addrs`, per-address incarnations under ``_caps_lock``), the
  router's connection set, and the supervisor's proc bookkeeping. A
  third phase bounces a live experiment between two shards with
  ``sup.handoff`` under concurrent writers: the client's monotonic map
  adoption + ``Migrating`` retry loop, the router's table swap under
  ``_map_lock``, and the supervisor's committed-map bookkeeping race. A
  fourth phase runs two batched workers (``workon(batch_size=...)``)
  sharing ONE :class:`BatchedExecutor` against an algorithm-hosting
  server: the fused multi-trial ``complete`` leg, the reservation race
  for pool slots, and the executor's launch telemetry under
  ``_tel_lock``. A fifth phase runs a mixed-wire fleet against one
  UDS-enabled server — a pinned-JSON client, a binary (wire v2)
  client, and a UDS-fast-path client concurrently — so the
  per-address wire/uds caches under ``_caps_lock``, the server's
  wire-keyed encode cache, and the per-connection codec detection all
  race across codecs. A sixth phase runs two weighted tenants whose
  experiment fleet is twice the server's residency budget: the
  housekeeping sweep keeps evicting LRU experiments while workers
  hydrate them back on first touch, so the tenancy map + WDRR
  scheduler under ``_tenant_lock`` and the residency bookkeeping
  (``_evicted``, ``_exp_last_touch``, eviction/hydration counters)
  under ``_evict_lock`` race the stub-indexed ``tenant_stats`` read
  path. A seventh phase turns the fused suggest plane on
  (``fuse_suggest=True`` at an aggressive tick interval) over a
  TPE-hosting fleet wider than the residency budget: the fuser's
  demand sweep (non-blocking launch-lock acquires, bucket launches,
  commit/abort) races per-experiment ``worker_cycle`` produce legs,
  the evict sweep tearing members down mid-sweep, and a
  ``tenant_stats`` prober reading the fuser telemetry under
  ``SuggestFuser._lock``.
* ``algo`` — CMA-ES (numpy-only: no compile cost inside the detector)
  with ``suggest_prefetch_depth=2``, a driver thread running
  suggest/observe generations against the SuggestAhead refill thread,
  and a prober thread hammering ``state_dict`` + telemetry — the
  workload shape that held the PR-4 MOTPE lock-order inversion.
* ``wal`` — 4 appender threads doing append+sync group commits against
  a compactor thread and a final close(), kill-free (the chaos fault
  points stay unarmed), on a real file so fsync windows are realistic.
* ``sim`` — a live threaded :class:`CoordServer` running on a shared
  :class:`VirtualClock` (the scale simulator's clock seam) under client
  worker threads, while an advancer thread pushes virtual time past
  stale-sweep expiries. The discrete-event simulator itself is
  single-threaded, but the seam is also used by tests that inject a
  virtual clock into a *started* server — so ``VirtualClock._now``
  under ``_lock`` must survive conn/housekeeping threads reading
  ``time()``/``monotonic()`` against concurrent ``advance()`` calls,
  and the sweep must keep CAS-releasing reservations whose heartbeats
  aged out in virtual (not wall) time.

Suites construct everything they touch INSIDE the instrumented region
(locks must be minted under instrumentation to be wrapped) and join all
their threads before returning — the conftest leak check and the
detector's join-edges both depend on it.

``scale`` multiplies iteration counts: 1 is the tier-1-friendly fast
run, the ``slow``-marked chaos-length variant passes more.
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Callable, Dict, List


def suite_coord(scale: int = 1) -> None:
    from metaopt_tpu.coord import CoordLedgerClient, CoordServer
    from metaopt_tpu.ledger import Experiment
    from metaopt_tpu.space import build_space

    workers = 8
    budget = workers * 6 * scale
    with tempfile.TemporaryDirectory() as td:
        snap = os.path.join(td, "coord.snap")
        with CoordServer(snapshot_path=snap, snapshot_interval_s=0.2,
                         stale_timeout_s=5.0, sweep_interval_s=0.1) as s:
            host, port = s.address
            c0 = CoordLedgerClient(host=host, port=port)
            Experiment(
                "race-coord", c0,
                space=build_space({"x": "uniform(-5, 5)"}),
                max_trials=budget, pool_size=workers,
                algorithm={"random": {"seed": 7}},
            ).configure()
            errors: List[BaseException] = []
            # odd workers share ONE client: cross-thread client state
            # (caps cache, live-reservation map, socket lock) is part of
            # the surface — benchmarks/coord_scale.py shares clients too
            shared = CoordLedgerClient(host=host, port=port)

            def worker(i: int) -> None:
                try:
                    c = (shared if i % 2
                         else CoordLedgerClient(host=host, port=port))
                    complete = None
                    for _ in range(budget * 4):
                        out = c.worker_cycle(
                            "race-coord", f"w{i}", pool_size=workers,
                            complete=complete)
                        complete = None
                        t = out["trial"]
                        if t is None:
                            if out["counts"]["completed"] >= budget:
                                return
                            continue
                        t.attach_results([{
                            "name": "objective", "type": "objective",
                            "value": (t.params["x"] - 1) ** 2,
                        }])
                        t.transition("completed")
                        complete = {"trial": t.to_dict(),
                                    "expected_status": "reserved",
                                    "expected_worker": f"w{i}"}
                except BaseException as e:  # surfaced by the runner
                    errors.append(e)

            threads = [threading.Thread(target=worker, args=(i,),
                                        name=f"race-worker-{i}")
                       for i in range(workers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120.0)
            if errors:
                raise errors[0]
    _coord_sharded_phase(scale)
    _coord_handoff_phase(scale)
    _coord_batched_phase(scale)
    _coord_mixed_wire_phase(scale)
    _coord_multitenant_phase(scale)
    _coord_fuser_phase(scale)
    _coord_archive_phase(scale)


def _coord_archive_phase(scale: int = 1) -> None:
    """Columnar-archive leg of the coord suite: a tiny ``segment_rows``
    makes every few completions seal a segment while the housekeeping
    loop takes incremental snapshots, so the archive's seal/append path
    (``_seg_lock`` under ``MemoryLedger._lock``) races snapshot capture
    (section cache + segment export under ``_snap_lock``), lazy batch
    materialization (``fetch_completed_since`` readers walking cursors
    across live sealing), and revivals flipping sealed rows dead from a
    worker thread."""
    from metaopt_tpu.coord import CoordLedgerClient, CoordServer
    from metaopt_tpu.ledger import Experiment, Trial
    from metaopt_tpu.space import build_space

    workers = 4
    budget = workers * 6 * scale
    with tempfile.TemporaryDirectory() as td:
        snap = os.path.join(td, "coord.snap")
        with CoordServer(snapshot_path=snap, snapshot_interval_s=0.05,
                         stale_timeout_s=5.0, sweep_interval_s=0.05,
                         archive_segment_rows=4) as s:
            host, port = s.address
            c0 = CoordLedgerClient(host=host, port=port)
            Experiment(
                "race-archive", c0,
                space=build_space({"x": "uniform(-5, 5)"}),
                max_trials=budget * 2, pool_size=workers,
                algorithm={"random": {"seed": 7}},
            ).configure()
            stop = threading.Event()
            errors: List[BaseException] = []

            def worker(i: int) -> None:
                try:
                    c = CoordLedgerClient(host=host, port=port)
                    done = 0
                    while done < budget // workers:
                        t = Trial(params={"x": float(i * 100 + done)},
                                  experiment="race-archive")
                        c.register(t)
                        got = c.reserve("race-archive", f"aw{i}")
                        if got is None:
                            continue
                        got.attach_results([{
                            "name": "objective", "type": "objective",
                            "value": (got.params["x"] - 1) ** 2,
                        }])
                        got.transition("completed")
                        if c.update_trial(got, expected_status="reserved"):
                            done += 1
                except BaseException as e:
                    errors.append(e)

            def reader() -> None:
                # cursor walker: batches materialize lazily off segments
                # that are being sealed (and snapshotted) under it
                try:
                    c = CoordLedgerClient(host=host, port=port)
                    cur = None
                    while not stop.is_set():
                        batch, cur = c.fetch_completed_since(
                            "race-archive", cur)
                        for t in batch:
                            assert t.status == "completed"
                except BaseException as e:
                    errors.append(e)

            def reviver() -> None:
                # flip completed rows back to new (dead-row path) and let
                # the workers re-complete them
                try:
                    c = CoordLedgerClient(host=host, port=port)
                    while not stop.is_set():
                        done = c.fetch("race-archive", "completed")
                        for t in done[:2]:
                            t.status = "new"
                            t.worker = None
                            t.results = []
                            c.update_trial(t, expected_status="completed")
                        stop.wait(0.02)
                except BaseException as e:
                    errors.append(e)

            threads = [threading.Thread(target=worker, args=(i,),
                                        name=f"race-archive-worker-{i}")
                       for i in range(workers)]
            threads.append(threading.Thread(target=reader,
                                            name="race-archive-reader"))
            threads.append(threading.Thread(target=reviver,
                                            name="race-archive-reviver"))
            for t in threads:
                t.start()
            for t in threads[:workers]:
                t.join(timeout=120.0)
            stop.set()
            for t in threads[workers:]:
                t.join(timeout=30.0)
            if errors:
                raise errors[0]


def _coord_sharded_phase(scale: int = 1) -> None:
    """2-shard leg of the coord suite: worker threads route by the shard
    map through one SHARED client (the routing table, per-address socket
    map and incarnation dict race here), while an old-style client with
    pinned caps drives the router fallback path concurrently."""
    from metaopt_tpu.coord import CoordLedgerClient, ShardSupervisor
    from metaopt_tpu.coord.shards import ring_of
    from metaopt_tpu.ledger import Experiment
    from metaopt_tpu.space import build_space

    workers = 4
    budget = workers * 3 * scale
    with ShardSupervisor(2, restart=False) as sup:
        host, port = sup.address
        # one experiment per shard, so routed traffic exercises both
        ring = ring_of(sup.shard_map)
        names: List[str] = []
        owners: set = set()
        i = 0
        while len(names) < 2:
            nm = f"race-shard{i}"
            if ring.owner(nm) not in owners:
                owners.add(ring.owner(nm))
                names.append(nm)
            i += 1
        shared = CoordLedgerClient(host=host, port=port)
        shared.ping()  # learn the map before the workers fan out
        for nm in names:
            Experiment(
                nm, shared,
                space=build_space({"x": "uniform(-5, 5)"}),
                max_trials=budget, pool_size=workers,
                algorithm={"random": {"seed": 7}},
            ).configure()
        errors: List[BaseException] = []
        stop = threading.Event()

        def worker(i: int) -> None:
            try:
                name = names[i % len(names)]
                for _ in range(budget * 4):
                    out = shared.worker_cycle(
                        name, f"sw{i}", pool_size=workers)
                    t = out["trial"]
                    if t is None:
                        if out["counts"]["completed"] >= budget:
                            return
                        continue
                    t.attach_results([{
                        "name": "objective", "type": "objective",
                        "value": (t.params["x"] - 1) ** 2,
                    }])
                    t.transition("completed")
                    shared.update_trial(
                        t, expected_status="reserved",
                        expected_worker=f"sw{i}")
            except BaseException as e:
                errors.append(e)

        # an old client never learns the map: every op relays through the
        # router (pinned caps predate the shard_map capability)
        legacy = CoordLedgerClient(host=host, port=port)
        legacy._caps = ("count", "fetch_completed_since", "worker_cycle")

        def legacy_prober() -> None:
            try:
                while not stop.is_set():
                    for nm in names:
                        legacy.count(nm, "completed")
            except BaseException as e:
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,),
                                    name=f"race-shard-worker-{i}")
                   for i in range(workers)]
        threads.append(threading.Thread(target=legacy_prober,
                                        name="race-shard-legacy"))
        for t in threads:
            t.start()
        for t in threads[:-1]:
            t.join(timeout=120.0)
        stop.set()
        threads[-1].join(timeout=30.0)
        if errors:
            raise errors[0]


def _coord_handoff_phase(scale: int = 1) -> None:
    """Live-migration leg of the coord suite: worker threads hammer ONE
    experiment through a shared routed client while the main thread
    bounces it between the two shards with ``sup.handoff``. The shard
    processes are uninstrumented; the surface under test is the client's
    monotonic map adoption (``_map_version`` under ``_caps_lock``), its
    ``Migrating``/``WrongShardError`` retry loop, the router's routing
    table swap under ``_map_lock``, and the supervisor's committed map +
    override bookkeeping under ``_procs_lock``."""
    from metaopt_tpu.coord import CoordLedgerClient, ShardSupervisor
    from metaopt_tpu.coord.shards import RoutingTable
    from metaopt_tpu.ledger import Experiment
    from metaopt_tpu.space import build_space

    workers = 4
    budget = workers * 3 * scale
    with tempfile.TemporaryDirectory() as td:
        with ShardSupervisor(2, restart=False,
                             snapshot_dir=os.path.join(td, "snaps")) as sup:
            host, port = sup.address
            nm = "race-handoff"
            shared = CoordLedgerClient(host=host, port=port)
            shared.ping()  # learn the map before the workers fan out
            Experiment(
                nm, shared,
                space=build_space({"x": "uniform(-5, 5)"}),
                max_trials=budget, pool_size=workers,
                algorithm={"random": {"seed": 7}},
            ).configure()
            errors: List[BaseException] = []

            def worker(i: int) -> None:
                try:
                    for _ in range(budget * 6):
                        out = shared.worker_cycle(
                            nm, f"hw{i}", pool_size=workers)
                        t = out["trial"]
                        if t is None:
                            if out["counts"]["completed"] >= budget:
                                return
                            continue
                        t.attach_results([{
                            "name": "objective", "type": "objective",
                            "value": (t.params["x"] - 1) ** 2,
                        }])
                        t.transition("completed")
                        shared.update_trial(
                            t, expected_status="reserved",
                            expected_worker=f"hw{i}")
                except BaseException as e:
                    errors.append(e)

            threads = [threading.Thread(target=worker, args=(i,),
                                        name=f"race-handoff-worker-{i}")
                       for i in range(workers)]
            for t in threads:
                t.start()
            try:
                # bounce the experiment source→dest→source while the
                # workers write through the migration fence
                sids = [s["id"] for s in sup.shard_map["shards"]]
                src = RoutingTable(sup.shard_map).owner(nm)
                dst = next(s for s in sids if s != src)
                for dest in (dst, src):
                    sup.handoff(nm, dest, drain_timeout_s=10.0,
                                window_s=30.0)
            except BaseException as e:
                errors.append(e)
            finally:
                for t in threads:
                    t.join(timeout=120.0)
            if errors:
                raise errors[0]


def _coord_batched_phase(scale: int = 1) -> None:
    """Batched-worker leg of the coord suite: two ``workon`` loops with
    ``batch_size=4`` share ONE :class:`BatchedExecutor` against a live
    algorithm-hosting server. The surface under test is the fused
    multi-trial ``complete`` leg (``completed_oks`` vs the reply cache),
    the cross-worker reservation race for pool slots, and the executor's
    launch/row telemetry counters under ``_tel_lock``. The objective is a
    one-liner so the jit compile inside the instrumented region stays
    cheap."""
    from metaopt_tpu.coord import CoordLedgerClient, CoordServer
    from metaopt_tpu.executor import BatchedExecutor
    from metaopt_tpu.ledger import Experiment
    from metaopt_tpu.space import build_space
    from metaopt_tpu.worker.loop import workon

    import jax.numpy as jnp

    budget = 16 * scale
    with CoordServer(host_algorithms=True, stale_timeout_s=5.0,
                     sweep_interval_s=0.1) as s:
        host, port = s.address
        c0 = CoordLedgerClient(host=host, port=port)
        c0.create_experiment({
            "name": "race-batched", "space": {"x": "uniform(-5, 5)"},
            "max_trials": budget, "pool_size": 4,
            "algorithm": {"random": {"seed": 7}},
        })
        space = build_space({"x": "uniform(-5, 5)"})
        shared_ex = BatchedExecutor(
            lambda cols: (jnp.asarray(cols["x"]) - 1.0) ** 2, space)
        errors: List[BaseException] = []

        def worker(i: int) -> None:
            try:
                c = CoordLedgerClient(host=host, port=port)
                exp = Experiment("race-batched", c).configure()
                workon(exp, shared_ex, worker_id=f"bw{i}",
                       producer_mode="coord", batch_size=4,
                       max_idle_cycles=100)
            except BaseException as e:
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,),
                                    name=f"race-batched-worker-{i}")
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        if errors:
            raise errors[0]


def _coord_mixed_wire_phase(scale: int = 1) -> None:
    """Mixed-wire leg of the coord suite: three client flavors drive one
    UDS-enabled server concurrently — one pinned to JSON (``wire="v1"``),
    one negotiating the binary wire over TCP, and one that adopts the
    advertised Unix-socket fast path. The surface under test is the
    client wire/uds caches under ``_caps_lock`` (negotiation racing the
    exchange loop), the server's wire-keyed preserialized-reply cache,
    and per-connection codec detection when frames of both formats hit
    the same ledger locks. When msgpack is absent every client degrades
    to JSON and the phase still runs as a plain 3-client workload."""
    from metaopt_tpu.coord import CoordLedgerClient, CoordServer
    from metaopt_tpu.ledger import Experiment
    from metaopt_tpu.space import build_space

    flavors = 3
    budget = flavors * 4 * scale
    with tempfile.TemporaryDirectory() as td:
        uds = os.path.join(td, "coord.sock")
        with CoordServer(stale_timeout_s=5.0, sweep_interval_s=0.1,
                         uds_path=uds) as s:
            host, port = s.address
            c0 = CoordLedgerClient(host=host, port=port, wire="v1")
            Experiment(
                "race-wire", c0,
                space=build_space({"x": "uniform(-5, 5)"}),
                max_trials=budget, pool_size=flavors,
                algorithm={"random": {"seed": 7}},
            ).configure()
            clients = [
                CoordLedgerClient(host=host, port=port, wire="v1"),
                CoordLedgerClient(host=host, port=port, wire="auto"),
                CoordLedgerClient(host=host, port=port, wire="auto"),
            ]
            clients[2].ping()  # learn uds_path before the fan-out
            errors: List[BaseException] = []

            def worker(i: int) -> None:
                try:
                    c = clients[i]
                    complete = None
                    for _ in range(budget * 4):
                        out = c.worker_cycle(
                            "race-wire", f"mw{i}", pool_size=flavors,
                            complete=complete)
                        complete = None
                        t = out["trial"]
                        if t is None:
                            if out["counts"]["completed"] >= budget:
                                return
                            continue
                        t.attach_results([{
                            "name": "objective", "type": "objective",
                            "value": (t.params["x"] - 1) ** 2,
                        }])
                        t.transition("completed")
                        complete = {"trial": t.to_dict(),
                                    "expected_status": "reserved",
                                    "expected_worker": f"mw{i}"}
                except BaseException as e:
                    errors.append(e)

            threads = [threading.Thread(target=worker, args=(i,),
                                        name=f"race-wire-{i}")
                       for i in range(flavors)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120.0)
            if errors:
                raise errors[0]


def _coord_multitenant_phase(scale: int = 1) -> None:
    """Multi-tenant leg of the coord suite: two weighted tenants own
    four experiments against a server whose residency budget
    (``max_resident=2``) is half the fleet, so the housekeeping sweep
    keeps evicting the LRU experiments while each worker's next touch
    hydrates its own back. The surface under test is the tenancy map +
    weighted deficit-round-robin arithmetic under ``_tenant_lock``
    (produce legs from both tenants racing the window roll), the
    residency bookkeeping (``_evicted`` stub index, ``_exp_last_touch``,
    eviction/hydration counters) under ``_evict_lock`` racing the sweep
    loop, and the stub-indexed ``tenant_stats`` read path — a prober
    thread hammers it with ``include_experiments=True`` against live
    evictions, which must never hydrate."""
    from metaopt_tpu.coord import CoordLedgerClient, CoordServer

    tenants = ("mt-a", "mt-b")
    per_exp = 6 * scale
    with tempfile.TemporaryDirectory() as td:
        snap = os.path.join(td, "coord.snap")
        with CoordServer(snapshot_path=snap, stale_timeout_s=5.0,
                         sweep_interval_s=0.1, max_resident=2,
                         tenant_weights={"mt-a": 2.0, "mt-b": 1.0}) as s:
            host, port = s.address
            c0 = CoordLedgerClient(host=host, port=port)
            names = []
            for k in range(4):
                nm = f"race-mt-{k}"
                c0.create_experiment({
                    "name": nm, "tenant": tenants[k % 2],
                    "space": {"x": "uniform(-5, 5)"},
                    "max_trials": per_exp, "pool_size": 2,
                    "algorithm": {"random": {"seed": 7 + k}},
                })
                names.append(nm)
            stop = threading.Event()
            errors: List[BaseException] = []

            def prober() -> None:
                # the no-hydrate status scan racing live evictions: the
                # stub index must answer without resurrecting anything
                try:
                    c = CoordLedgerClient(host=host, port=port)
                    while not stop.is_set():
                        st = c.tenant_stats(include_experiments=True)
                        if set(st["experiments"]) != set(names):
                            raise AssertionError(
                                f"status scan lost experiments: {st}")
                except BaseException as e:
                    errors.append(e)

            def worker(i: int) -> None:
                try:
                    c = CoordLedgerClient(host=host, port=port)
                    nm = names[i]
                    complete = None
                    for _ in range(per_exp * 12):
                        out = c.worker_cycle(
                            nm, f"mtw{i}", pool_size=2, complete=complete)
                        complete = None
                        t = out["trial"]
                        if t is None:
                            if out["counts"]["completed"] >= per_exp:
                                return
                            continue
                        t.attach_results([{
                            "name": "objective", "type": "objective",
                            "value": (t.params["x"] - 1) ** 2,
                        }])
                        t.transition("completed")
                        complete = {"trial": t.to_dict(),
                                    "expected_status": "reserved",
                                    "expected_worker": f"mtw{i}"}
                except BaseException as e:
                    errors.append(e)

            threads = [threading.Thread(target=worker, args=(i,),
                                        name=f"race-mt-worker-{i}")
                       for i in range(4)]
            p = threading.Thread(target=prober, name="race-mt-prober")
            p.start()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120.0)
            stop.set()
            p.join(timeout=30.0)
            if errors:
                raise errors[0]


def _coord_fuser_phase(scale: int = 1) -> None:
    """Fused-suggest leg of the coord suite: the fuser's demand sweep
    (housekeeping-adjacent ``coord-fuser`` thread) against a TPE-hosting
    fleet wider than the residency budget. The surface under test is the
    fuser tick racing per-experiment produce legs for each member's
    launch lock (non-blocking acquire → snapshot → bucket launch →
    commit/abort), the evict sweep tearing members down between the
    sweep's lock hand-offs, and the telemetry rollup under
    ``SuggestFuser._lock`` racing a ``tenant_stats`` prober. TPE's
    ``n_initial_points`` is small so the EI path (the only fusable
    phase) engages within the budget."""
    from metaopt_tpu.coord import CoordLedgerClient, CoordServer

    per_exp = 8 * scale
    with tempfile.TemporaryDirectory() as td:
        with CoordServer(evict_dir=os.path.join(td, "evict"),
                         stale_timeout_s=5.0, sweep_interval_s=0.1,
                         max_resident=3, fuse_suggest=True,
                         fuse_interval_s=0.02, fuse_bucket_max=4) as s:
            host, port = s.address
            c0 = CoordLedgerClient(host=host, port=port)
            names = []
            for k in range(4):
                nm = f"race-fuse-{k}"
                c0.create_experiment({
                    "name": nm,
                    "space": {"x": "uniform(-5, 5)"},
                    "max_trials": per_exp, "pool_size": 2,
                    "algorithm": {"tpe": {
                        "seed": 17 + k, "n_initial_points": 2,
                        "pool_prefetch": 4,
                    }},
                })
                names.append(nm)
            stop = threading.Event()
            errors: List[BaseException] = []

            def prober() -> None:
                # fuser telemetry rollup racing live sweeps + evictions
                try:
                    c = CoordLedgerClient(host=host, port=port)
                    last = -1
                    while not stop.is_set():
                        st = c.tenant_stats()
                        fu = st.get("fuser")
                        if fu is not None:
                            if fu["ticks"] < last:
                                raise AssertionError(
                                    f"fuser tick count regressed: {fu}")
                            last = fu["ticks"]
                except BaseException as e:
                    errors.append(e)

            def worker(i: int) -> None:
                try:
                    c = CoordLedgerClient(host=host, port=port)
                    nm = names[i]
                    complete = None
                    for _ in range(per_exp * 12):
                        out = c.worker_cycle(
                            nm, f"fw{i}", pool_size=2, complete=complete)
                        complete = None
                        t = out["trial"]
                        if t is None:
                            if out["counts"]["completed"] >= per_exp:
                                return
                            continue
                        t.attach_results([{
                            "name": "objective", "type": "objective",
                            "value": (t.params["x"] - 1) ** 2,
                        }])
                        t.transition("completed")
                        complete = {"trial": t.to_dict(),
                                    "expected_status": "reserved",
                                    "expected_worker": f"fw{i}"}
                except BaseException as e:
                    errors.append(e)

            threads = [threading.Thread(target=worker, args=(i,),
                                        name=f"race-fuse-worker-{i}")
                       for i in range(4)]
            p = threading.Thread(target=prober, name="race-fuse-prober")
            p.start()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180.0)
            stop.set()
            p.join(timeout=30.0)
            if errors:
                raise errors[0]


def suite_algo(scale: int = 1) -> None:
    from metaopt_tpu.algo import CMAES
    from metaopt_tpu.ledger.trial import Trial
    from metaopt_tpu.space import build_space

    space = build_space({"x": "uniform(-5, 5)", "y": "uniform(-5, 5)"})
    algo = CMAES(space, seed=11, population_size=6,
                 suggest_prefetch_depth=2)
    stop = threading.Event()
    errors: List[BaseException] = []

    def prober() -> None:
        # the PR-4 MOTPE inversion lived exactly here: state_dict
        # racing the speculative refill thread's lock acquisitions
        try:
            while not stop.is_set():
                algo.state_dict()
                algo.suggest_ahead_telemetry()
        except BaseException as e:
            errors.append(e)

    p = threading.Thread(target=prober, name="race-prober")
    p.start()
    try:
        for gen in range(8 * scale):
            pts = algo.suggest(6)
            if not pts:
                break
            trials = []
            for pt in pts:
                t = Trial(params=pt, experiment="race-algo")
                t.lineage = space.hash_point(pt)
                t.transition("reserved")
                t.attach_results([{
                    "name": "o", "type": "objective",
                    "value": (pt["x"] - 1.0) ** 2 + (pt["y"] + 2.0) ** 2,
                }])
                t.transition("completed")
                trials.append(t)
            algo.observe(trials)
    finally:
        stop.set()
        p.join(timeout=30.0)
        algo.drain_suggest_ahead()
    if errors:
        raise errors[0]


def suite_wal(scale: int = 1) -> None:
    from metaopt_tpu.coord.wal import WriteAheadLog

    per_thread = 25 * scale
    with tempfile.TemporaryDirectory() as td:
        wal = WriteAheadLog(os.path.join(td, "race.wal")).open()
        stop = threading.Event()
        errors: List[BaseException] = []

        def appender(i: int) -> None:
            try:
                for n in range(per_thread):
                    seq = wal.append({"op": "race", "w": i, "n": n})
                    wal.sync(seq)
            except BaseException as e:
                errors.append(e)

        def compactor() -> None:
            try:
                while not stop.is_set():
                    wal.compact(upto_seq=max(0, wal.durable_seq - 20))
                    stop.wait(0.01)
            except BaseException as e:
                errors.append(e)

        threads = [threading.Thread(target=appender, args=(i,),
                                    name=f"race-wal-{i}") for i in range(4)]
        threads.append(threading.Thread(target=compactor,
                                        name="race-wal-compact"))
        for t in threads:
            t.start()
        for t in threads[:-1]:
            t.join(timeout=60.0)
        stop.set()
        threads[-1].join(timeout=30.0)
        wal.close()
        if errors:
            raise errors[0]


def suite_sim(scale: int = 1) -> None:
    from metaopt_tpu.coord import CoordLedgerClient, CoordServer
    from metaopt_tpu.ledger import Experiment
    from metaopt_tpu.ledger.trial import set_trial_clock
    from metaopt_tpu.sim.clock import VirtualClock
    from metaopt_tpu.space import build_space

    workers = 4
    budget = workers * 4 * scale
    clk = VirtualClock()
    prev = set_trial_clock(clk)
    try:
        # generous VIRTUAL stale timeout: the advancer moves ~1 virtual
        # second per 5 real ms, so expiries fire but in-flight trials
        # usually finish first — both the sweep-release and the happy
        # path get exercised
        with CoordServer(host_algorithms=True, stale_timeout_s=60.0,
                         sweep_interval_s=1.0, clock=clk) as s:
            host, port = s.address
            c0 = CoordLedgerClient(host=host, port=port)
            Experiment(
                "race-sim", c0,
                space=build_space({"x": "uniform(-5, 5)"}),
                max_trials=budget, pool_size=workers,
                algorithm={"random": {"seed": 7}},
            ).configure()
            stop = threading.Event()
            errors: List[BaseException] = []

            def advancer() -> None:
                # the simulator's event loop, compressed: advance races
                # every time()/monotonic() read on conn + sweep threads
                try:
                    while not stop.is_set():
                        clk.advance(1.0)
                        clk.advance_to(clk.monotonic())
                        stop.wait(0.005)
                except BaseException as e:
                    errors.append(e)

            def worker(i: int) -> None:
                try:
                    c = CoordLedgerClient(host=host, port=port)
                    complete = None
                    for _ in range(budget * 6):
                        out = c.worker_cycle(
                            "race-sim", f"vw{i}", pool_size=workers,
                            complete=complete)
                        complete = None
                        t = out["trial"]
                        if t is None:
                            if out["counts"]["completed"] >= budget:
                                return
                            continue
                        t.attach_results([{
                            "name": "objective", "type": "objective",
                            "value": (t.params["x"] - 1) ** 2,
                        }])
                        t.transition("completed")
                        complete = {"trial": t.to_dict(),
                                    "expected_status": "reserved",
                                    "expected_worker": f"vw{i}"}
                except BaseException as e:
                    errors.append(e)

            threads = [threading.Thread(target=worker, args=(i,),
                                        name=f"race-sim-worker-{i}")
                       for i in range(workers)]
            adv = threading.Thread(target=advancer, name="race-sim-adv")
            adv.start()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120.0)
            stop.set()
            adv.join(timeout=30.0)
            if errors:
                raise errors[0]
    finally:
        set_trial_clock(prev)


SUITES: Dict[str, Callable[[int], None]] = {
    "coord": suite_coord,
    "algo": suite_algo,
    "wal": suite_wal,
    "sim": suite_sim,
}
