"""AST-based static analysis for repo-specific invariants (``mtpu lint``).

PRs 1-3 grew three classes of invariants that nothing enforced mechanically:

* a sharded lock hierarchy in the coordinator (``_exp_locks`` under
  ``_exp_locks_guard``, WAL buffer locks, reply-cache guards) with a
  documented acquisition order,
* donated-buffer JAX kernels (``obs_buffer`` appends) and trace-time
  hygiene rules (no ambient-context reads inside ``jax.jit`` bodies —
  the ``active_mesh()`` class of bug from ADVICE round 5),
* a durability contract: every acked mutation journals to the WAL before
  its reply leaves the sender thread.

Each was hand-verified in review. This package checks them on every PR,
in the spirit of kernel lockdep (lock-order validation) and
FindBugs-style project-specific bug patterns.

Checker families and rule ids:

=========  ==============================================================
MTL001     lock-order inversion (cycle in the lock-acquisition graph)
MTL002     blocking call while holding a no-block lock
MTL003     write to a registered guarded attribute outside its guard
MTL004     call into a ``holds(X)``-annotated function without X held
MTJ001     use of a donated buffer after the donating jit call
MTJ002     ambient mutable context read inside a jit-traced function
MTJ003     host-sync call inside a ``# mtpu: hotpath`` function
MTJ004     non-static / non-hashable value bound to ``static_argnames``
MTD001     journaled op whose dispatch branch reaches no journal call
MTD002     registry drift between protocol registry and server op sets
MTD003     reply-journaled op whose handler never journals its reply
MTD004     mutating/journaled op missing from the binary-wire
           ``WIRE_OPCODES`` table, or a duplicate/reserved opcode value
=========  ==============================================================

Findings carry ``file:line`` + rule id. A checked-in baseline
(``analysis/baseline.json``) grandfathers pre-existing findings so the
CI gate (``tests/unit/test_lint_clean.py``) fails only on regressions.

Source pragmas (comments)::

    # mtpu: hotpath             -- function must never host-sync (MTJ003)
    # mtpu: holds(_lock)        -- caller holds _lock (MTL003/MTL004)
    # mtpu: lint-ok MTL003 why  -- suppress one rule on this line
"""

from metaopt_tpu.analysis.core import Finding, LintModule, load_paths
from metaopt_tpu.analysis.registry import LintConfig, default_config
from metaopt_tpu.analysis.runner import run_lint

__all__ = [
    "Finding", "LintModule", "load_paths",
    "LintConfig", "default_config", "run_lint",
]
