"""``mtpu race`` — hybrid lockset + vector-clock race detection.

The static linter (PR 4) proves lock-ORDER discipline from AST facts; it
cannot see interleaving-sensitive bugs (a write published outside its
guard is only a race if some unordered thread reads it). This module adds
the dynamic half and the static glue between them:

**Static (MTR001)** — extends the lint registry: the *shared-attribute
set* is every attribute with a ``holds()``/``guarded_attrs`` declaration
plus every attribute written from ≥ 2 thread entry points in the
lock-order call graph (thread entry points = ``Thread(target=...)`` /
``self._spawn(...)`` targets found in the AST, plus declared extras).
A shared-written attribute with NO guard declaration is a finding: the
declaration is what wires the attribute into both MTL003 and the dynamic
instrumentation below, so "undeclared shared write" means "invisible to
every checker".

**Dynamic (MTR101/MTR102)** — Eraser-style lockset refined with
FastTrack-style vector-clock epochs (Savage et al. 1997; Flanagan &
Freund 2009). Inside :func:`instrument`, ``threading.Lock/RLock/
Condition``, ``threading.Thread.start/join``, ``threading.Event`` and
``queue.Queue`` are wrapped so acquire/release/fork/join/wait/notify/
put/get maintain per-thread vector clocks, and every guard-declared
class gets ``__setattr__``/``__getattribute__`` hooks. Each access to a
monitored attribute records an epoch ``(tid, clock)``, the thread's held
lockset and a cheap stack; two accesses to the same attribute race
(**MTR101**) when they come from different threads, at least one is a
write, their locksets are disjoint AND neither epoch happens-before the
other's clock. Nested acquisitions also feed a runtime lock-order graph
whose cycles are **MTR102** (the dynamic mirror of MTL001 — it sees
locks the AST cannot name, e.g. per-experiment RLock families, which
collapse to one node by creation site exactly like the EXP pseudo-node).
Both report with the stacks of BOTH sides.

Wrapped primitives keep working after :func:`instrument` exits (event
emission is gated on the runtime's ``active`` flag), so objects built
under instrumentation survive it.

Rule table:

========  ============================================================
MTR001    shared-written attribute lacks a guard declaration (static)
MTR101    data race: unordered accesses with disjoint locksets (dynamic)
MTR102    lock-order inversion observed at runtime (dynamic)
========  ============================================================
"""

from __future__ import annotations

import ast
import itertools
import os
import queue as _queue_mod
import sys
import threading
from contextlib import contextmanager
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Set, Tuple

from metaopt_tpu.analysis.core import Finding, LintModule, dotted_name
from metaopt_tpu.analysis.locks import LockChecker, _looks_like_lock
from metaopt_tpu.analysis.registry import LintConfig, RaceConfig

# The runtime's own synchronization must bypass the wrappers (a wrapped
# lock inside the event handler would recurse), so the real primitives
# are captured at import time, before any instrument() patches land.
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition
_REAL_THREAD_START = threading.Thread.start
_REAL_THREAD_JOIN = threading.Thread.join
_REAL_EVENT_SET = threading.Event.set
_REAL_EVENT_WAIT = threading.Event.wait
_REAL_QUEUE_PUT = _queue_mod.Queue.put
_REAL_QUEUE_GET = _queue_mod.Queue.get

_STACK_DEPTH = 14
_PKG_FILE_MARK = os.sep + "metaopt_tpu" + os.sep
_SELF_FILE = os.path.abspath(__file__)


def _fast_stack(skip: int = 2) -> Tuple[Tuple[str, int, str], ...]:
    """A cheap stack: (abspath, lineno, funcname) per frame, innermost
    first, without touching source files (formatted lazily at report
    time). ~1-2us vs ~50us for traceback.extract_stack."""
    out: List[Tuple[str, int, str]] = []
    try:
        f = sys._getframe(skip)
    except ValueError:  # pragma: no cover - shallow stack
        return ()
    while f is not None and len(out) < _STACK_DEPTH:
        code = f.f_code
        if code.co_filename != _SELF_FILE:
            out.append((code.co_filename, f.f_lineno, code.co_name))
        f = f.f_back
    return tuple(out)


def _render_stack(stack: Tuple[Tuple[str, int, str], ...],
                  indent: str = "      ") -> str:
    import linecache

    lines = []
    for fname, lineno, func in stack:
        short = fname
        mark = short.rfind(_PKG_FILE_MARK)
        if mark != -1:
            short = short[mark + 1:]
        else:
            short = os.path.basename(short)
        src = linecache.getline(fname, lineno).strip()
        lines.append(f"{indent}{short}:{lineno} in {func}"
                     + (f"  `{src}`" if src else ""))
    return "\n".join(lines)


def _primary_frame(stack: Tuple[Tuple[str, int, str], ...]
                   ) -> Tuple[str, int, str]:
    """Innermost frame inside the scanned package (falls back to the
    innermost frame) — the finding's file:line anchor."""
    for fname, lineno, func in stack:
        if _PKG_FILE_MARK in fname:
            return fname, lineno, func
    return stack[0] if stack else ("<unknown>", 0, "<unknown>")


# ---------------------------------------------------------------------------
# vector clocks
# ---------------------------------------------------------------------------


def _merge(dst: Dict[int, int], src: Dict[int, int]) -> None:
    for tid, c in src.items():
        if dst.get(tid, 0) < c:
            dst[tid] = c


class _SyncMeta:
    """Per-primitive state: identity, a human label, and the vector clock
    last published into it (release / put / set)."""

    __slots__ = ("uid", "label", "vc", "site")

    def __init__(self, uid: int, label: str, site: str) -> None:
        self.uid = uid
        self.label = label
        self.vc: Dict[int, int] = {}
        self.site = site


class _ThreadState:
    __slots__ = ("tid", "ident", "name", "vc", "held")

    def __init__(self, tid: int, ident: int) -> None:
        self.tid = tid
        self.ident = ident
        self.name: Optional[str] = None  # resolved lazily (see _state)
        self.vc: Dict[int, int] = {tid: 1}
        #: _SyncMeta -> recursion count (lockset = keys with count > 0)
        self.held: Dict[_SyncMeta, int] = {}

    @property
    def label(self) -> str:
        return self.name or f"thread-{self.ident}"


class _Access:
    __slots__ = ("tid", "clock", "lockset", "stack", "thread", "write")

    def __init__(self, st: _ThreadState, lockset: FrozenSet[int],
                 stack, write: bool) -> None:
        self.tid = st.tid
        self.clock = st.vc[st.tid]
        self.lockset = lockset
        self.stack = stack
        self.thread = st.label
        self.write = write


class _AttrState:
    """FastTrack-shaped per-(object, attr) history: the last write plus
    the most recent read per thread since that write."""

    __slots__ = ("write", "reads")

    def __init__(self) -> None:
        self.write: Optional[_Access] = None
        self.reads: Dict[int, _Access] = {}


# ---------------------------------------------------------------------------
# the runtime
# ---------------------------------------------------------------------------


class RaceRuntime:
    """Event sink for the wrapped primitives and attribute hooks.

    One instance per :func:`instrument` context. All shared structures
    are guarded by one real (unwrapped) lock; the event volume of the
    designated suites is small enough that a single lock beats the
    complexity of sharding the detector itself.
    """

    def __init__(self, monitor: Dict[type, FrozenSet[str]],
                 root: Optional[str] = None) -> None:
        #: class -> attrs to check (already MRO-merged by the caller)
        self.monitor = monitor
        self.root = os.path.abspath(root or os.getcwd())
        self.active = False
        self._big = _REAL_LOCK()
        self._local = threading.local()
        self._uids = itertools.count(1)
        self._tids = itertools.count(1)
        #: thread ident -> state (ident reuse after a join is tolerated:
        #: the dead thread's clock was already merged by on_join)
        self._states: Dict[int, _ThreadState] = {}
        #: (id(obj), clsname, attr) -> history; obj kept alive in _pins so
        #: a recycled id can never alias two objects' histories
        self._attrs: Dict[Tuple[int, str, str], _AttrState] = {}
        self._pins: Dict[int, Any] = {}
        #: (label_a, label_b) -> (stack, thread_name) of first observation
        self._edges: Dict[Tuple[str, str], Tuple[Any, str]] = {}
        #: report key -> Finding (dedup across the run)
        self._reports: Dict[Tuple, Finding] = {}
        self.events = 0

    # -- thread state ------------------------------------------------------
    def _state(self) -> _ThreadState:
        # NEVER threading.current_thread() here: a just-born thread emits
        # its first event (``_started.set()`` in _bootstrap_inner) BEFORE
        # registering in threading._active, and current_thread() would
        # mint a _DummyThread whose __init__ itself sets a wrapped Event
        # — unbounded recursion. get_ident() allocates nothing.
        st = getattr(self._local, "st", None)
        if st is None:
            ident = threading.get_ident()
            with self._big:
                st = _ThreadState(next(self._tids), ident)
                self._states[ident] = st
            self._local.st = st
        if st.name is None:
            t = threading._active.get(st.ident)  # plain dict read
            if t is not None:
                st.name = t.name
                fork_vc = getattr(t, "_mtpu_race_fork_vc", None)
                if fork_vc:
                    _merge(st.vc, fork_vc)
                    st.vc[st.tid] = st.vc.get(st.tid, 0) + 1
        return st

    def _lockset(self, st: _ThreadState) -> FrozenSet[int]:
        return frozenset(m.uid for m, n in st.held.items() if n > 0)

    # -- sync events -------------------------------------------------------
    def new_meta(self, kind: str, skip: int = 2) -> _SyncMeta:
        """Label by creation site (file:line) — every lock minted at the
        same line is one graph node, which is exactly the EXP pseudo-node
        doctrine for per-experiment RLock families. The label is refined
        to ``Class.attr`` when the object is later assigned onto a
        monitored class (see the setattr hook)."""
        try:
            f = sys._getframe(skip)
            while f is not None and f.f_code.co_filename == _SELF_FILE:
                f = f.f_back
            site = (f"{os.path.basename(f.f_code.co_filename)}:"
                    f"{f.f_lineno}" if f is not None else "?")
        except ValueError:  # pragma: no cover
            site = "?"
        uid = next(self._uids)
        return _SyncMeta(uid, f"{kind}@{site}", site)

    def on_acquire(self, meta: _SyncMeta, stack_skip: int = 3) -> None:
        if not self.active:
            return
        st = self._state()
        with self._big:
            self.events += 1
            prev = st.held.get(meta, 0)
            if prev:  # re-entrant: no ordering, no new HB information
                st.held[meta] = prev + 1
                return
            _merge(st.vc, meta.vc)
            for held, n in st.held.items():
                if n > 0 and held.label != meta.label:
                    key = (held.label, meta.label)
                    if key not in self._edges:
                        self._edges[key] = (_fast_stack(stack_skip),
                                            st.label)
            st.held[meta] = 1

    def on_release(self, meta: _SyncMeta) -> None:
        if not self.active:
            return
        st = self._state()
        with self._big:
            self.events += 1
            n = st.held.get(meta, 0)
            if n > 1:
                st.held[meta] = n - 1
                return
            st.held.pop(meta, None)
            _merge(meta.vc, st.vc)
            st.vc[st.tid] += 1

    def on_publish(self, meta: _SyncMeta) -> None:
        """Event.set / queue.put: one-way clock transfer to the object."""
        if not self.active:
            return
        st = self._state()
        with self._big:
            self.events += 1
            _merge(meta.vc, st.vc)
            st.vc[st.tid] += 1

    def on_receive(self, meta: _SyncMeta) -> None:
        """Successful Event.wait / queue.get: merge the published clock."""
        if not self.active:
            return
        st = self._state()
        with self._big:
            self.events += 1
            _merge(st.vc, meta.vc)

    def on_wait_release(self, meta: _SyncMeta) -> int:
        """Condition.wait entry: the wait fully releases the cv lock
        (RLocks release every recursion level); returns the count to
        restore on wake."""
        if not self.active:
            return 0
        st = self._state()
        with self._big:
            self.events += 1
            n = st.held.pop(meta, 0)
            if n:
                _merge(meta.vc, st.vc)
                st.vc[st.tid] += 1
            return n

    def on_wait_wake(self, meta: _SyncMeta, count: int) -> None:
        if not self.active:
            return
        st = self._state()
        with self._big:
            self.events += 1
            _merge(st.vc, meta.vc)
            if count:
                st.held[meta] = st.held.get(meta, 0) + count

    def on_fork(self, child: threading.Thread) -> None:
        if not self.active:
            return
        st = self._state()
        with self._big:
            self.events += 1
            child._mtpu_race_fork_vc = dict(st.vc)  # type: ignore[attr-defined]
            st.vc[st.tid] += 1

    def on_join(self, child: threading.Thread) -> None:
        if not self.active or child.is_alive():
            return
        ident = child.ident
        st = self._state()
        with self._big:
            self.events += 1
            cst = self._states.get(ident) if ident is not None else None
            if cst is not None:
                _merge(st.vc, cst.vc)

    # -- attribute accesses ------------------------------------------------
    def on_access(self, obj: Any, clsname: str, attr: str,
                  write: bool) -> None:
        if not self.active:
            return
        if getattr(self._local, "in_hook", False):
            return  # the handler itself must never re-enter
        self._local.in_hook = True
        try:
            st = self._state()
            acc = _Access(st, self._lockset(st), _fast_stack(3), write)
            with self._big:
                self.events += 1
                key = (id(obj), clsname, attr)
                hist = self._attrs.get(key)
                if hist is None:
                    hist = self._attrs[key] = _AttrState()
                    self._pins.setdefault(id(obj), obj)
                if write:
                    if hist.write is not None:
                        self._check_pair(clsname, attr, hist.write, acc,
                                         st.vc)
                    for r in hist.reads.values():
                        self._check_pair(clsname, attr, r, acc, st.vc)
                    hist.write = acc
                    hist.reads.clear()
                else:
                    if hist.write is not None:
                        self._check_pair(clsname, attr, hist.write, acc,
                                         st.vc)
                    hist.reads[acc.tid] = acc
        finally:
            self._local.in_hook = False

    def _check_pair(self, clsname: str, attr: str, prev: _Access,
                    cur: _Access, cur_vc: Dict[int, int]) -> None:
        """Report when prev/cur conflict: different threads, at least one
        write, disjoint locksets, and prev NOT happens-before cur (the
        FastTrack epoch test: cur's clock component for prev's thread is
        older than prev's epoch)."""
        if prev.tid == cur.tid:
            return
        if not (prev.write or cur.write):
            return
        if prev.lockset & cur.lockset:
            return
        if cur_vc.get(prev.tid, 0) >= prev.clock:
            return  # ordered by a tracked sync edge
        sym_prev = _primary_frame(prev.stack)[2]
        sym_cur = _primary_frame(cur.stack)[2]
        key = ("MTR101", clsname, attr, frozenset((sym_prev, sym_cur)))
        if key in self._reports:
            return
        fname, lineno, _ = _primary_frame(cur.stack)
        kind = "write/write" if (prev.write and cur.write) else "read/write"
        msg = (
            f"data race on {clsname}.{attr} ({kind}): unordered accesses "
            f"with disjoint locksets\n"
            f"    {'write' if prev.write else 'read'} by thread "
            f"{prev.thread} [{self._fmt_lockset(prev.lockset)}]:\n"
            f"{_render_stack(prev.stack)}\n"
            f"    {'write' if cur.write else 'read'} by thread "
            f"{cur.thread} [{self._fmt_lockset(cur.lockset)}]:\n"
            f"{_render_stack(cur.stack)}"
        )
        self._reports[key] = Finding(
            "MTR101", self._rel(fname), lineno, msg,
            symbol=f"{clsname}.{attr}",
            detail="|".join(sorted((sym_prev, sym_cur))))

    def _fmt_lockset(self, lockset: FrozenSet[int]) -> str:
        if not lockset:
            return "no locks held"
        labels = sorted(self._label_of.get(uid, f"#{uid}")
                        for uid in lockset)
        return "holding " + ",".join(labels)

    #: uid -> current label; maintained by the labeling hook
    @property
    def _label_of(self) -> Dict[int, str]:
        d = getattr(self, "_label_cache", None)
        if d is None:
            d = self._label_cache = {}
        return d

    def note_label(self, meta: _SyncMeta, label: str) -> None:
        """Refine a creation-site label to ``Class.attr`` (first naming
        wins: a lock shared across attrs keeps its original name)."""
        with self._big:
            if "@" in meta.label:
                meta.label = label
            self._label_of[meta.uid] = meta.label

    def seen_label(self, meta: _SyncMeta) -> None:
        with self._big:
            self._label_of.setdefault(meta.uid, meta.label)

    # -- findings ----------------------------------------------------------
    def _rel(self, fname: str) -> str:
        try:
            rel = os.path.relpath(os.path.abspath(fname), self.root)
        except ValueError:  # pragma: no cover - windows drive mismatch
            return fname
        return rel if not rel.startswith("..") else fname

    def findings(self) -> List[Finding]:
        """Race reports plus lock-order cycles from the dynamic graph."""
        out = list(self._reports.values())
        adj: Dict[str, Set[str]] = {}
        for a, b in self._edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        for (a, b), (stack, tname) in sorted(self._edges.items()):
            # edge a->b is on a cycle iff a is reachable from b
            stack_, seen = [b], {b}
            on_cycle = False
            while stack_:
                n = stack_.pop()
                if n == a:
                    on_cycle = True
                    break
                for m in adj.get(n, ()):
                    if m not in seen:
                        seen.add(m)
                        stack_.append(m)
            if not on_cycle:
                continue
            fname, lineno, sym = _primary_frame(stack)
            msg = (f"lock-order inversion observed at runtime: {a} -> {b} "
                   f"completes a cycle\n    {a} -> {b} by thread {tname}:\n"
                   f"{_render_stack(stack)}")
            rev = self._edges.get((b, a))
            if rev is not None:
                msg += (f"\n    {b} -> {a} by thread {rev[1]}:\n"
                        f"{_render_stack(rev[0])}")
            key = ("MTR102", a, b)
            if key not in self._reports:
                self._reports[key] = Finding(
                    "MTR102", self._rel(fname), lineno, msg, symbol=sym,
                    detail=f"{a}->{b}")
                out.append(self._reports[key])
        out.sort(key=lambda f: (f.file, f.line, f.rule, f.detail))
        return out


# ---------------------------------------------------------------------------
# wrapped primitives
# ---------------------------------------------------------------------------


class _WrappedLock:
    """Instrumented Lock/RLock. Delegates to the real primitive; event
    emission is gated on the runtime's ``active`` flag so instances
    outlive their instrument() context safely."""

    def __init__(self, rt: RaceRuntime, real: Any, meta: _SyncMeta) -> None:
        self._rt = rt
        self._real = real
        self._meta = meta
        rt.seen_label(meta)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._real.acquire(blocking, timeout)
        if got:
            self._rt.on_acquire(self._meta)
        return got

    def release(self) -> None:
        self._rt.on_release(self._meta)
        self._real.release()

    def __enter__(self) -> "_WrappedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self._real.locked()

    def __getattr__(self, name: str) -> Any:  # _at_fork_reinit etc.
        return getattr(self._real, name)


class _WrappedCondition:
    """Instrumented Condition. Built either standalone (fresh inner
    RLock) or over a :class:`_WrappedLock`, in which case the condition
    IS that lock's node (same meta) — mirroring how ``queue.Queue``
    shares one mutex across its three conditions."""

    def __init__(self, rt: RaceRuntime, lock: Any = None,
                 meta: Optional[_SyncMeta] = None) -> None:
        self._rt = rt
        if isinstance(lock, _WrappedLock):
            self._meta = lock._meta
            self._real = _REAL_CONDITION(lock._real)
        elif lock is not None:  # a real, uninstrumented lock
            self._meta = meta or rt.new_meta("Condition", skip=3)
            self._real = _REAL_CONDITION(lock)
        else:
            self._meta = meta or rt.new_meta("Condition", skip=3)
            self._real = _REAL_CONDITION(_REAL_RLOCK())
        rt.seen_label(self._meta)

    def acquire(self, *args: Any) -> bool:
        got = self._real.acquire(*args)
        if got:
            self._rt.on_acquire(self._meta)
        return got

    def release(self) -> None:
        self._rt.on_release(self._meta)
        self._real.release()

    def __enter__(self) -> "_WrappedCondition":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        n = self._rt.on_wait_release(self._meta)
        try:
            return self._real.wait(timeout)
        finally:
            self._rt.on_wait_wake(self._meta, n)

    def wait_for(self, predicate: Callable[[], bool],
                 timeout: Optional[float] = None) -> bool:
        # re-implemented over our wait() so every wake re-merges clocks
        import time as _time

        end = None if timeout is None else _time.monotonic() + timeout
        result = predicate()
        while not result:
            rem = None if end is None else end - _time.monotonic()
            if rem is not None and rem <= 0:
                break
            self.wait(rem)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._real.notify(n)

    def notify_all(self) -> None:
        self._real.notify_all()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._real, name)


# ---------------------------------------------------------------------------
# instrumentation: patches + class hooks
# ---------------------------------------------------------------------------

_LOCKISH = (_WrappedLock, _WrappedCondition)


def _install_class_hooks(rt: RaceRuntime) -> List[Tuple[type, str, Any, bool]]:
    """Hook ``__setattr__``/``__getattribute__`` on every monitored class.

    The setattr hook does double duty: it reports writes to monitored
    attrs AND names any lock/condition assigned onto the class
    (``self._buf_lock = Lock()`` -> node "WriteAheadLog._buf_lock").
    Returns an undo list of (cls, name, original, was_inherited).
    """
    undo: List[Tuple[type, str, Any, bool]] = []
    for cls, attrs in rt.monitor.items():
        clsname = cls.__name__
        orig_set = cls.__setattr__
        orig_get = cls.__getattribute__
        attrset = frozenset(attrs)

        def make_set(orig_set: Any, clsname: str, attrset: FrozenSet[str]):
            def hooked_setattr(self: Any, name: str, value: Any) -> None:
                if isinstance(value, _LOCKISH):
                    rt.note_label(value._meta, f"{clsname}.{name}")
                if name in attrset:
                    rt.on_access(self, clsname, name, write=True)
                orig_set(self, name, value)
            return hooked_setattr

        def make_get(orig_get: Any, clsname: str, attrset: FrozenSet[str]):
            def hooked_getattribute(self: Any, name: str) -> Any:
                if name in attrset:
                    rt.on_access(self, clsname, name, write=False)
                return orig_get(self, name)
            return hooked_getattribute

        undo.append((cls, "__setattr__", cls.__dict__.get("__setattr__"),
                     "__setattr__" not in cls.__dict__))
        undo.append((cls, "__getattribute__",
                     cls.__dict__.get("__getattribute__"),
                     "__getattribute__" not in cls.__dict__))
        cls.__setattr__ = make_set(orig_set, clsname, attrset)  # type: ignore[assignment]
        cls.__getattribute__ = make_get(orig_get, clsname, attrset)  # type: ignore[assignment]
    return undo


def _uninstall_class_hooks(undo: List[Tuple[type, str, Any, bool]]) -> None:
    for cls, name, orig, was_inherited in undo:
        if was_inherited:
            try:
                delattr(cls, name)
            except AttributeError:  # pragma: no cover
                pass
        else:
            setattr(cls, name, orig)


@contextmanager
def instrument(rt: RaceRuntime):
    """Patch the synchronization primitives and install attribute hooks
    for the duration of the block. Not re-entrant; one active runtime
    per process."""

    def lock_factory() -> _WrappedLock:
        return _WrappedLock(rt, _REAL_LOCK(), rt.new_meta("Lock"))

    def rlock_factory() -> _WrappedLock:
        return _WrappedLock(rt, _REAL_RLOCK(), rt.new_meta("RLock"))

    def condition_factory(lock: Any = None) -> _WrappedCondition:
        return _WrappedCondition(rt, lock)

    def thread_start(self: threading.Thread) -> None:
        rt.on_fork(self)
        return _REAL_THREAD_START(self)

    def thread_join(self: threading.Thread,
                    timeout: Optional[float] = None) -> None:
        _REAL_THREAD_JOIN(self, timeout)
        rt.on_join(self)

    def _obj_meta(obj: Any, kind: str) -> _SyncMeta:
        meta = obj.__dict__.get("_mtpu_race_meta")
        if meta is None:
            meta = rt.new_meta(kind, skip=3)
            obj.__dict__["_mtpu_race_meta"] = meta
        return meta

    def event_set(self: threading.Event) -> None:
        rt.on_publish(_obj_meta(self, "Event"))
        return _REAL_EVENT_SET(self)

    def event_wait(self: threading.Event,
                   timeout: Optional[float] = None) -> bool:
        got = _REAL_EVENT_WAIT(self, timeout)
        if got:
            rt.on_receive(_obj_meta(self, "Event"))
        return got

    def queue_put(self: Any, item: Any, block: bool = True,
                  timeout: Optional[float] = None) -> None:
        # publish BEFORE the item becomes visible to a getter
        rt.on_publish(_obj_meta(self, "Queue"))
        return _REAL_QUEUE_PUT(self, item, block, timeout)

    def queue_get(self: Any, block: bool = True,
                  timeout: Optional[float] = None) -> Any:
        item = _REAL_QUEUE_GET(self, block, timeout)
        rt.on_receive(_obj_meta(self, "Queue"))
        return item

    undo_hooks = _install_class_hooks(rt)
    threading.Lock = lock_factory  # type: ignore[misc]
    threading.RLock = rlock_factory  # type: ignore[misc]
    threading.Condition = condition_factory  # type: ignore[misc]
    threading.Thread.start = thread_start  # type: ignore[method-assign]
    threading.Thread.join = thread_join  # type: ignore[method-assign]
    threading.Event.set = event_set  # type: ignore[method-assign]
    threading.Event.wait = event_wait  # type: ignore[method-assign]
    _queue_mod.Queue.put = queue_put  # type: ignore[method-assign]
    _queue_mod.Queue.get = queue_get  # type: ignore[method-assign]
    rt.active = True
    try:
        yield rt
    finally:
        rt.active = False
        threading.Lock = _REAL_LOCK  # type: ignore[misc]
        threading.RLock = _REAL_RLOCK  # type: ignore[misc]
        threading.Condition = _REAL_CONDITION  # type: ignore[misc]
        threading.Thread.start = _REAL_THREAD_START  # type: ignore[method-assign]
        threading.Thread.join = _REAL_THREAD_JOIN  # type: ignore[method-assign]
        threading.Event.set = _REAL_EVENT_SET  # type: ignore[method-assign]
        threading.Event.wait = _REAL_EVENT_WAIT  # type: ignore[method-assign]
        _queue_mod.Queue.put = _REAL_QUEUE_PUT  # type: ignore[method-assign]
        _queue_mod.Queue.get = _REAL_QUEUE_GET  # type: ignore[method-assign]
        _uninstall_class_hooks(undo_hooks)


def monitored_classes(cfg: LintConfig, race_cfg: RaceConfig
                      ) -> Dict[type, FrozenSet[str]]:
    """Resolve the monitor map: import each declared class and merge the
    guarded-attr declarations down its MRO (a mixin's declarations apply
    to every concrete adopter), minus the unlocked-read/exempt lists."""
    out: Dict[type, FrozenSet[str]] = {}
    for clsname, modpath in sorted(race_cfg.monitor_modules.items()):
        import importlib

        cls = getattr(importlib.import_module(modpath), clsname)
        attrs: Set[str] = set()
        for k in cls.__mro__:
            attrs |= set(cfg.guarded_attrs.get(k.__name__, ()))
            attrs |= race_cfg.extra_monitored.get(k.__name__, set())
        attrs -= {a for c, a in race_cfg.race_exempt
                  if c in {k.__name__ for k in cls.__mro__}}
        if attrs:
            out[cls] = frozenset(attrs)
    return out


# ---------------------------------------------------------------------------
# static half: the shared-attribute set + MTR001
# ---------------------------------------------------------------------------


def _thread_targets(checker: LockChecker) -> Dict[str, Any]:
    """Thread entry points found in the AST: ``Thread(target=X)`` (any
    receiver spelling) and ``self._spawn(X, ...)``. Returns
    {root_qualname: _FuncInfo}."""
    roots: Dict[str, Any] = {}

    def add(info: Any) -> None:
        if info is not None:
            roots.setdefault(info.qualname, info)

    for mod in checker.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            target_expr = None
            if dn and dn.split(".")[-1] == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        target_expr = kw.value
            elif dn and dn.split(".")[-1] == "_spawn" and node.args:
                target_expr = node.args[0]
            if target_expr is None:
                continue
            tdn = dotted_name(target_expr)
            if not tdn:
                continue
            parts = tdn.split(".")
            cls = mod.enclosing_class(node)
            clsname = cls.name if cls is not None else None
            if parts[0] == "self" and len(parts) == 2 and clsname:
                add(checker.by_class.get((clsname, parts[1])))
            elif len(parts) == 1:
                # nested worker fn (``target=work``): same module, and the
                # spawning function's qualname is a prefix of the worker's
                outer = mod.qualname(node)
                for info in checker.by_name.get(parts[0], ()):
                    if info.mod is mod and info.qualname.startswith(outer):
                        add(info)
    return roots


def _threadlocal_attrs(checker: LockChecker, cfg: LintConfig
                       ) -> Set[Tuple[str, str]]:
    """(class, attr) pairs assigned ``threading.local()`` in an init
    method — per-thread by construction, never shared."""
    out: Set[Tuple[str, str]] = set()
    for info in checker.funcs:
        if not info.cls or info.node.name not in cfg.init_methods:
            continue
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Assign):
                continue
            dn = dotted_name(node.value.func) if isinstance(
                node.value, ast.Call) else None
            if not dn or dn.split(".")[-1] != "local":
                continue
            for tgt in node.targets:
                tdn = dotted_name(tgt)
                if tdn and tdn.startswith("self.") and tdn.count(".") == 1:
                    out.add((info.cls, tdn.split(".")[1]))
    return out


def compute_shared_attrs(checker: LockChecker, cfg: LintConfig,
                         race_cfg: RaceConfig
                         ) -> Dict[Tuple[str, str],
                                   Tuple[Set[str], FrozenSet[str]]]:
    """(class, attr) -> (entry-point qualnames that can write it, the
    intersection of locksets over every such write path).

    The BFS from each thread entry point carries the locks held along
    the path (a call made under ``with self._exp_lock(n):`` protects the
    whole subtree, including the sharded-ledger proxy's implicit EXP),
    so a write counts as *unprotected sharing* only when two entry
    points reach it and no single lock covers all the paths — a static
    Eraser lockset, not mere reachability.
    """
    roots = dict(_thread_targets(checker))
    for qn in race_cfg.entry_points:
        for info in checker.funcs:
            if info.qualname == qn:
                roots.setdefault(qn, info)
    #: (cls, attr) -> {root: intersection of per-write locksets}
    shared: Dict[Tuple[str, str], Dict[str, FrozenSet[str]]] = {}
    for root_qn, root in sorted(roots.items()):
        # visited[id(info)] = held-sets already walked; a superset of a
        # walked set can only see MORE protection, so it is skipped
        visited: Dict[int, List[FrozenSet[str]]] = {}
        stack: List[Tuple[Any, FrozenSet[str]]] = [
            (root, frozenset(root.holds))]
        while stack:
            info, held = stack.pop()
            done = visited.setdefault(id(info), [])
            if any(h <= held for h in done):
                continue
            done.append(held)
            for ev in info.events:
                here = held | ev.held
                if ev.kind == "write" and info.cls:
                    if info.node.name in cfg.init_methods:
                        continue
                    per_root = shared.setdefault((info.cls, ev.name), {})
                    prev = per_root.get(root_qn)
                    per_root[root_qn] = (here if prev is None
                                         else prev & here)
                elif ev.kind == "call":
                    callees, extra = checker._resolve(ev.name, info)
                    for c in callees:
                        if c is not info:
                            stack.append(
                                (c, here | extra | frozenset(c.holds)))
    out: Dict[Tuple[str, str], Tuple[Set[str], FrozenSet[str]]] = {}
    for key, per_root in shared.items():
        if len(per_root) < 2:
            continue
        common = frozenset.intersection(*per_root.values())
        if not common:
            out[key] = (set(per_root), common)
    return out


def check_shared(modules: List[LintModule], cfg: LintConfig,
                 race_cfg: RaceConfig,
                 checker: Optional[LockChecker] = None) -> List[Finding]:
    """MTR001: shared-written attribute without a guard declaration.

    Scope is the *declared concurrency surface* — classes that own locks
    (``lock_attrs``), already guard attrs (``guarded_attrs``), or are
    dynamically monitored (``monitor_modules``). A class in that set has
    announced itself thread-shared; its shared-written but undeclared
    attrs are the blind spots of both MTL003 and the instrumentation.
    Classes outside it are left to the dynamic detector (the bare-name
    static call graph is too coarse to accuse them soundly).
    """
    checker = checker or LockChecker(modules, cfg)
    surface = (set(cfg.lock_attrs) | set(cfg.guarded_attrs)
               | set(race_cfg.monitor_modules))
    tlocal = _threadlocal_attrs(checker, cfg)
    shared = compute_shared_attrs(checker, cfg, race_cfg)
    out: List[Finding] = []
    for (clsname, attr), (roots, _) in sorted(shared.items()):
        if clsname not in surface:
            continue
        if attr in cfg.guarded_attrs.get(clsname, ()):
            continue  # declared: MTL003 + the dynamic hooks cover it
        if (clsname, attr) in race_cfg.race_exempt:
            continue
        if (clsname, attr) in tlocal:
            continue  # threading.local: per-thread by construction
        if attr in cfg.lock_attrs.get(clsname, set()) or _looks_like_lock(
                attr):
            continue  # the lock IS the synchronization
        # anchor at the first write site in qualname order
        site = None
        for info in checker.funcs:
            if info.cls != clsname:
                continue
            if info.node.name in cfg.init_methods:
                continue
            for ev in info.events:
                if ev.kind == "write" and ev.name == attr:
                    cand = (info.mod.relpath, ev.line, info.qualname)
                    if site is None or cand < site:
                        site = cand
        if site is None:
            continue
        relpath, line, sym = site
        out.append(Finding(
            "MTR001", relpath, line,
            f"{clsname}.{attr} is written from {len(roots)} thread entry "
            f"points ({', '.join(sorted(roots))}) with no common lock and "
            f"no guard declaration (guarded_attrs/holds) — invisible to "
            f"MTL003 and to `mtpu race` instrumentation",
            symbol=sym, detail=attr))
    return [f for f in out if not _suppressed(modules, f)]


def _suppressed(modules: List[LintModule], f: Finding) -> bool:
    for mod in modules:
        if mod.relpath == f.file:
            return mod.suppressed(f.line, f.rule)
    return False
