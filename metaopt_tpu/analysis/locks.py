"""Lock-discipline checkers (MTL001-MTL004) — lockdep in miniature.

The pass walks every function with a stack of held lock nodes (``with
self._foo:`` pushes "Class._foo"; ``with self._exp_lock(n):`` pushes the
EXP pseudo-node), records three event kinds in context — lock
acquisitions, calls, attribute writes — then:

* builds the global lock-acquisition graph (including one level of
  cross-function propagation through a name-based call graph iterated to
  a fixpoint) and reports every edge on a cycle as **MTL001**;
* reports blocking calls (fsync / socket / sleep / subprocess), direct
  or via a callee, made while holding a lock from the configured
  no-block set as **MTL002**;
* reports writes to registered guarded attributes outside their guard
  as **MTL003** (``__init__`` and ``holds(<guard>)``-annotated functions
  excepted);
* reports calls into ``holds(X)``-annotated functions from a context not
  holding X as **MTL004**.

Call resolution is deliberately conservative: ``self.m()`` resolves only
within the class, ``super().m()`` walks the scanned base-class chain
(and resolves nowhere else — bare-name fan-out across sibling classes
manufactured phantom cycles), known receiver *roles* (``self.ledger`` ->
the sharded proxy, ``self._wal`` -> the WAL) resolve through the config,
common container method names (``append``, ``get``, ...) never resolve,
and anything else resolves by bare method name across the scanned set.

Inherited locks share one graph node: ``self._kernel_lock`` acquired in a
subclass canonicalizes to the class whose ``__init__`` creates the lock
(``MOTPE._kernel_lock`` -> ``TPE._kernel_lock``), so a subclass method
holding an inherited lock while ``super()`` re-acquires sibling locks
participates in the same cycle check as the base class's own methods.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from metaopt_tpu.analysis.core import Finding, LintModule, dotted_name
from metaopt_tpu.analysis.registry import EXP_LOCK, LintConfig

_MUTATING_METHODS = {
    "append", "add", "pop", "popitem", "update", "setdefault", "clear",
    "extend", "remove", "discard", "insert",
}


@dataclass
class _Event:
    kind: str                  # "acquire" | "call" | "write"
    name: str                  # lock node / dotted callee / attr name
    line: int
    held: FrozenSet[str]


@dataclass
class _FuncInfo:
    mod: LintModule
    node: ast.FunctionDef
    cls: Optional[str]
    qualname: str
    holds: FrozenSet[str]
    events: List[_Event] = field(default_factory=list)
    # transitive summaries (fixpoint)
    locks: Set[str] = field(default_factory=set)
    blocking: Set[Tuple[str, str]] = field(default_factory=set)


def _norm_lock(name: str, cls: Optional[str]) -> str:
    """Bare pragma/config lock names -> graph nodes ("_lock" in class
    MemoryLedger -> "MemoryLedger._lock"; "EXP" stays)."""
    if name == EXP_LOCK or "." in name:
        return name
    return f"{cls}.{name}" if cls else name


def _looks_like_lock(attr: str) -> bool:
    return (attr.endswith("lock") or attr.endswith("guard")
            or attr in ("_cv", "_mutex") or "mutex" in attr)


class _FuncWalker(ast.NodeVisitor):
    """Collects acquire/call/write events with the held-lock stack."""

    def __init__(self, info: _FuncInfo, cfg: LintConfig,
                 owner) -> None:
        self.info = info
        self.cfg = cfg
        self.owner = owner  # (cls, attr) -> defining class for lock nodes
        self.held: Tuple[str, ...] = tuple(sorted(info.holds))

    def _emit(self, kind: str, name: str, line: int) -> None:
        self.info.events.append(
            _Event(kind, name, line, frozenset(self.held)))

    def _lock_of(self, expr: ast.AST) -> Optional[Tuple[str, List[str]]]:
        """(lock_node, locks_taken_inside) for a with-item, else None."""
        cls = self.info.cls
        if isinstance(expr, ast.Call):
            dn = dotted_name(expr.func)
            if dn:
                fac = self.cfg.lock_factories.get(dn.split(".")[-1])
                if fac:
                    return fac[0], list(fac[1])
            return None
        dn = dotted_name(expr)
        if not dn:
            return None
        parts = dn.split(".")
        attr = parts[-1]
        if parts[0] == "self" and len(parts) == 2 and cls:
            declared = self.cfg.lock_attrs.get(cls)
            if declared is not None:
                if attr in declared:
                    return f"{self.owner(cls, attr)}.{attr}", []
                return None
            if _looks_like_lock(attr):
                return f"{self.owner(cls, attr)}.{attr}", []
            return None
        if len(parts) == 1 and _looks_like_lock(attr):
            return attr, []
        return None

    # -- with / locks ------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            got = self._lock_of(item.context_expr)
            if got is None:
                continue
            lock, inner = got
            self._emit("acquire", lock, node.lineno)
            for sub in inner:
                self._emit("acquire", sub, node.lineno)
            self.held = self.held + (lock,)
            pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        if pushed:
            self.held = self.held[:-pushed]

    # -- calls -------------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        dn = dotted_name(node.func)
        if dn is None and isinstance(node.func, ast.Attribute):
            attrs: List[str] = []
            cur: ast.AST = node.func
            while isinstance(cur, ast.Attribute):
                attrs.append(cur.attr)
                cur = cur.value
            if (isinstance(cur, ast.Call) and isinstance(cur.func, ast.Name)
                    and cur.func.id == "super" and len(attrs) == 1):
                # super().m(): same-object dispatch up the base chain —
                # resolved against scanned bases only, never by bare name
                # (sibling classes sharing method names otherwise create
                # phantom cross-class edges)
                dn = "super." + attrs[0]
            else:
                # call-rooted chain (``Experiment(...).configure()``): keep
                # the attribute tail so bare-name resolution still sees the
                # method — this is how the _producers_guard -> EXP edge
                # behind the delete_experiment AB-BA doctrine enters the
                # graph
                dn = "?." + ".".join(reversed(attrs))
        if dn:
            self._emit("call", dn, node.lineno)
            parts = dn.split(".")
            if len(parts) >= 2 and parts[-1] in _MUTATING_METHODS:
                # self.X.append(...) mutates self.X
                owner = dotted_name(node.func.value) if isinstance(
                    node.func, ast.Attribute) else None
                if owner:
                    op = owner.split(".")
                    if op[0] == "self" and len(op) == 2:
                        self._emit("write", op[1], node.lineno)
        self.generic_visit(node)

    # -- writes ------------------------------------------------------------
    def _write_targets(self, tgt: ast.AST, line: int) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._write_targets(e, line)
            return
        if isinstance(tgt, ast.Starred):
            self._write_targets(tgt.value, line)
            return
        if isinstance(tgt, ast.Subscript):
            tgt = tgt.value
        dn = dotted_name(tgt)
        if dn:
            parts = dn.split(".")
            if parts[0] == "self" and len(parts) >= 2:
                self._emit("write", parts[1], line)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._write_targets(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._write_targets(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._write_targets(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._write_targets(t, node.lineno)
        self.generic_visit(node)

    # nested defs get their own _FuncInfo; don't double-walk their bodies
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.visit(node.body)


class LockChecker:
    def __init__(self, modules: List[LintModule], cfg: LintConfig) -> None:
        self.modules = modules
        self.cfg = cfg
        self.funcs: List[_FuncInfo] = []
        self.by_class: Dict[Tuple[str, str], _FuncInfo] = {}
        self.by_name: Dict[str, List[_FuncInfo]] = {}
        self.class_bases: Dict[str, List[str]] = {}
        self.class_lock_defs: Dict[str, Set[str]] = {}
        self._hierarchy()
        self._collect()
        self._summarize()

    # -- pass 0: class hierarchy + lock-defining classes -------------------
    def _hierarchy(self) -> None:
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                self.class_bases.setdefault(node.name, [
                    b.id for b in node.bases if isinstance(b, ast.Name)])
                defs: Set[str] = set()
                declared = self.cfg.lock_attrs.get(node.name, frozenset())
                for item in node.body:
                    if not (isinstance(item, ast.FunctionDef)
                            and item.name in self.cfg.init_methods):
                        continue
                    for sub in ast.walk(item):
                        if not isinstance(sub, ast.Assign):
                            continue
                        for tgt in sub.targets:
                            dn = dotted_name(tgt)
                            if not dn:
                                continue
                            p = dn.split(".")
                            if p[0] == "self" and len(p) == 2 and (
                                    _looks_like_lock(p[1])
                                    or p[1] in declared):
                                defs.add(p[1])
                self.class_lock_defs.setdefault(node.name, set()).update(defs)

    def _lock_owner(self, cls: str, attr: str) -> str:
        """Nearest ancestor (self included) whose __init__ creates the
        lock — inherited acquisitions share the base class's node."""
        cur, seen = cls, set()
        while cur and cur not in seen:
            seen.add(cur)
            if attr in self.class_lock_defs.get(cur, ()):
                return cur
            cur = next((b for b in self.class_bases.get(cur, ())
                        if b in self.class_bases), None)
        return cls

    def _norm(self, name: str, cls: Optional[str]) -> str:
        node = _norm_lock(name, cls)
        if cls and node == f"{cls}.{name}":
            return f"{self._lock_owner(cls, name)}.{name}"
        return node

    # -- pass 1: per-function events --------------------------------------
    def _collect(self) -> None:
        for mod in self.modules:
            for fn, cls in mod.functions():
                clsname = cls.name if cls is not None else None
                holds = frozenset(
                    self._norm(h, clsname) for h in mod.holds_locks(fn))
                info = _FuncInfo(mod, fn, clsname, mod.qualname(fn), holds)
                walker = _FuncWalker(info, self.cfg, self._lock_owner)
                for stmt in fn.body:
                    walker.visit(stmt)
                self.funcs.append(info)
                if clsname:
                    self.by_class.setdefault((clsname, fn.name), info)
                self.by_name.setdefault(fn.name, []).append(info)

    # -- call resolution ---------------------------------------------------
    def _resolve(self, dn: str, caller: _FuncInfo
                 ) -> Tuple[List[_FuncInfo], Set[str]]:
        """(callee infos, extra lock nodes acquired by the call itself).

        The extra set models the sharded-ledger proxy: a mutator call
        acquires EXP and journals into the WAL buffer even though no
        scanned function by that name does so directly.
        """
        cfg = self.cfg
        parts = dn.split(".")
        last = parts[-1]
        if parts[0] == "self" and len(parts) == 2 and caller.cls:
            hit = self.by_class.get((caller.cls, last))
            return ([hit] if hit else []), set()
        if parts[0] == "super" and len(parts) == 2 and caller.cls:
            cur, seen = caller.cls, {caller.cls}
            while True:
                cur = next((b for b in self.class_bases.get(cur, ())
                            if b in self.class_bases and b not in seen),
                           None)
                if cur is None:
                    return [], set()
                seen.add(cur)
                hit = self.by_class.get((cur, last))
                if hit:
                    return [hit], set()
        recv = parts[-2] if len(parts) >= 2 else None
        role = cfg.receiver_roles.get(recv) if recv else None
        if role == "wal":
            hit = self.by_class.get((cfg.wal_class, last))
            return ([hit] if hit else []), set()
        if role == "backend":
            hit = self.by_class.get((cfg.backend_class, last))
            return ([hit] if hit else []), set()
        if role == "proxy":
            if last in cfg.proxy_lock_free:
                return [], set()
            extra: Set[str] = set()
            if last in cfg.proxy_mutators:
                extra = {EXP_LOCK, f"{cfg.wal_class}._buf_lock"}
            hit = self.by_class.get((cfg.backend_class, last))
            return ([hit] if hit else []), extra
        if last in cfg.never_resolve:
            return [], set()
        if len(parts) == 1:
            return list(self.by_name.get(last, ())), set()
        # foreign receiver: resolve by bare method name across the set
        return [f for f in self.by_name.get(last, ())
                if f.cls and f.cls not in cfg.no_fallback_classes], set()

    # -- pass 2: transitive summaries to a fixpoint ------------------------
    def _summarize(self) -> None:
        for info in self.funcs:
            for ev in info.events:
                if ev.kind == "acquire":
                    info.locks.add(ev.name)
                elif ev.kind == "call" and self._blocking(ev.name):
                    info.blocking.add(
                        (ev.name, f"{info.mod.relpath}:{ev.line}"))
        changed = True
        while changed:
            changed = False
            for info in self.funcs:
                for ev in info.events:
                    if ev.kind != "call":
                        continue
                    callees, extra = self._resolve(ev.name, info)
                    add_locks = set(extra)
                    add_block: Set[Tuple[str, str]] = set()
                    for c in callees:
                        if c is info:
                            continue
                        add_locks |= c.locks
                        add_block |= c.blocking
                    if not add_locks <= info.locks:
                        info.locks |= add_locks
                        changed = True
                    if not add_block <= info.blocking:
                        info.blocking |= add_block
                        changed = True

    def _blocking(self, dn: str) -> bool:
        last = dn.split(".")[-1]
        for pat in self.cfg.blocking_calls:
            if "." in pat:
                if dn == pat or dn.endswith("." + pat):
                    return True
            elif last == pat:
                return True
        return False

    # -- findings ----------------------------------------------------------
    def run(self) -> List[Finding]:
        out: List[Finding] = []
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

        def edge(a: str, b: str, mod: LintModule, line: int,
                 sym: str) -> None:
            if a != b and (a, b) not in edges:
                edges[(a, b)] = (mod.relpath, line, sym)

        for info in self.funcs:
            for ev in info.events:
                if ev.kind == "acquire":
                    for h in ev.held:
                        edge(h, ev.name, info.mod, ev.line, info.qualname)
                elif ev.kind == "call":
                    callees, extra = self._resolve(ev.name, info)
                    acq = set(extra)
                    blk: Set[Tuple[str, str]] = set()
                    for c in callees:
                        if c is not info:
                            acq |= c.locks
                            blk |= c.blocking
                    for h in ev.held:
                        for l in acq:
                            if l in ev.held:
                                # re-entrant: the callee re-acquires a lock
                                # the caller already holds — no new ordering
                                continue
                            edge(h, l, info.mod, ev.line, info.qualname)
                    hot = ev.held & self.cfg.no_block_locks
                    if hot:
                        held = ",".join(sorted(hot))
                        if self._blocking(ev.name):
                            out.append(self._f(
                                "MTL002", info, ev.line,
                                f"blocking call {ev.name}() while holding "
                                f"{held}", detail=f"{ev.name}|{held}"))
                        else:
                            for bname, bloc in sorted(blk):
                                out.append(self._f(
                                    "MTL002", info, ev.line,
                                    f"call {ev.name}() reaches blocking "
                                    f"{bname}() (at {bloc}) while holding "
                                    f"{held}",
                                    detail=f"{ev.name}>{bname}|{held}"))
                    # MTL004: holds-contract at the call site
                    for c in callees:
                        need = c.holds - ev.held
                        if need and c is not info:
                            out.append(self._f(
                                "MTL004", info, ev.line,
                                f"call {ev.name}() requires "
                                f"{','.join(sorted(need))} held "
                                f"(holds pragma on {c.qualname})",
                                detail=f"{ev.name}|"
                                       f"{','.join(sorted(need))}"))
                elif ev.kind == "write":
                    out.extend(self._check_write(info, ev))

        out.extend(self._cycles(edges))
        return [f for f in out if not self._suppressed(f)]

    def _check_write(self, info: _FuncInfo, ev: _Event) -> List[Finding]:
        if not info.cls or info.node.name in self.cfg.init_methods:
            return []
        guard = self.cfg.guarded_attrs.get(info.cls, {}).get(ev.name)
        if guard is None or guard in ev.held or guard in info.holds:
            return []
        return [self._f(
            "MTL003", info, ev.line,
            f"write to {info.cls}.{ev.name} outside its guard {guard}",
            detail=f"{ev.name}|{guard}")]

    def _cycles(self, edges: Dict[Tuple[str, str],
                                  Tuple[str, int, str]]) -> List[Finding]:
        adj: Dict[str, Set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        # nodes reachable from b back to a => edge a->b is on a cycle
        out: List[Finding] = []
        for (a, b), (relpath, line, sym) in sorted(edges.items()):
            stack, seen = [b], {b}
            on_cycle = False
            while stack:
                n = stack.pop()
                if n == a:
                    on_cycle = True
                    break
                for m in adj.get(n, ()):
                    if m not in seen:
                        seen.add(m)
                        stack.append(m)
            if on_cycle:
                out.append(Finding(
                    "MTL001", relpath, line,
                    f"lock-order inversion: {a} -> {b} completes a cycle "
                    f"(potential deadlock)", symbol=sym,
                    detail=f"{a}->{b}"))
        return out

    def _f(self, rule: str, info: _FuncInfo, line: int, msg: str,
           detail: str = "") -> Finding:
        return Finding(rule, info.mod.relpath, line, msg,
                       symbol=info.qualname, detail=detail)

    def _suppressed(self, f: Finding) -> bool:
        for mod in self.modules:
            if mod.relpath == f.file:
                return mod.suppressed(f.line, f.rule)
        return False


def check_locks(modules: List[LintModule], cfg: LintConfig
                ) -> List[Finding]:
    return LockChecker(modules, cfg).run()
