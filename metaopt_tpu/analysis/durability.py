"""Durability-contract checkers (MTD001-MTD004).

The contract (coord/protocol.py, "Durability semantics"): once the reply
to a mutating op is on the wire, the mutation and its reply-cache entry
are fsynced. Statically that decomposes into:

* the protocol module *declares* which ops journal
  (``JOURNALED_OPS`` / ``REPLY_JOURNALED_OPS`` / ``NESTED_JOURNALED_OPS``);
* every declared-journaled op's ``_dispatch`` branch must reach a
  journal point — a sharded-ledger mutator call (which journals inside
  the experiment lock) or a direct ``self._wal.append`` — else
  **MTD001**;
* the registries must not drift from the server's op sets: every op in
  ``_MUTATING_OPS`` is declared journaled, and every declared-journaled
  op is in ``_DURABLE_OPS`` so its reply actually waits on the fsync
  barrier — else **MTD002**;
* reply-journaled ops (``worker_cycle``) must call ``_journal_reply`` in
  their ``_handle_<op>`` handler — else **MTD003**;
* the binary wire's opcode table (``WIRE_OPCODES``) must cover every
  mutating/journaled op (a v2 request for one would otherwise carry the
  opcode-0 "unknown" hint, losing routing observability for exactly the
  ops whose retries depend on the reply cache), and opcode values must
  be unique and nonzero (they are on the wire; 0 is reserved for
  not-in-table) — else **MTD004**. Modules with no ``WIRE_OPCODES``
  declaration skip the check: a repo (or fixture) without the binary
  wire has nothing to drift.

The checker reads both the registry and the server sets from the AST
(never imports), so fixture modules in tests exercise it hermetically.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from metaopt_tpu.analysis.core import Finding, LintModule, dotted_name
from metaopt_tpu.analysis.registry import LintConfig, registry_frozensets

_REGISTRY_NAMES = {"JOURNALED_OPS", "REPLY_JOURNALED_OPS",
                   "NESTED_JOURNALED_OPS"}
_SERVER_SETS = {"_MUTATING_OPS", "_DURABLE_OPS", "_MUTATORS"}
_WIRE_TABLE_NAME = "WIRE_OPCODES"


def _wire_opcodes(modules: List[LintModule]
                  ) -> Tuple[Optional[Dict[str, int]],
                             Optional[LintModule], int]:
    """The binary wire's op→opcode table, parsed from whichever scanned
    module declares it (``WIRE_OPCODES = {...}``, plain or annotated
    assignment). None when no module declares one — MTD004 then has
    nothing to check."""
    for mod in modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                tgt, val = node.target, node.value
            else:
                continue
            if not (isinstance(tgt, ast.Name)
                    and tgt.id == _WIRE_TABLE_NAME):
                continue
            try:
                d = ast.literal_eval(val)
            except (ValueError, SyntaxError):
                continue
            if isinstance(d, dict) and all(
                    isinstance(k, str) and isinstance(v, int)
                    for k, v in d.items()):
                return d, mod, node.lineno
    return None, None, 0


def _find_registry(modules: List[LintModule], cfg: LintConfig
                   ) -> Tuple[Dict[str, FrozenSet[str]],
                              Optional[LintModule]]:
    """The declared op registries: from the config when set explicitly
    (tests), else parsed out of the protocol module."""
    reg: Dict[str, FrozenSet[str]] = {}
    if cfg.journaled_ops is not None:
        reg["JOURNALED_OPS"] = cfg.journaled_ops
        reg["REPLY_JOURNALED_OPS"] = cfg.reply_journaled_ops or frozenset()
        reg["NESTED_JOURNALED_OPS"] = (cfg.nested_journaled_ops
                                       or frozenset())
        return reg, None
    for mod in modules:
        if mod.relpath.endswith(cfg.protocol_module):
            got = registry_frozensets(mod, _REGISTRY_NAMES)
            if "JOURNALED_OPS" in got:
                for k in _REGISTRY_NAMES:
                    reg[k] = got.get(k, frozenset())
                return reg, mod
    return reg, None


def _server_class(mod: LintModule) -> Optional[ast.ClassDef]:
    """The class that declares op sets and a dispatch method."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            names = {t.id for s in node.body
                     if isinstance(s, ast.Assign)
                     for t in s.targets if isinstance(t, ast.Name)}
            if "_MUTATING_OPS" in names or "_DURABLE_OPS" in names:
                return node
    return None


def _branch_ops(test: ast.AST, op_var: str) -> Set[str]:
    """Op literals a dispatch ``if`` guards: ``op == "register"`` or
    ``op in ("a", "b")``."""
    if not isinstance(test, ast.Compare) or len(test.ops) != 1:
        return set()
    left = test.left
    if not (isinstance(left, ast.Name) and left.id == op_var):
        return set()
    cmp = test.comparators[0]
    if isinstance(test.ops[0], ast.Eq) and isinstance(cmp, ast.Constant) \
            and isinstance(cmp.value, str):
        return {cmp.value}
    if isinstance(test.ops[0], ast.In) and isinstance(
            cmp, (ast.Tuple, ast.List, ast.Set)):
        return {e.value for e in cmp.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)}
    return set()


def _journals(body: List[ast.stmt], cfg: LintConfig) -> bool:
    """Does this dispatch branch reach a journal point?"""
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if dn is None:
                continue
            parts = dn.split(".")
            last = parts[-1]
            if last in ("_journal_mutation", "_journal_reply"):
                return True
            recv = parts[-2] if len(parts) >= 2 else None
            if last == "append" and recv in cfg.journal_receivers:
                return True
            if recv is not None and cfg.receiver_roles.get(
                    recv) == "proxy" and last in cfg.proxy_mutators:
                return True
    return False


def check_durability(modules: List[LintModule], cfg: LintConfig
                     ) -> List[Finding]:
    out: List[Finding] = []
    reg, reg_mod = _find_registry(modules, cfg)
    server_mod: Optional[LintModule] = None
    server_cls: Optional[ast.ClassDef] = None
    for mod in modules:
        cls = _server_class(mod)
        if cls is not None:
            server_mod, server_cls = mod, cls
            break
    if server_cls is None or server_mod is None:
        return out
    sets = registry_frozensets(server_mod, _SERVER_SETS)
    mutating = sets.get("_MUTATING_OPS", frozenset())
    durable = sets.get("_DURABLE_OPS", frozenset())
    journaled = reg.get("JOURNALED_OPS", frozenset())
    reply_j = reg.get("REPLY_JOURNALED_OPS", frozenset())
    nested_j = reg.get("NESTED_JOURNALED_OPS", frozenset())
    cls_line = server_cls.lineno
    reg_file = reg_mod.relpath if reg_mod else server_mod.relpath

    if not reg:
        out.append(Finding(
            "MTD002", server_mod.relpath, cls_line,
            "no JOURNALED_OPS registry found for a server class with "
            "declared op sets", symbol=server_cls.name, detail="missing"))
        return out

    # registry drift (MTD002)
    for op in sorted(mutating - (journaled | reply_j | nested_j)):
        out.append(Finding(
            "MTD002", reg_file, 1,
            f"op {op!r} is in _MUTATING_OPS but not declared in the "
            f"journaled-ops registry", symbol=server_cls.name,
            detail=f"undeclared|{op}"))
    for op in sorted((journaled | reply_j | nested_j) - durable):
        out.append(Finding(
            "MTD002", server_mod.relpath, cls_line,
            f"op {op!r} is declared journaled but missing from "
            f"_DURABLE_OPS — its reply never waits on the fsync barrier",
            symbol=server_cls.name, detail=f"nobarrier|{op}"))

    # dispatch branches (MTD001)
    dispatch: Optional[ast.FunctionDef] = None
    handlers: Dict[str, ast.FunctionDef] = {}
    for node in server_cls.body:
        if isinstance(node, ast.FunctionDef):
            if node.name == cfg.dispatch_function:
                dispatch = node
            handlers[node.name] = node
    seen_ops: Set[str] = set()
    if dispatch is not None:
        for node in ast.walk(dispatch):
            if not isinstance(node, ast.If):
                continue
            ops = _branch_ops(node.test, cfg.dispatch_op_var)
            seen_ops |= ops
            need = ops & journaled
            if need and not _journals(node.body, cfg):
                out.append(Finding(
                    "MTD001", server_mod.relpath, node.lineno,
                    f"dispatch branch for {'/'.join(sorted(need))} "
                    f"mutates without reaching a wal.append/journal "
                    f"call", symbol=f"{server_cls.name}."
                    f"{cfg.dispatch_function}",
                    detail="|".join(sorted(need))))
    for op in sorted(journaled - seen_ops):
        out.append(Finding(
            "MTD001", server_mod.relpath,
            dispatch.lineno if dispatch else cls_line,
            f"declared-journaled op {op!r} has no dispatch branch",
            symbol=server_cls.name, detail=f"nobranch|{op}"))

    # reply-journaled handlers (MTD003)
    for op in sorted(reply_j):
        h = handlers.get(f"_handle_{op}")
        if h is None:
            out.append(Finding(
                "MTD003", server_mod.relpath, cls_line,
                f"reply-journaled op {op!r} has no _handle_{op} handler",
                symbol=server_cls.name, detail=f"nohandler|{op}"))
            continue
        called = any(
            isinstance(n, ast.Call)
            and (dotted_name(n.func) or "").endswith("_journal_reply")
            for n in ast.walk(h))
        if not called:
            out.append(Finding(
                "MTD003", server_mod.relpath, h.lineno,
                f"_handle_{op} never journals its reply "
                f"(_journal_reply) — retries across a restart "
                f"double-execute", symbol=f"{server_cls.name}."
                f"_handle_{op}", detail=f"nojournal|{op}"))

    # binary-wire opcode table vs the durability contract (MTD004)
    table = cfg.wire_opcodes
    wire_mod: Optional[LintModule] = None
    wire_line = cls_line
    if table is None:
        table, wire_mod, wire_line = _wire_opcodes(modules)
    if table is not None:
        wire_file = wire_mod.relpath if wire_mod else reg_file
        need = journaled | reply_j | nested_j | mutating
        for op in sorted(need - set(table)):
            out.append(Finding(
                "MTD004", wire_file, wire_line,
                f"mutating/journaled op {op!r} has no WIRE_OPCODES "
                f"entry — its binary-wire requests degrade to the "
                f"opcode-0 'unknown' hint", symbol=_WIRE_TABLE_NAME,
                detail=f"missing|{op}"))
        codes: Dict[int, str] = {}
        for op, code in table.items():
            if code == 0:
                out.append(Finding(
                    "MTD004", wire_file, wire_line,
                    f"op {op!r} is assigned opcode 0, the reserved "
                    f"not-in-table value", symbol=_WIRE_TABLE_NAME,
                    detail=f"reserved|{op}"))
            elif code in codes:
                out.append(Finding(
                    "MTD004", wire_file, wire_line,
                    f"opcode {code} is assigned to both {codes[code]!r} "
                    f"and {op!r} — opcodes are on the wire and must be "
                    f"unique", symbol=_WIRE_TABLE_NAME,
                    detail=f"dup|{code}"))
            else:
                codes[code] = op
    return [f for f in out if not _suppressed(modules, f)]


def _suppressed(modules: List[LintModule], f: Finding) -> bool:
    for mod in modules:
        if mod.relpath == f.file:
            return mod.suppressed(f.line, f.rule)
    return False
