"""The ``name~prior(...)`` DSL: parse priors out of a user command line or a

config-file template, and build (Space, CommandTemplate).

ref: src/metaopt/core/io/space_builder.py — the DSL is the product's signature
UX and is preserved:

    mopt hunt -n exp ./train.py --lr~'loguniform(1e-5, 1e-1)' \
        --layers~'uniform(1, 8, discrete=True)' data.yaml

Differences from the lineage (documented, per SURVEY.md §7 "hard parts"):
prior expressions are evaluated with a restricted AST walker (literals only),
never ``eval``; config-template keys are named by their dotted path.
"""

from __future__ import annotations

import ast
import copy
import re
from typing import Any, Dict, List, Mapping, Optional, Tuple

from metaopt_tpu.io.converters import infer_converter
from metaopt_tpu.space.dimensions import (
    Categorical,
    Dimension,
    Fidelity,
    Integer,
    Real,
)
from metaopt_tpu.space.space import Space

#: token shapes accepted: ``--name~prior(...)``, ``-n~prior(...)``,
#: ``name~prior(...)``; also ``--name=prior-expr`` style after ``~``.
_TOKEN_RE = re.compile(
    r"""^(?P<dashes>-{0,2})          # optional leading dashes
        (?P<name>[A-Za-z0-9_][A-Za-z0-9_.\-/]*)   # param name
        ~                            # the DSL marker
        (?P<expr>[A-Za-z_][A-Za-z0-9_]*\(.*\))$   # prior call
    """,
    re.VERBOSE | re.DOTALL,
)

#: ``name~prior(...)`` occurrences inside a TEXT config template (the
#: lineage's generic-converter fallback): one nesting level of parens so
#: kwargs like ``shape=(2, 2)`` parse
_TEXT_RE = re.compile(
    r"(?P<name>[A-Za-z_][A-Za-z0-9_.\-]*)"
    r"~(?P<expr>[A-Za-z_][A-Za-z0-9_]*\((?:[^()]|\([^()]*\))*\))"
)

#: prior-name → dimension class routing (``discrete=True`` reroutes to Integer)
_REAL_PRIORS = {"uniform", "loguniform", "normal"}
_INT_PRIORS = {"randint"}
_KNOWN_PRIORS = _REAL_PRIORS | _INT_PRIORS | {"choices", "fidelity"}


class PriorSyntaxError(ValueError):
    pass


def _literal(node: ast.expr, src: str) -> Any:
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        raise PriorSyntaxError(
            f"prior arguments must be literals, got {ast.dump(node)} in {src!r}"
        ) from None


def parse_prior(name: str, expr: str) -> Dimension:
    """``parse_prior('lr', 'loguniform(1e-5, 1e-1)')`` → a typed Dimension.

    The expression is parsed as a single call with literal args/kwargs only —
    a restricted, safe replacement for the lineage's eval-against-scipy-names.
    """
    try:
        tree = ast.parse(expr.strip(), mode="eval")
    except SyntaxError as e:
        raise PriorSyntaxError(f"cannot parse prior {expr!r} for {name!r}: {e}") from None
    call = tree.body
    if not isinstance(call, ast.Call) or not isinstance(call.func, ast.Name):
        raise PriorSyntaxError(f"prior must be a simple call, got {expr!r}")
    prior = call.func.id.lower()
    args = [_literal(a, expr) for a in call.args]
    kwargs = {}
    for kw in call.keywords:
        if kw.arg is None:
            raise PriorSyntaxError(f"**kwargs not allowed in prior {expr!r}")
        kwargs[kw.arg] = _literal(kw.value, expr)

    shape = kwargs.pop("shape", None)
    if shape is not None:
        shape = tuple(shape) if isinstance(shape, (list, tuple)) else (int(shape),)
    default_value = kwargs.pop("default_value", None)
    common = dict(shape=shape, default_value=default_value)

    if prior == "fidelity":
        return Fidelity(name, prior, *args, **{**kwargs, **common})
    if prior == "choices":
        return Categorical(name, prior, *args, **{**kwargs, **common})
    if prior in _INT_PRIORS or (prior in _REAL_PRIORS and kwargs.pop("discrete", False)):
        if prior == "normal":
            raise PriorSyntaxError("normal prior cannot be discrete")
        return Integer(name, prior, *args, **{**kwargs, **common})
    if prior in _REAL_PRIORS:
        return Real(name, prior, *args, **{**kwargs, **common})
    raise PriorSyntaxError(
        f"unknown prior {prior!r} in {expr!r}; known: uniform, loguniform, "
        f"normal, randint, choices, fidelity"
    )


def build_space(spec: Mapping[str, str]) -> Space:
    """Build a Space from ``{name: 'prior(...)'}`` (configuration round-trip)."""
    space = Space()
    for name, expr in spec.items():
        expr = expr.strip()
        if expr.startswith("~"):
            expr = expr[1:]
        space.register(parse_prior(name, expr))
    return space


class CommandTemplate:
    """The user command with prior tokens replaced by fillable slots.

    ``format(params)`` materializes argv for one trial: a token parsed from
    ``--lr~'loguniform(...)'`` becomes ``--lr=0.0003``; a bare ``x~uniform(..)``
    token becomes ``0.42`` positionally prefixed by nothing (name is only the
    space key). Config-file templates are materialized separately via
    :meth:`materialize_config`.
    """

    def __init__(
        self,
        argv: List[str],
        slots: Dict[int, Tuple[str, str]],  # argv index -> (param name, dashes)
        config_path: Optional[str] = None,
        config_template: Optional[Dict[str, Any]] = None,
        config_slots: Optional[Dict[str, str]] = None,  # dotted path -> param name
        config_argv_index: Optional[int] = None,
        config_text: Optional[str] = None,        # generic TEXT template
        config_text_slots: Optional[Dict[str, str]] = None,  # token -> param
    ) -> None:
        self.argv = list(argv)
        self.slots = dict(slots)
        self.config_path = config_path
        self.config_template = config_template
        self.config_slots = dict(config_slots or {})
        self.config_argv_index = config_argv_index
        self.config_text = config_text
        self.config_text_slots = dict(config_text_slots or {})

    def format(self, params: Mapping[str, Any], config_out: Optional[str] = None) -> List[str]:
        out = list(self.argv)
        for idx, (pname, dashes) in self.slots.items():
            val = params[pname]
            out[idx] = f"{dashes}{pname}={val}" if dashes else str(val)
        if self.config_argv_index is not None and config_out is not None:
            out[self.config_argv_index] = config_out
        return out

    def materialize_config(self, params: Mapping[str, Any], out_path: str) -> None:
        """Write the user config file with priors replaced by concrete values."""
        if self.config_text is not None:
            # generic text template: ONE regex pass replacing whole
            # `name~prior(...)` tokens — sequential str.replace would let a
            # dim whose name suffixes another's (lr vs wlr) corrupt it
            slots = self.config_text_slots

            def fill(m: "re.Match[str]") -> str:
                pname = slots.get(m.group(0))
                return str(params[pname]) if pname is not None else m.group(0)

            with open(out_path, "w") as f:
                f.write(_TEXT_RE.sub(fill, self.config_text))
            return
        if self.config_template is None:
            raise RuntimeError("no config template attached")
        data = copy.deepcopy(self.config_template)
        for dotted, pname in self.config_slots.items():
            node = data
            *parents, leaf = dotted.split(".")
            for p in parents:
                node = node[p]
            node[leaf] = params[pname]
        infer_converter(out_path).generate(out_path, data)

    @property
    def has_config(self) -> bool:
        return self.config_template is not None or self.config_text is not None

    @property
    def param_names(self) -> List[str]:
        return (
            [n for n, _ in self.slots.values()]
            + list(self.config_slots.values())
            + list(self.config_text_slots.values())
        )


class SpaceBuilder:
    """Parse ``~prior`` markers out of user argv (and any config file in it)."""

    def build(self, user_argv: List[str]) -> Tuple[Space, CommandTemplate]:
        space = Space()
        slots: Dict[int, Tuple[str, str]] = {}
        config_path: Optional[str] = None
        config_template: Optional[Dict[str, Any]] = None
        config_slots: Dict[str, str] = {}
        config_argv_index: Optional[int] = None

        config_text: Optional[str] = None
        config_text_slots: Dict[str, str] = {}

        for i, tok in enumerate(user_argv):
            m = _TOKEN_RE.match(tok)
            if m:
                name = m.group("name")
                space.register(parse_prior(name, m.group("expr")))
                slots[i] = (name, m.group("dashes"))
                continue
            if tok.endswith((".yaml", ".yml", ".json")) and i > 0:
                found = self._scan_config(tok)
                if found:
                    if config_path is not None:
                        raise PriorSyntaxError(
                            f"two config templates carry priors "
                            f"({config_path!r} and {tok!r}); only one "
                            "config file per command may hold ~priors"
                        )
                    config_path = tok
                    config_argv_index = i
                    config_template, config_slots = found
                    for dotted, (pname, expr) in config_slots.items():
                        space.register(parse_prior(pname, expr))
                    config_slots = {d: p for d, (p, _) in config_slots.items()}
                    continue
            if i > 0:
                # generic fallback (lineage's GenericConverter): ANY text
                # config carrying `name~prior(...)` tokens becomes a
                # textual template — ini/gin/toml/whatever, format
                # untouched. Deliberately NOT elif: a yaml-suffixed file
                # whose structured scan failed (list top level, bad syntax)
                # still gets the text scan instead of dropping its priors
                found_text = self._scan_text_config(tok)
                if found_text:
                    if config_path is not None:
                        raise PriorSyntaxError(
                            f"two config templates carry priors "
                            f"({config_path!r} and {tok!r}); only one "
                            "config file per command may hold ~priors"
                        )
                    config_path = tok
                    config_argv_index = i
                    config_text, text_priors = found_text
                    for pname, (token, expr) in text_priors.items():
                        space.register(parse_prior(pname, expr))
                        config_text_slots[token] = pname

        template = CommandTemplate(
            user_argv, slots, config_path, config_template, config_slots,
            config_argv_index, config_text, config_text_slots,
        )
        return space, template

    @staticmethod
    def _scan_text_config(path: str):
        """Generic text template: find ``name~prior(...)`` tokens in a file.

        Returns (raw text, {param name: (full token, prior expr)}) or None
        when the path isn't a readable modest-size text file with tokens.
        Script sources (.py/.sh) are excluded — the script is the thing
        being RUN, not a config to rewrite.
        """
        import os

        if path.endswith((".py", ".sh")) or not os.path.isfile(path):
            return None
        try:
            if os.path.getsize(path) > 1 << 20:
                return None
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except (OSError, UnicodeDecodeError):
            return None
        found: Dict[str, Tuple[str, str]] = {}
        for m in _TEXT_RE.finditer(text):
            name, expr, token = m.group("name"), m.group("expr"), m.group(0)
            # only tokens that fully PARSE as known priors turn a file into
            # a template: prose like "see y~f(x)" or "lr~uniform(low, high)"
            # in an inert data/doc file must stay inert
            if expr.split("(", 1)[0].lower() not in _KNOWN_PRIORS:
                continue
            try:
                parse_prior(name, expr)
            except PriorSyntaxError:
                continue
            if name in found and found[name][1] != expr:
                raise PriorSyntaxError(
                    f"{path}: dimension {name!r} declared twice with "
                    f"different priors ({found[name][1]!r} vs {expr!r})"
                )
            found[name] = (token, expr)
        return (text, found) if found else None

    @staticmethod
    def _scan_config(path: str):
        """Parse a config file; collect string values matching the DSL.

        Returns (template dict, {dotted path: (param name, prior expr)}) or
        None if the file can't be read as a mapping / has no priors.
        """
        try:
            data = infer_converter(path).parse(path)
        except Exception:
            return None
        if not isinstance(data, dict):
            return None
        found: Dict[str, Tuple[str, str]] = {}

        def walk(node: Any, prefix: str) -> None:
            if isinstance(node, dict):
                for k, v in node.items():
                    walk(v, f"{prefix}.{k}" if prefix else str(k))
            elif isinstance(node, str):
                m = _TOKEN_RE.match(node.strip())
                if m:
                    # inside a config file the value may be written either as
                    # 'name~prior(...)' or just '~prior(...)'; the key path
                    # names the dimension when the name part is absent.
                    found[prefix] = (m.group("name"), m.group("expr"))
                elif node.strip().startswith("~"):
                    expr = node.strip()[1:]
                    pname = prefix.split(".")[-1]
                    found[prefix] = (pname, expr)

        walk(data, "")
        if not found:
            return None
        return data, found
