"""The joint search space: an ordered mapping of named dimensions.

ref: src/metaopt/algo/space.py (``Space`` as an ordered dict of Dimensions;
joint ``sample`` returns per-dimension tuples). Points here are plain dicts
``{name: value}`` — friendlier than positional tuples and unambiguous under
space transforms.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from metaopt_tpu.space.dimensions import (
    Categorical,
    Dimension,
    Fidelity,
    Integer,
    Real,
)
from metaopt_tpu.utils.hashing import point_hash

# fidelity-cache sentinel (None is a valid cached value); a str is
# deepcopy-atomic, so a copied Space still compares ``is _UNSET`` correctly
_UNSET = "__fidelity_unset__"


class Space:
    """Ordered collection of :class:`Dimension`, keyed by name."""

    def __init__(self, dimensions: Optional[Mapping[str, Dimension] | List[Dimension]] = None):
        self._dims: Dict[str, Dimension] = {}
        self._fidelity_cache: Any = _UNSET
        if dimensions:
            items = (
                dimensions.values() if isinstance(dimensions, Mapping) else dimensions
            )
            for dim in items:
                self.register(dim)

    # -- container --------------------------------------------------------
    def register(self, dim: Dimension) -> None:
        if dim.name in self._dims:
            raise ValueError(f"dimension {dim.name!r} already in space")
        self._dims[dim.name] = dim
        self._fidelity_cache = _UNSET

    def __getitem__(self, name: str) -> Dimension:
        return self._dims[name]

    def __iter__(self) -> Iterator[str]:
        return iter(self._dims)

    def __len__(self) -> int:
        return len(self._dims)

    def items(self):
        return self._dims.items()

    def values(self):
        return list(self._dims.values())

    def keys(self):
        return list(self._dims)

    # -- fidelity ---------------------------------------------------------
    @property
    def fidelity(self) -> Optional[Fidelity]:
        """The (single) fidelity dimension, if any.

        Cached (``register`` invalidates): ``hash_point`` reads this on
        every trial-identity hash, which is per-registration hot.
        """
        if self._fidelity_cache is _UNSET:
            fids = [d for d in self._dims.values() if isinstance(d, Fidelity)]
            if len(fids) > 1:
                raise ValueError(
                    f"multiple fidelity dimensions: {[f.name for f in fids]}"
                )
            self._fidelity_cache = fids[0] if fids else None
        return self._fidelity_cache

    @property
    def searchable(self) -> List[Dimension]:
        """Dimensions the optimizer actually searches (everything non-fidelity)."""
        return [d for d in self._dims.values() if not isinstance(d, Fidelity)]

    # -- sampling / geometry ----------------------------------------------
    def sample(self, n: int = 1, seed=None) -> List[Dict[str, Any]]:
        """Joint sample of ``n`` points as dicts (fidelity set to max budget)."""
        rng = np.random.default_rng(seed) if not isinstance(seed, np.random.Generator) else seed
        cols = {name: dim.sample(n, rng) for name, dim in self._dims.items()}
        return [{name: cols[name][i] for name in self._dims} for i in range(n)]

    def __contains__(self, point) -> bool:
        if isinstance(point, str):
            return point in self._dims
        if not isinstance(point, Mapping):
            return False
        if set(point) != set(self._dims):
            return False
        return all(point[name] in dim for name, dim in self._dims.items())

    def hash_point(self, point: Mapping[str, Any], *, with_fidelity: bool = False) -> str:
        """Identity hash of a point; by default fidelity is excluded so a

        promoted trial (same params, higher budget) shares a lineage id with
        its parent — the key ASHA bookkeeping invariant.
        """
        fid = self.fidelity
        ignore = () if (with_fidelity or fid is None) else (fid.name,)
        return point_hash(point, ignore=ignore)

    @property
    def cardinality(self) -> float:
        card = 1.0
        for dim in self._dims.values():
            card *= dim.cardinality
        return card

    # -- vectorization ----------------------------------------------------
    def why_not_vectorizable(self) -> Optional[str]:
        """Reason this space cannot stack into device arrays, or None.

        A space is vectorizable when every dimension is a scalar
        Real/Integer/Categorical/Fidelity: reals and ints lower to float
        columns, categoricals to index columns (branchless ``jnp.take`` /
        ``lax.switch`` on the objective side), and the single fidelity dim
        is carried out-of-band (it must be constant per batch anyway).
        Shaped dimensions would need ragged stacking, so they opt out.
        """
        for name, dim in self._dims.items():
            if dim.shape:
                return f"dimension {name!r} is array-valued (shape={dim.shape})"
            if not isinstance(dim, (Real, Integer, Categorical, Fidelity)):
                return f"dimension {name!r} has unsupported type {dim.type!r}"
        return None

    def vectorizable(self) -> bool:
        """True when a pool of points can stack into homogeneous arrays."""
        return self.why_not_vectorizable() is None

    def stack_points(
        self, points: Sequence[Mapping[str, Any]]
    ) -> Tuple[Dict[str, np.ndarray], Optional[int]]:
        """Stack a homogeneous pool of points into per-dimension columns.

        Returns ``(cols, fidelity)`` where ``cols`` maps each non-fidelity
        dimension name to a ``(B,)`` numpy column — float64 for Real,
        int32 for Integer, int32 *option indices* for Categorical — and
        ``fidelity`` is the batch's single budget value (None if the space
        has no fidelity dim). Raises ValueError when the space is not
        vectorizable, the pool is empty, or fidelity varies across the
        batch: a mixed-fidelity pool is two device programs, not one.
        """
        reason = self.why_not_vectorizable()
        if reason is not None:
            raise ValueError(f"space is not vectorizable: {reason}")
        if not points:
            raise ValueError("cannot stack an empty pool")
        cols: Dict[str, np.ndarray] = {}
        fid = self.fidelity
        fid_value: Optional[int] = None
        if fid is not None:
            budgets = {int(p[fid.name]) for p in points if fid.name in p}
            if len(budgets) > 1:
                raise ValueError(
                    f"fidelity {fid.name!r} must be constant per batch, "
                    f"got {sorted(budgets)}"
                )
            fid_value = budgets.pop() if budgets else None
        for name, dim in self._dims.items():
            if isinstance(dim, Fidelity):
                continue
            raw = [p[name] for p in points]
            if isinstance(dim, Categorical):
                index = {repr(opt): i for i, opt in enumerate(dim.options)}
                try:
                    cols[name] = np.asarray(
                        [index[repr(v)] for v in raw], dtype=np.int32
                    )
                except KeyError as exc:
                    raise ValueError(
                        f"value {exc} not an option of {name!r}"
                    ) from None
            elif isinstance(dim, Integer):
                cols[name] = np.asarray([int(v) for v in raw], dtype=np.int32)
            else:
                cols[name] = np.asarray([float(v) for v in raw], dtype=np.float64)
        return cols, fid_value

    def unstack_points(
        self,
        cols: Mapping[str, np.ndarray],
        fidelity: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        """Inverse of :meth:`stack_points`: columns back to point dicts.

        Categorical index columns are mapped back to their option objects;
        the fidelity value (if given) is broadcast into every point.
        """
        sizes = {len(np.asarray(c)) for c in cols.values()}
        if len(sizes) != 1:
            raise ValueError(f"ragged columns: lengths {sorted(sizes)}")
        (batch,) = sizes
        fid = self.fidelity
        points: List[Dict[str, Any]] = []
        for i in range(batch):
            pt: Dict[str, Any] = {}
            for name, dim in self._dims.items():
                if isinstance(dim, Fidelity):
                    if fidelity is not None:
                        pt[name] = int(fidelity)
                    continue
                v = np.asarray(cols[name])[i]
                if isinstance(dim, Categorical):
                    pt[name] = dim.options[int(v)]
                elif isinstance(dim, Integer):
                    pt[name] = int(v)
                else:
                    pt[name] = float(v)
            points.append(pt)
        return points

    # -- config -----------------------------------------------------------
    @property
    def configuration(self) -> Dict[str, Any]:
        return {name: dim.get_prior_string() for name, dim in self._dims.items()}

    def __repr__(self) -> str:
        inner = ", ".join(f"{n}: {d.get_prior_string()}" for n, d in self._dims.items())
        return f"Space({{{inner}}})"

    def __eq__(self, other) -> bool:
        return isinstance(other, Space) and list(self.items()) == list(other.items())
