"""The joint search space: an ordered mapping of named dimensions.

ref: src/metaopt/algo/space.py (``Space`` as an ordered dict of Dimensions;
joint ``sample`` returns per-dimension tuples). Points here are plain dicts
``{name: value}`` — friendlier than positional tuples and unambiguous under
space transforms.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Mapping, Optional

import numpy as np

from metaopt_tpu.space.dimensions import Dimension, Fidelity
from metaopt_tpu.utils.hashing import point_hash

# fidelity-cache sentinel (None is a valid cached value); a str is
# deepcopy-atomic, so a copied Space still compares ``is _UNSET`` correctly
_UNSET = "__fidelity_unset__"


class Space:
    """Ordered collection of :class:`Dimension`, keyed by name."""

    def __init__(self, dimensions: Optional[Mapping[str, Dimension] | List[Dimension]] = None):
        self._dims: Dict[str, Dimension] = {}
        self._fidelity_cache: Any = _UNSET
        if dimensions:
            items = (
                dimensions.values() if isinstance(dimensions, Mapping) else dimensions
            )
            for dim in items:
                self.register(dim)

    # -- container --------------------------------------------------------
    def register(self, dim: Dimension) -> None:
        if dim.name in self._dims:
            raise ValueError(f"dimension {dim.name!r} already in space")
        self._dims[dim.name] = dim
        self._fidelity_cache = _UNSET

    def __getitem__(self, name: str) -> Dimension:
        return self._dims[name]

    def __iter__(self) -> Iterator[str]:
        return iter(self._dims)

    def __len__(self) -> int:
        return len(self._dims)

    def items(self):
        return self._dims.items()

    def values(self):
        return list(self._dims.values())

    def keys(self):
        return list(self._dims)

    # -- fidelity ---------------------------------------------------------
    @property
    def fidelity(self) -> Optional[Fidelity]:
        """The (single) fidelity dimension, if any.

        Cached (``register`` invalidates): ``hash_point`` reads this on
        every trial-identity hash, which is per-registration hot.
        """
        if self._fidelity_cache is _UNSET:
            fids = [d for d in self._dims.values() if isinstance(d, Fidelity)]
            if len(fids) > 1:
                raise ValueError(
                    f"multiple fidelity dimensions: {[f.name for f in fids]}"
                )
            self._fidelity_cache = fids[0] if fids else None
        return self._fidelity_cache

    @property
    def searchable(self) -> List[Dimension]:
        """Dimensions the optimizer actually searches (everything non-fidelity)."""
        return [d for d in self._dims.values() if not isinstance(d, Fidelity)]

    # -- sampling / geometry ----------------------------------------------
    def sample(self, n: int = 1, seed=None) -> List[Dict[str, Any]]:
        """Joint sample of ``n`` points as dicts (fidelity set to max budget)."""
        rng = np.random.default_rng(seed) if not isinstance(seed, np.random.Generator) else seed
        cols = {name: dim.sample(n, rng) for name, dim in self._dims.items()}
        return [{name: cols[name][i] for name in self._dims} for i in range(n)]

    def __contains__(self, point) -> bool:
        if isinstance(point, str):
            return point in self._dims
        if not isinstance(point, Mapping):
            return False
        if set(point) != set(self._dims):
            return False
        return all(point[name] in dim for name, dim in self._dims.items())

    def hash_point(self, point: Mapping[str, Any], *, with_fidelity: bool = False) -> str:
        """Identity hash of a point; by default fidelity is excluded so a

        promoted trial (same params, higher budget) shares a lineage id with
        its parent — the key ASHA bookkeeping invariant.
        """
        fid = self.fidelity
        ignore = () if (with_fidelity or fid is None) else (fid.name,)
        return point_hash(point, ignore=ignore)

    @property
    def cardinality(self) -> float:
        card = 1.0
        for dim in self._dims.values():
            card *= dim.cardinality
        return card

    # -- config -----------------------------------------------------------
    @property
    def configuration(self) -> Dict[str, Any]:
        return {name: dim.get_prior_string() for name, dim in self._dims.items()}

    def __repr__(self) -> str:
        inner = ", ".join(f"{n}: {d.get_prior_string()}" for n, d in self._dims.items())
        return f"Space({{{inner}}})"

    def __eq__(self, other) -> bool:
        return isinstance(other, Space) and list(self.items()) == list(other.items())
