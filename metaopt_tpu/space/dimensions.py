"""Typed dimensions over named priors.

ref: src/metaopt/algo/space.py — the lineage wraps scipy.stats distributions
and stores (prior name, args) for reproducibility. Here each prior is
implemented directly against ``numpy.random.Generator`` (uniform, loguniform,
normal, randint, choices) so sampling is dependency-light and exactly
reproducible from (prior, args, seed); the stored ``configuration`` round-trips
through the ``name~prior(...)`` DSL.

Supported priors (DSL names):

- ``uniform(low, high)``            → Real on [low, high)
- ``loguniform(low, high)``         → Real, log-uniform on [low, high)
- ``normal(loc, scale)``            → Real, unbounded
- ``uniform(low, high, discrete=True)`` → Integer on {low..high}
- ``randint(low, high)``            → Integer on {low..high-1} (numpy conv.)
- ``choices([...])`` / ``choices({opt: prob, ...})`` → Categorical
- ``fidelity(low, high, base=b)``   → Fidelity (the budget axis for ASHA/HB)

Every dimension supports ``sample(n, rng)``, ``interval()``, ``__contains__``,
an optional ``default_value``, and a ``shape`` for array-valued params.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

_RNG = np.random.Generator


def _as_rng(seed_or_rng) -> _RNG:
    if isinstance(seed_or_rng, np.random.Generator):
        return seed_or_rng
    return np.random.default_rng(seed_or_rng)


class Dimension:
    """One named axis of the search space.

    Subclasses implement ``_sample_one(rng, size)`` returning a numpy array of
    ``size`` draws, plus containment and interval logic.
    """

    #: DSL type tag used in configuration round-trips.
    type: str = "dimension"

    def __init__(
        self,
        name: str,
        prior_name: str,
        *args: Any,
        default_value: Any = None,
        shape: Optional[Tuple[int, ...]] = None,
        **kwargs: Any,
    ) -> None:
        if not name or not isinstance(name, str):
            raise ValueError(f"dimension name must be a non-empty str, got {name!r}")
        self.name = name
        self.prior_name = prior_name
        self.args = tuple(args)
        self.kwargs = dict(kwargs)
        self.shape = tuple(shape) if shape else ()
        self.default_value = default_value
        if default_value is not None and default_value not in self:
            raise ValueError(
                f"default_value {default_value!r} not inside dimension {self!r}"
            )

    # -- sampling ---------------------------------------------------------
    def _sample_scalar(self, rng: _RNG, size: int) -> np.ndarray:
        raise NotImplementedError

    def sample(self, n: int = 1, seed=None) -> List[Any]:
        """Draw ``n`` values (each of ``self.shape``) as Python/numpy values."""
        rng = _as_rng(seed)
        count = n * self.n_elements
        flat = self._sample_scalar(rng, count)
        if self.shape:
            return list(flat.reshape((n,) + self.shape))
        return [self._to_py(v) for v in flat]

    @staticmethod
    def _to_py(v):
        return v.item() if hasattr(v, "item") else v

    # -- geometry ---------------------------------------------------------
    def interval(self) -> Tuple[Any, Any]:
        raise NotImplementedError

    def __contains__(self, value: Any) -> bool:
        raise NotImplementedError

    @property
    def n_elements(self) -> int:
        """Scalar count of one value of this dimension (1 unless shaped)."""
        return max(1, int(np.prod(self.shape)) if self.shape else 1)

    def _each(self, value) -> Iterable[Any]:
        if self.shape:
            # object dtype: mixed-type categorical options (e.g. [1, 'a'])
            # must not coerce to a common dtype during the check
            arr = np.asarray(value, dtype=object)
            if arr.shape != self.shape:
                return iter(())  # wrong shape → nothing to check → not contained
            return arr.flat
        return (value,)

    # -- config -----------------------------------------------------------
    @property
    def configuration(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {
            "type": self.type,
            "prior": self.prior_name,
            "args": list(self.args),
            "kwargs": dict(self.kwargs),
        }
        if self.shape:
            cfg["shape"] = list(self.shape)
        if self.default_value is not None:
            cfg["default_value"] = self.default_value
        return cfg

    def get_prior_string(self) -> str:
        """Round-trip back to the DSL text, e.g. ``uniform(-5, 5)``."""
        parts = [repr(a) for a in self.args]
        parts += [f"{k}={v!r}" for k, v in self.kwargs.items()]
        if self.shape:
            parts.append(f"shape={list(self.shape)!r}")
        if self.default_value is not None:
            parts.append(f"default_value={self.default_value!r}")
        return f"{self.prior_name}({', '.join(parts)})"

    def __repr__(self) -> str:
        return f"{type(self).__name__}(name={self.name!r}, {self.get_prior_string()})"

    def __eq__(self, other) -> bool:
        return (
            type(self) is type(other)
            and self.name == other.name
            and self.prior_name == other.prior_name
            and self.args == other.args
            and self.kwargs == other.kwargs
            and self.shape == other.shape
        )

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.name, self.prior_name, self.args))

    @property
    def cardinality(self) -> float:
        return math.inf


class Real(Dimension):
    """Continuous dimension: uniform, loguniform, or normal prior."""

    type = "real"

    def __init__(self, name: str, prior_name: str, *args, precision: Optional[int] = None, **kwargs):
        self.precision = precision
        if prior_name in ("uniform", "loguniform"):
            if len(args) != 2:
                raise ValueError(f"{prior_name} takes (low, high), got {args}")
            low, high = float(args[0]), float(args[1])
            if not low < high:
                raise ValueError(f"{prior_name} needs low < high, got ({low}, {high})")
            if prior_name == "loguniform" and low <= 0:
                raise ValueError(f"loguniform needs low > 0, got {low}")
            self._low, self._high = low, high
        elif prior_name == "normal":
            if len(args) != 2:
                raise ValueError(f"normal takes (loc, scale), got {args}")
            self._loc, self._scale = float(args[0]), float(args[1])
            if self._scale <= 0:
                raise ValueError(f"normal needs scale > 0, got {self._scale}")
        else:
            raise ValueError(f"unknown real prior {prior_name!r}")
        super().__init__(name, prior_name, *args, **kwargs)
        if precision is not None:
            self.kwargs["precision"] = precision

    def _sample_scalar(self, rng: _RNG, size: int) -> np.ndarray:
        if self.prior_name == "uniform":
            out = rng.uniform(self._low, self._high, size)
        elif self.prior_name == "loguniform":
            out = np.exp(rng.uniform(math.log(self._low), math.log(self._high), size))
        else:  # normal
            out = rng.normal(self._loc, self._scale, size)
        if self.precision is not None:
            out = np.asarray([float(f"%.{self.precision}g" % v) for v in out])
            if self.prior_name != "normal":
                # %g rounding can step just past a bound; clip back inside
                out = np.clip(out, self._low, self._high)
        return out

    def interval(self) -> Tuple[float, float]:
        if self.prior_name == "normal":
            return (-math.inf, math.inf)
        return (self._low, self._high)

    def __contains__(self, value) -> bool:
        low, high = self.interval()
        try:
            return all(low <= float(v) <= high for v in self._each(value))
        except (TypeError, ValueError):
            return False


class Integer(Dimension):
    """Discrete numeric dimension on an inclusive integer range."""

    type = "integer"

    def __init__(self, name: str, prior_name: str, *args, **kwargs):
        kwargs.pop("discrete", None)  # the DSL flag that routed us here
        if prior_name in ("uniform", "randint"):
            if len(args) != 2:
                raise ValueError(f"{prior_name} takes (low, high), got {args}")
            low, high = int(args[0]), int(args[1])
            if prior_name == "randint":
                high -= 1  # numpy-style exclusive high → inclusive
            if not low <= high:
                raise ValueError(f"integer range empty: ({args[0]}, {args[1]})")
            self._low, self._high = low, high
        else:
            raise ValueError(f"unknown integer prior {prior_name!r}")
        super().__init__(name, prior_name, *args, **kwargs)
        if prior_name == "uniform":
            # so configuration/DSL round-trips route back to Integer
            self.kwargs["discrete"] = True

    def _sample_scalar(self, rng: _RNG, size: int) -> np.ndarray:
        return rng.integers(self._low, self._high + 1, size)

    def interval(self) -> Tuple[int, int]:
        return (self._low, self._high)

    def __contains__(self, value) -> bool:
        def ok(v) -> bool:
            try:
                return float(v) == int(v) and self._low <= int(v) <= self._high
            except (TypeError, ValueError):
                return False

        return all(ok(v) for v in self._each(value))

    @property
    def cardinality(self) -> float:
        return float(self._high - self._low + 1) ** self.n_elements


class Categorical(Dimension):
    """Finite unordered set of options, optionally with probabilities.

    DSL: ``choices(['a', 'b'])`` or ``choices({'a': 0.7, 'b': 0.3})`` or
    ``choices('a', 'b')``.
    """

    type = "categorical"

    def __init__(self, name: str, prior_name: str = "choices", *args, **kwargs):
        if len(args) == 1 and isinstance(args[0], dict):
            options = list(args[0].keys())
            probs = np.asarray([float(p) for p in args[0].values()], dtype=float)
            if not math.isclose(probs.sum(), 1.0, rel_tol=1e-6):
                raise ValueError(f"choice probabilities must sum to 1, got {probs.sum()}")
            probs = probs / probs.sum()
        else:
            if len(args) == 1 and isinstance(args[0], (list, tuple)):
                options = list(args[0])
            else:
                options = list(args)
            if not options:
                raise ValueError("choices() needs at least one option")
            probs = np.full(len(options), 1.0 / len(options))
        if len(set(map(repr, options))) != len(options):
            raise ValueError(f"duplicate options in {options!r}")
        self.options = options
        self.probabilities = probs
        super().__init__(name, prior_name, *args, **kwargs)

    def _sample_scalar(self, rng: _RNG, size: int) -> np.ndarray:
        idx = rng.choice(len(self.options), size=size, p=self.probabilities)
        return np.asarray([self.options[i] for i in idx], dtype=object)

    @staticmethod
    def _to_py(v):
        return v

    def interval(self) -> Tuple[Any, ...]:
        return tuple(self.options)

    def __contains__(self, value) -> bool:
        return all(any(v == opt for opt in self.options) for v in self._each(value))

    @property
    def cardinality(self) -> float:
        # like Integer: a shaped dim is the product over its elements
        return float(len(self.options)) ** self.n_elements


class Fidelity(Dimension):
    """The budget axis (epochs/steps) consumed by multi-fidelity algorithms.

    ref: the lineage's Fidelity dimension (post-v0; mandated by
    BASELINE.json's ASHA/Hyperband configs). ``base`` is the reduction factor
    eta used to derive rung levels: low, low*base, low*base^2, ... capped at
    high. Not sampled — algorithms assign fidelity explicitly; plain
    ``sample`` returns the maximum budget so fidelity-unaware algorithms run
    full-budget trials.
    """

    type = "fidelity"

    def __init__(self, name: str, prior_name: str = "fidelity", *args, base: int = 2, **kwargs):
        if len(args) != 2:
            raise ValueError(f"fidelity takes (low, high), got {args}")
        low, high = int(args[0]), int(args[1])
        if not (1 <= low <= high):
            raise ValueError(f"fidelity needs 1 <= low <= high, got ({low}, {high})")
        if base < 1:
            raise ValueError(f"fidelity base must be >= 1, got {base}")
        self.low, self.high, self.base = low, high, int(base)
        kwargs["base"] = int(base)
        super().__init__(name, prior_name, *args, **kwargs)

    def rungs(self) -> List[int]:
        """Budget levels from low to high by powers of base (high always last)."""
        if self.base == 1:
            return [self.high]
        levels = []
        b = self.low
        while b < self.high:
            levels.append(int(b))
            b *= self.base
        levels.append(self.high)
        return levels

    def _sample_scalar(self, rng: _RNG, size: int) -> np.ndarray:
        return np.full(size, self.high, dtype=int)

    def interval(self) -> Tuple[int, int]:
        return (self.low, self.high)

    def __contains__(self, value) -> bool:
        try:
            return all(self.low <= int(v) <= self.high for v in self._each(value))
        except (TypeError, ValueError):
            return False

    @property
    def cardinality(self) -> float:
        return float(len(self.rungs()))
