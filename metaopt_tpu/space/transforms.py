"""Space → unit-cube vectorization for algorithm math.

ref: the lineage's transformer/PrimaryAlgo pair (core/worker/transformer.py,
core/worker/primary_algo.py): algorithms see a uniform real vector space and
the wrapper converts on the suggest/observe boundary. Re-designed as a single
bijection ``UnitCube``: every searchable dimension maps to one column in
[0, 1], so surrogate models (TPE's KDE, EvolutionES mutations) are plain
array math that vectorizes/jits cleanly.

Column semantics per dimension type:

- Real uniform       → linear rescale
- Real loguniform    → log-linear rescale
- Real normal        → Gaussian CDF (scipy.special.ndtr)
- Integer            → linear rescale over [low - 0.5, high + 0.5], rounded on
                       the way back (so each integer owns an equal-width bin)
- Categorical        → bin center (i + .5)/k, floor on the way back; columns
                       carrying categoricals are flagged in ``categorical_mask``
                       so algorithms that want per-category frequencies (TPE)
                       can treat them specially
- Fidelity           → excluded (budget is assigned by the algorithm, not
                       searched)
- array-shaped dims  → one column per element (``w[0, 1]``-style names),
                       reassembled into arrays on the way back — surrogate
                       models see a flat cube regardless of param shapes
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Mapping, Sequence

import numpy as np
from scipy.special import ndtr, ndtri

from metaopt_tpu.space.dimensions import Categorical, Fidelity, Integer, Real
from metaopt_tpu.space.space import Space

_EPS = 1e-12


class UnitCube:
    """Bijection between space points (dicts) and vectors in [0, 1]^d."""

    def __init__(self, space: Space):
        self.space = space
        #: (dimension, element-index-or-None) per cube column: scalar dims
        #: own one column, array-shaped dims expand to one column per
        #: element so surrogate math never sees a ragged structure
        self.columns = []
        for d in space.values():
            if isinstance(d, Fidelity):
                continue
            if d.shape:
                for idx in np.ndindex(d.shape):
                    self.columns.append((d, idx))
            else:
                self.columns.append((d, None))
        #: per-column dimension objects (a shaped dim repeats)
        self.dims = [d for d, _ in self.columns]
        self.names = [
            d.name if idx is None else f"{d.name}{list(idx)}"
            for d, idx in self.columns
        ]
        self.categorical_mask = np.asarray(
            [isinstance(d, Categorical) for d in self.dims]
        )
        #: number of categories per column (1 for non-categorical)
        self.n_choices = np.asarray(
            [len(d.options) if isinstance(d, Categorical) else 1 for d in self.dims]
        )

    @property
    def n_dims(self) -> int:
        return len(self.dims)

    # -- forward ----------------------------------------------------------
    def _fwd_one(self, dim, value) -> float:
        if isinstance(dim, Categorical):
            i = next(j for j, opt in enumerate(dim.options) if opt == value)
            return (i + 0.5) / len(dim.options)
        if isinstance(dim, Integer):
            low, high = dim.interval()
            return (float(value) - (low - 0.5)) / ((high + 0.5) - (low - 0.5))
        assert isinstance(dim, Real)
        if dim.prior_name == "uniform":
            low, high = dim.interval()
            return min(1.0, max(0.0, (float(value) - low) / (high - low)))
        if dim.prior_name == "loguniform":
            low, high = dim.interval()
            return min(
                1.0,
                max(
                    0.0,
                    (math.log(float(value)) - math.log(low))
                    / (math.log(high) - math.log(low)),
                ),
            )
        # normal
        return float(ndtr((float(value) - dim._loc) / dim._scale))

    def transform(self, point: Mapping[str, Any]) -> np.ndarray:
        """Point dict → vector in [0,1]^d (fidelity dropped)."""
        out = []
        arrays: Dict[str, np.ndarray] = {}  # one conversion per shaped dim
        for d, idx in self.columns:
            if idx is None:
                value = point[d.name]
            else:
                arr = arrays.get(d.name)
                if arr is None:
                    # object dtype for categoricals: mixed-type options
                    # must not coerce
                    arr = np.asarray(
                        point[d.name],
                        dtype=object if isinstance(d, Categorical) else None,
                    )
                    arrays[d.name] = arr
                value = arr[idx]
            out.append(self._fwd_one(d, value))
        return np.asarray(out)

    def transform_many(self, points: Sequence[Mapping[str, Any]]) -> np.ndarray:
        if not points:
            return np.zeros((0, self.n_dims))
        return np.stack([self.transform(p) for p in points])

    def transform_columns(
        self, cols: Mapping[str, Sequence[Any]], n: int
    ) -> np.ndarray:
        """Column-major forward transform: per-param value columns (one
        sequence of ``n`` raw values each, the shape ``CompletedBatch.
        columns()`` hands over) → an ``(n, n_dims)`` matrix bit-identical
        row-for-row to ``transform(point)``.

        Uniform reals and integers vectorize over the column — the
        elementwise IEEE ops match the scalar path exactly. Loguniform
        and normal go through the SAME per-element ``math.log`` / scalar
        ``ndtr`` calls as ``_fwd_one`` (a vectorized np.log can differ in
        the last ulp, and the surrogate replay contract is bit-identity
        with the per-point stream). Categorical and array-shaped columns
        reuse ``_fwd_one`` per element: option equality is arbitrary-
        object equality, nothing to vectorize.
        """
        out = np.empty((n, self.n_dims), dtype=np.float64)
        for j, (d, idx) in enumerate(self.columns):
            col = cols[d.name]
            if idx is not None:
                for i in range(n):
                    arr = np.asarray(
                        col[i],
                        dtype=object if isinstance(d, Categorical) else None,
                    )
                    out[i, j] = self._fwd_one(d, arr[idx])
                continue
            if isinstance(d, Real) and d.prior_name == "uniform":
                low, high = d.interval()
                vals = np.asarray([float(v) for v in col], dtype=np.float64)
                np.clip((vals - low) / (high - low), 0.0, 1.0, out=out[:, j])
            elif isinstance(d, Integer):
                low, high = d.interval()
                vals = np.asarray([float(v) for v in col], dtype=np.float64)
                out[:, j] = (vals - (low - 0.5)) / ((high + 0.5) - (low - 0.5))
            else:
                for i in range(n):
                    out[i, j] = self._fwd_one(d, col[i])
        return out

    # -- backward ---------------------------------------------------------
    def _bwd_one(self, dim, u: float):
        u = min(1.0 - _EPS, max(_EPS, float(u)))
        if isinstance(dim, Categorical):
            i = min(len(dim.options) - 1, int(u * len(dim.options)))
            return dim.options[i]
        if isinstance(dim, Integer):
            low, high = dim.interval()
            v = (low - 0.5) + u * ((high + 0.5) - (low - 0.5))
            return int(min(high, max(low, round(v))))
        assert isinstance(dim, Real)
        if dim.prior_name == "uniform":
            low, high = dim.interval()
            return low + u * (high - low)
        if dim.prior_name == "loguniform":
            low, high = dim.interval()
            return math.exp(math.log(low) + u * (math.log(high) - math.log(low)))
        return dim._loc + dim._scale * float(ndtri(u))

    def untransform(self, vec: np.ndarray) -> Dict[str, Any]:
        """Vector in [0,1]^d → point dict (without fidelity)."""
        vec = np.asarray(vec)
        if vec.shape != (self.n_dims,):
            raise ValueError(f"expected shape ({self.n_dims},), got {vec.shape}")
        out: Dict[str, Any] = {}
        pending: Dict[str, Dict[tuple, Any]] = {}
        for (d, idx), u in zip(self.columns, vec):
            if idx is None:
                out[d.name] = self._bwd_one(d, u)
            else:
                pending.setdefault(d.name, {})[idx] = self._bwd_one(d, u)
        for (d, idx) in self.columns:  # reassemble shaped dims
            if idx is None or d.name in out:
                continue
            elems = pending[d.name]
            arr = np.empty(d.shape, dtype=object)
            for i, v in elems.items():
                arr[i] = v
            if isinstance(d, Integer):
                arr = arr.astype(np.int64)
            elif isinstance(d, Real):
                arr = arr.astype(np.float64)
            else:
                # Categorical: nested list, NOT np.asarray — mixed-type
                # options (e.g. [1, 'a']) must not coerce to one dtype
                arr = arr.tolist()
            out[d.name] = arr
        return out

    def untransform_many(self, mat: np.ndarray) -> List[Dict[str, Any]]:
        return [self.untransform(row) for row in np.asarray(mat)]
