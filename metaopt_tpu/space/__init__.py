"""Typed search space.

ref: src/metaopt/algo/space.py (Space, Dimension/Real/Integer/Categorical) and
the lineage's Fidelity dimension. Sampling here is host-side control-plane work
over ``numpy.random.Generator``; the algorithm-facing vectorization lives in
:mod:`metaopt_tpu.space.transforms` so surrogate math can run as jitted JAX.
"""

from metaopt_tpu.space.dimensions import (
    Categorical,
    Dimension,
    Fidelity,
    Integer,
    Real,
)
from metaopt_tpu.space.space import Space
from metaopt_tpu.space.transforms import UnitCube
from metaopt_tpu.space.builder import SpaceBuilder, parse_prior, build_space

__all__ = [
    "Dimension",
    "Real",
    "Integer",
    "Categorical",
    "Fidelity",
    "Space",
    "UnitCube",
    "SpaceBuilder",
    "parse_prior",
    "build_space",
]
