"""Sharded serving: N coordinator processes behind one consistent-hash map.

PR 3 measured the WAL tax on the 1-core CI box at ~30% and attributed it
to GIL-bound wakeup scheduling, not fsync — no in-process tuning buys it
back; only more processes can. This module escapes the single Python
coordinator process while keeping every per-shard guarantee intact:

- **Sharding unit = experiment.** Every request that names an experiment
  (directly, via a trial doc, or via a config) is owned by exactly one
  shard, chosen by a consistent-hash ring over the experiment id
  (:class:`HashRing`). A shard is a full, unmodified
  :class:`~metaopt_tpu.coord.server.CoordServer` subprocess with its OWN
  WAL + crash-atomic snapshot + journaled reply cache, so the durability
  and exactly-once story is per-shard verbatim — nothing is re-proved.
- **Routing, two ways (rolling-upgrade safe both directions).** New
  clients learn the shard map from the ``ping`` reply (cap
  ``"shard_map"``) and route DIRECTLY to the owning shard — zero extra
  hops on the hot path. Old clients that ignore the cap keep talking to
  the public address, where a thin stdlib :class:`ShardRouter` process
  decodes just enough of each frame to pick the shard, forwards the raw
  payload, and relays the raw reply — request ids pass through
  untouched, so the shard's journaled reply cache still gives
  exactly-once across router-side retries.
- **Recovery isolation.** :class:`ShardSupervisor` spawns shards as
  subprocesses (``python -m metaopt_tpu.coord.shards``), waits for each
  one's ``coordinator ready`` line (which doubles as the
  recovery-complete signal — restore + WAL replay happen inside
  ``start()``), and restarts any shard that dies on the SAME
  snapshot/WAL paths. One shard's crash+replay never stalls the others:
  each shard recovers in its own process while the survivors keep
  serving, and the router retries only the dead shard's traffic inside
  its reconnect window.

The hash uses md5, not Python's builtin ``hash()`` — the builtin is
salted per process (PYTHONHASHSEED), and a ring that two processes
disagree on routes every request wrong.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import logging
import os
import signal as _signal_mod
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from metaopt_tpu.coord.protocol import (
    ProtocolError,
    WIRE_OPCODES,
    decode_payload,
    encode_msg,
    encode_reply_v2,
    payload_is_v2,
    recv_payload,
    reply_shard_miss,
    request_opcode,
    request_routing_key,
    send_msg,
    send_payload,
)

log = logging.getLogger(__name__)

SHARD_MAP_VERSION = 1
#: virtual nodes per shard on the ring — enough that a 2..16-shard map
#: balances experiment ownership to within a few percent
DEFAULT_VNODES = 64

#: the ping cap a shard-map-aware server (or the router) advertises;
#: clients that know it read ``shard_map`` off the ping reply and route
#: directly, clients that don't simply keep using the address they have
SHARD_MAP_CAP = "shard_map"

#: the ops the router answers itself rather than relaying; a v2 request
#: whose header opcode is outside this set is routed WITHOUT decoding
_PAN_SHARD_OPS = ("ping", "list_experiments", "snapshot", "tenant_stats")
_PAN_SHARD_OPCODES = frozenset(WIRE_OPCODES[op] for op in _PAN_SHARD_OPS)


def stable_hash(key: str) -> int:
    """Process-independent 64-bit hash of ``key``.

    Python's builtin ``hash()`` is salted per process — every router,
    shard, and client must place an experiment at the SAME ring position,
    so the hash has to be deterministic across processes and runs.
    """
    return int.from_bytes(hashlib.md5(key.encode("utf-8")).digest()[:8],
                          "big")


def merge_tenant_stats(parts: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold per-shard ``tenant_stats`` replies into one pod-wide view.

    Counters are additive (each shard only grants produce legs for the
    experiments it owns); a tenant's weight is configuration, identical
    on every shard, so any shard's value stands.
    """
    out: Dict[str, Any] = {
        "tenants": {}, "resident": 0, "evicted": 0,
        "evictions": 0, "hydrations": 0,
    }
    for part in parts:
        if not isinstance(part, dict):
            continue
        for key in ("resident", "evicted", "evictions", "hydrations"):
            out[key] += int(part.get(key) or 0)
        for tenant, row in (part.get("tenants") or {}).items():
            acc = out["tenants"].setdefault(tenant, {
                "granted": 0, "denied": 0, "experiments": 0,
                "evicted": 0, "weight": row.get("weight", 1.0),
            })
            for key in ("granted", "denied", "experiments", "evicted"):
                acc[key] += int(row.get(key) or 0)
        if "experiments" in part:
            out.setdefault("experiments", {}).update(
                part["experiments"] or {})
    return out


def experiment_of(op: Optional[str], args: Dict[str, Any]) -> Optional[str]:
    """The routing key (experiment id) of one request, or None.

    Mirrors ``_ShardedLedger._exp_of`` — the same derivation the server
    uses to pick a lock picks the shard: trial-payload ops ride the
    trial doc's ``experiment``, ``create_experiment`` the config's
    ``name``, everything else the explicit ``experiment``/``name`` arg.
    Requests with no key (ping, list_experiments, snapshot) are
    pan-shard and handled by the caller.
    """
    exp = args.get("experiment")
    if isinstance(exp, str):
        return exp
    if op == "create_experiment":
        cfg = args.get("config") or {}
        name = cfg.get("name")
        return name if isinstance(name, str) else None
    trial = args.get("trial")
    if isinstance(trial, dict):
        t_exp = trial.get("experiment")
        if isinstance(t_exp, str):
            return t_exp
    name = args.get("name")
    return name if isinstance(name, str) else None


class HashRing:
    """Consistent-hash ring: shard ids placed at ``vnodes`` points each.

    ``owner(key)`` is the first point clockwise of ``hash(key)`` —
    adding/removing one shard remaps only ~1/N of the keyspace, which is
    what makes the stretch goal (experiment hand-off on rebalance)
    tractable later without re-routing the world.
    """

    def __init__(self, shard_ids: List[str],
                 vnodes: int = DEFAULT_VNODES) -> None:
        if not shard_ids:
            raise ValueError("hash ring needs at least one shard")
        points = []
        for sid in shard_ids:
            for v in range(vnodes):
                points.append((stable_hash(f"{sid}#{v}"), sid))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._owners = [sid for _, sid in points]

    def owner(self, key: str) -> str:
        i = bisect.bisect_right(self._hashes, stable_hash(key))
        return self._owners[i % len(self._owners)]


def make_shard_map(shards: List[Tuple[str, str, int]],
                   vnodes: int = DEFAULT_VNODES) -> Dict[str, Any]:
    """Wire-form shard map from ``[(shard_id, host, port), …]``."""
    return {
        "version": SHARD_MAP_VERSION,
        "vnodes": int(vnodes),
        "shards": [
            {"id": sid, "host": host, "port": int(port)}
            for sid, host, port in shards
        ],
    }


def ring_of(shard_map: Dict[str, Any]) -> HashRing:
    return HashRing([s["id"] for s in shard_map["shards"]],
                    vnodes=int(shard_map.get("vnodes", DEFAULT_VNODES)))


def shard_addrs(shard_map: Dict[str, Any]) -> Dict[str, Tuple[str, int]]:
    """shard id → (host, port), in map order."""
    return {s["id"]: (s["host"], int(s["port"]))
            for s in shard_map["shards"]}


class RoutingTable:
    """Overrides-aware routing view of ONE shard-map version.

    A hand-off pins the moved experiment to its new owner via the map's
    ``overrides`` dict (experiment → shard id) so the move does not have
    to wait for ring churn; the ring stays the default for every
    un-pinned key. ``owner()`` keeps the HashRing signature, so every
    caller that used to hold a ring can hold a table instead.
    """

    def __init__(self, shard_map: Dict[str, Any]) -> None:
        self.shard_map = shard_map
        self.version = int(shard_map.get("version", 0))
        self.overrides: Dict[str, str] = dict(
            shard_map.get("overrides") or {})
        self.addrs = shard_addrs(shard_map)
        self._ring = ring_of(shard_map)

    def owner(self, key: str) -> str:
        sid = self.overrides.get(key)
        return sid if sid is not None else self._ring.owner(key)


def map_version(shard_map: Optional[Dict[str, Any]]) -> int:
    return int(shard_map.get("version", 0)) if shard_map else -1


def with_override(shard_map: Dict[str, Any], experiment: str,
                  dest_sid: str) -> Dict[str, Any]:
    """A version-bumped copy of the map pinning ``experiment`` to
    ``dest_sid`` (or un-pinning it when that is its natural ring owner)."""
    if dest_sid not in shard_addrs(shard_map):
        raise ValueError(f"unknown destination shard {dest_sid!r}")
    new = json.loads(json.dumps(shard_map))
    overrides = dict(new.get("overrides") or {})
    if ring_of(new).owner(experiment) == dest_sid:
        overrides.pop(experiment, None)
    else:
        overrides[experiment] = dest_sid
    new["overrides"] = overrides
    new["version"] = map_version(shard_map) + 1
    return new


def without_shard(shard_map: Dict[str, Any], dead_sid: str
                  ) -> Dict[str, Any]:
    """A version-bumped copy of the map with ``dead_sid`` removed.

    Overrides that pinned experiments to the dead shard are dropped —
    the shrunken ring's natural owner (always a survivor) takes over;
    survivors' own keys don't move, that is the point of the
    consistent hash.
    """
    new = json.loads(json.dumps(shard_map))
    new["shards"] = [s for s in new["shards"] if s["id"] != dead_sid]
    if not new["shards"]:
        raise ValueError("cannot remove the last shard from the map")
    new["overrides"] = {e: s
                       for e, s in (new.get("overrides") or {}).items()
                       if s != dead_sid}
    new["version"] = map_version(shard_map) + 1
    return new


def _free_port(host: str = "127.0.0.1") -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# router — the old-client fallback path
# ---------------------------------------------------------------------------

class ShardRouter:
    """Thin stdlib proxy for clients that don't speak the shard map.

    Per client connection, one thread: decode the request frame (JSON —
    only to read ``op``/``args`` for the routing key), forward the raw
    payload to the owning shard over a per-connection upstream socket,
    and relay the shard's raw reply bytes verbatim. No reply re-encode,
    no state: the request id inside the payload reaches the shard
    unmodified, so retries the router itself performs after an upstream
    drop are answered exactly-once from the shard's journaled reply
    cache — the router adds a hop, never a semantics change.

    Pan-shard ops are the only ones the router answers itself:

    - ``ping`` → forwarded to the first shard, then augmented with the
      shard map + the ``shard_map`` cap, so even a via-router ping
      teaches a NEW client to go direct on its next call.
    - ``list_experiments`` → fan-out, merged + sorted.
    - ``snapshot`` → fan-out; each shard snapshots its own configured
      path (or ``<path>.<shard id>`` when the caller named one).

    A dead upstream is retried with decorrelated jitter inside
    ``reconnect_window_s`` (a shard restart + replay window); past it
    the client connection is dropped and the old client's own
    reconnect/retry logic takes over.
    """

    def __init__(self, shard_map: Dict[str, Any], host: str = "127.0.0.1",
                 port: int = 0, reconnect_window_s: float = 30.0) -> None:
        self.shard_map = shard_map
        self.reconnect_window_s = reconnect_window_s
        #: routing state (shard_map/_table/_addrs/_first_sid) is read per
        #: request and replaced wholesale by update_map() after a
        #: hand-off/failover — all of it lives under _map_lock
        self._map_lock = threading.Lock()
        self._table = RoutingTable(shard_map)
        self._addrs = shard_addrs(shard_map)
        self._first_sid = shard_map["shards"][0]["id"]
        self._bind = (host, port)
        self._sock: Optional[socket.socket] = None
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self._stopping = threading.Event()

    # -- lifecycle ---------------------------------------------------------
    @property
    def address(self) -> Tuple[str, int]:
        assert self._sock is not None, "router not started"
        return self._sock.getsockname()[:2]

    def start(self) -> "ShardRouter":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(self._bind)
        self._sock.listen(128)
        t = threading.Thread(target=self._accept_loop,
                             name="coord-router-accept", daemon=True)
        t.start()
        self._threads.append(t)
        log.info("shard router listening on %s:%d (%d shards)",
                 *self.address, len(self._addrs))
        return self

    def stop(self) -> None:
        self._stopping.set()
        if self._sock is not None:
            # shutdown() before close(): same accept()-never-wakes doctrine
            # as CoordServer.stop()
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2)

    def __enter__(self) -> "ShardRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- map churn ---------------------------------------------------------
    def update_map(self, new_map: Dict[str, Any]) -> bool:
        """Adopt ``new_map`` iff its version is strictly newer.

        Called by the supervisor after a hand-off/failover commit and by
        the relay path itself when a shard's reply reveals a newer map.
        Monotonic: a stale lower-version map (a slow pre-migration ping
        racing the commit) can never roll the routing table back.
        """
        with self._map_lock:
            if map_version(new_map) <= self._table.version:
                return False
            self.shard_map = new_map
            self._table = RoutingTable(new_map)
            self._addrs = shard_addrs(new_map)
            self._first_sid = new_map["shards"][0]["id"]
        log.info("router adopted shard map v%d (%d shards, %d overrides)",
                 map_version(new_map), len(new_map["shards"]),
                 len(new_map.get("overrides") or {}))
        return True

    def _refresh_map(self, sid: str,
                     upstream: Dict[str, socket.socket]) -> None:
        """Best-effort: ping shard ``sid`` and adopt any newer map it
        advertises (post-commit, the migration source/survivors all carry
        the bumped map)."""
        try:
            reply = decode_payload(self._forward(
                sid, encode_msg({"op": "ping", "args": {}}), upstream))
            smap = (reply.get("result") or {}).get("shard_map") \
                if reply.get("ok") else None
            if smap:
                self.update_map(smap)
        except (ConnectionError, BrokenPipeError, OSError, ProtocolError,
                json.JSONDecodeError, KeyError):
            log.debug("router map refresh via %s failed", sid,
                      exc_info=True)

    @staticmethod
    def _routing_miss(reply: Dict[str, Any]) -> bool:
        """True for the two retryable mid-migration answers."""
        return (not reply.get("ok")
                and reply.get("error") in ("WrongShardError", "Migrating"))

    # -- relay plumbing ----------------------------------------------------
    def _accept_loop(self) -> None:
        assert self._sock is not None
        while not self._stopping.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # socket closed by stop()
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="coord-router-conn", daemon=True)
            t.start()

    def _connect(self, sid: str) -> socket.socket:
        with self._map_lock:
            addr = self._addrs[sid]
        s = socket.create_connection(addr, timeout=10.0)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(None)
        return s

    def _forward(self, sid: str, payload: bytes,
                 upstream: Dict[str, socket.socket]) -> bytes:
        """Send ``payload`` to shard ``sid``; return the raw reply payload.

        Retries through a shard restart: the resent payload carries the
        SAME request id, so a mutating op that executed before the crash
        is answered from the shard's journaled reply cache, not re-run.
        """
        from metaopt_tpu.coord.client_backend import decorrelated_jitter

        deadline = time.monotonic() + self.reconnect_window_s
        delay = 0.0
        while True:
            try:
                s = upstream.get(sid)
                if s is None:
                    s = upstream[sid] = self._connect(sid)
                send_payload(s, payload)
                reply = recv_payload(s)
                if reply is None:
                    raise ConnectionError("shard closed the connection")
                return reply
            except (ConnectionError, BrokenPipeError, OSError,
                    ProtocolError):
                stale = upstream.pop(sid, None)
                if stale is not None:
                    try:
                        stale.close()
                    except OSError:
                        pass
                if (self._stopping.is_set()
                        or time.monotonic() >= deadline):
                    raise
                delay = decorrelated_jitter(delay)
                time.sleep(delay)

    def _fanout(self, msg: Dict[str, Any],
                upstream: Dict[str, socket.socket]) -> List[Dict[str, Any]]:
        """One reply dict per shard, in map order; raises on dead shard.

        A shard that answers ``WrongShardError``/``Migrating`` is
        mid-hand-off, not broken: refresh the map from it and re-run the
        fan-out against the (possibly newer) shard set instead of
        surfacing a transient routing error to an old client.
        """
        from metaopt_tpu.coord.client_backend import decorrelated_jitter

        deadline = time.monotonic() + self.reconnect_window_s
        delay = 0.0
        while True:
            with self._map_lock:
                sids = list(self._addrs)
            replies = []
            stale_sid = None
            for sid in sids:
                a = dict(msg.get("args") or {})
                if msg.get("op") == "snapshot" and a.get("path"):
                    # each shard owns its own snapshot file — a shared
                    # literal path would have N processes racing one
                    # atomic rename
                    a["path"] = f"{a['path']}.{sid}"
                try:
                    r = decode_payload(self._forward(
                        sid, encode_msg({**msg, "args": a}), upstream))
                except KeyError:
                    # the sid left the map mid-fan-out (failover shrank
                    # the ring): re-run against the current shard set
                    stale_sid = sid
                    replies = None
                    break
                if self._routing_miss(r):
                    stale_sid = sid
                replies.append(r)
            if replies is not None and (stale_sid is None
                                        or time.monotonic() >= deadline):
                return replies
            self._refresh_map(stale_sid, upstream)
            delay = decorrelated_jitter(delay)
            time.sleep(delay)

    def _ping_reply(self, msg: Dict[str, Any],
                    upstream: Dict[str, socket.socket]) -> Dict[str, Any]:
        with self._map_lock:
            first_sid = self._first_sid
        reply = decode_payload(self._forward(
            first_sid, encode_msg(msg), upstream))
        if reply.get("ok"):
            res = reply["result"]
            # a post-hand-off shard may advertise a newer map than the
            # router has seen — adopt it before echoing a map back
            smap = res.get("shard_map")
            if smap:
                self.update_map(smap)
            caps = set(res.get("caps") or ())
            caps.add(SHARD_MAP_CAP)
            res["caps"] = sorted(caps)
            with self._map_lock:
                res["shard_map"] = self.shard_map
            # the first shard's shard_id is ITS identity, not this
            # connection's — a routed client has no single shard
            res.pop("shard_id", None)
            # ditto its Unix socket: a same-host client that adopted it
            # would dial shard 0 directly for SEED traffic and bypass
            # the router's fan-out ops entirely
            res.pop("uds_path", None)
        return reply

    def _relay(self, conn: socket.socket, payload: bytes,
               exp: Optional[str],
               upstream: Dict[str, socket.socket]) -> None:
        """Forward one experiment-keyed request payload verbatim (either
        codec), chasing a live hand-off.

        ``Migrating`` means the owner is quiescing the experiment (retry
        the same shard until the commit lands); ``WrongShardError`` means
        ownership already moved (refresh the map and follow it). Past the
        window the last reply — whatever it was — is surfaced.
        """
        from metaopt_tpu.coord.client_backend import decorrelated_jitter

        deadline = time.monotonic() + self.reconnect_window_s
        delay = 0.0
        while True:
            with self._map_lock:
                sid = (self._table.owner(exp) if exp is not None
                       else self._first_sid)
            try:
                raw = self._forward(sid, payload, upstream)
            except KeyError:
                # the owner left the map mid-forward (failover shrank the
                # ring under a connect retry): re-resolve against the new
                # table — the shrunken ring names a survivor
                if time.monotonic() >= deadline:
                    raise ConnectionError(f"shard {sid} left the map")
                delay = decorrelated_jitter(delay)
                time.sleep(delay)
                continue
            if exp is not None:
                if payload_is_v2(raw):
                    # two header bytes say miss-or-not — no body decode
                    miss = reply_shard_miss(raw)
                else:
                    # cheap sniff before a JSON parse: routing misses are
                    # tiny error frames, hot replies pass untouched
                    miss = None
                    if (len(raw) < 512 and (b"WrongShardError" in raw
                                            or b"Migrating" in raw)):
                        reply = json.loads(raw)
                        if self._routing_miss(reply):
                            miss = reply["error"]
                if miss is not None and time.monotonic() < deadline:
                    self._refresh_map(sid, upstream)
                    delay = decorrelated_jitter(delay)
                    time.sleep(delay)
                    continue
            send_payload(conn, raw)
            return

    @staticmethod
    def _send_reply(conn: socket.socket, reply: Dict[str, Any],
                    wire: str) -> None:
        """A router-composed reply, in the codec the request arrived in."""
        if wire == "v2":
            try:
                send_payload(conn, encode_reply_v2(reply))
                return
            except ProtocolError:
                pass  # unencodable body: this one frame goes JSON
        send_msg(conn, reply)

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with self._conns_lock:
            self._conns.add(conn)
        upstream: Dict[str, socket.socket] = {}
        try:
            while not self._stopping.is_set():
                try:
                    payload = recv_payload(conn)
                except (ProtocolError, ConnectionError, OSError):
                    return
                if payload is None or self._stopping.is_set():
                    return
                v2 = payload_is_v2(payload)
                if v2 and request_opcode(payload) not in _PAN_SHARD_OPCODES:
                    # the zero-parse hot path: a v2 request's routing key
                    # sits at a fixed header offset, so the router picks
                    # the shard and forwards the frame verbatim without
                    # ever decoding the body. (A foreign v2 encoder that
                    # sets opcode 0 on a pan-shard op degrades to a relay
                    # to the owning/first shard — still a correct answer,
                    # minus the router's map augmentation.)
                    try:
                        exp = request_routing_key(payload)
                        self._relay(conn, payload, exp, upstream)
                    except (ConnectionError, BrokenPipeError, OSError,
                            ProtocolError, KeyError):
                        return
                    continue
                # pan-shard v2 ops and every JSON frame: decode for
                # op/args (JSON routing needs the body; pan-shard replies
                # are composed here)
                try:
                    msg = decode_payload(payload)
                except (ProtocolError, json.JSONDecodeError):
                    return
                op = msg.get("op")
                wire = "v2" if v2 else "v1"
                try:
                    if op == "ping":
                        self._send_reply(conn, self._ping_reply(
                            msg, upstream), wire)
                        continue
                    if op == "list_experiments":
                        replies = self._fanout(msg, upstream)
                        bad = next(
                            (r for r in replies if not r.get("ok")), None)
                        if bad is None:
                            names = sorted(
                                {n for r in replies for n in r["result"]})
                            self._send_reply(
                                conn, {"ok": True, "result": names}, wire)
                        else:
                            self._send_reply(conn, bad, wire)
                        continue
                    if op == "snapshot":
                        replies = self._fanout(msg, upstream)
                        bad = next(
                            (r for r in replies if not r.get("ok")), None)
                        if bad is None:
                            self._send_reply(conn, {
                                "ok": True,
                                "result": ";".join(
                                    str(r["result"]) for r in replies),
                            }, wire)
                        else:
                            self._send_reply(conn, bad, wire)
                        continue
                    if op == "tenant_stats":
                        # per-shard tenant accounting merges additively:
                        # each shard grants produce legs only for the
                        # experiments it owns, so summing counters (and
                        # unioning residency) is the pod-wide truth
                        replies = self._fanout(msg, upstream)
                        bad = next(
                            (r for r in replies if not r.get("ok")), None)
                        if bad is None:
                            self._send_reply(conn, {
                                "ok": True,
                                "result": merge_tenant_stats(
                                    [r["result"] for r in replies]),
                            }, wire)
                        else:
                            self._send_reply(conn, bad, wire)
                        continue
                    exp = experiment_of(op, msg.get("args") or {})
                    self._relay(conn, payload, exp, upstream)
                except (ConnectionError, BrokenPipeError, OSError,
                        ProtocolError, KeyError):
                    # upstream stayed dead past the window, or the client
                    # side broke mid-reply: drop the connection and let
                    # the client's own retry take over
                    return
        finally:
            for s in upstream.values():
                try:
                    s.close()
                except OSError:
                    pass
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# supervisor — spawn, health-check, restart-with-recovery
# ---------------------------------------------------------------------------

class _ShardProc:
    """One shard incarnation: its process + ready signal + stdout drain."""

    __slots__ = ("proc", "ready", "lines", "elapsed", "t0", "reader")

    def __init__(self, proc: subprocess.Popen, t0: float) -> None:
        self.proc = proc
        self.ready = threading.Event()
        self.lines: List[str] = []  # pre-ready output, for spawn errors
        self.elapsed: Optional[float] = None
        self.t0 = t0
        self.reader: Optional[threading.Thread] = None


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


class ShardSupervisor:
    """Spawn/health-check/restart N CoordServer shard subprocesses.

    Each shard runs ``python -m metaopt_tpu.coord.shards`` on a fixed
    port with its own snapshot path (``shard-<i>.snap.json`` under
    ``snapshot_dir``), so a restart lands on the same WAL + snapshot and
    recovers exactly like the single-process crash path
    (tests/functional/test_coord_crash.py). The ``coordinator ready``
    stdout line doubles as the recovery-done signal; a per-shard drain
    thread keeps consuming output afterwards so a chatty shard can never
    block on a full pipe.

    The watcher respawns any dead shard with ``METAOPT_TPU_FAULTS``
    disarmed (a chaos fault fires once per test, same doctrine as the
    crash-test supervisor) and never blocks on the respawn's recovery —
    death detection stays 20 ms-granular for the OTHER shards, which is
    what "one shard's crash+replay never stalls the others" means at the
    supervision layer.

    ``router=True`` (default) also runs a :class:`ShardRouter` on the
    public ``(host, port)`` — the address old clients keep using; new
    clients learn the map from any ping and go direct.

    ``failover=True`` changes what death means: instead of respawning
    the dead shard, its experiments are recovered from its snapshot+WAL
    on disk and handed to the SURVIVORS via the live hand-off protocol
    (:mod:`metaopt_tpu.coord.handoff`), shrinking the ring; survivors
    keep answering their own traffic throughout, and the wall time of
    each redistribution lands in ``failover_times``. ``handoff()`` runs
    the same protocol on demand for live rebalancing (`mtpu rebalance`).
    """

    def __init__(
        self,
        n_shards: int,
        host: str = "127.0.0.1",
        port: int = 0,
        snapshot_dir: Optional[str] = None,
        snapshot_interval_s: float = 30.0,
        stale_timeout_s: Optional[float] = None,
        router: bool = True,
        restart: bool = True,
        failover: bool = False,
        vnodes: int = DEFAULT_VNODES,
        shard_ports: Optional[List[int]] = None,
        shard_env: Optional[Dict[int, Dict[str, str]]] = None,
        ready_timeout_s: float = 120.0,
        suggest_prefetch_depth: int = 1,
        event_log_dir: Optional[str] = None,
        produce_coalesce_ms: Optional[float] = None,
        evict_idle_s: Optional[float] = None,
        max_resident: Optional[int] = None,
        max_experiments: Optional[int] = None,
        max_experiments_per_tenant: Optional[int] = None,
        tenant_weights: Optional[Dict[str, float]] = None,
        fuse_suggest: bool = False,
        fuse_bucket_max: Optional[int] = None,
    ) -> None:
        if n_shards < 1:
            raise ValueError("need at least one shard")
        self.n_shards = n_shards
        self.host = host
        self._public_port = port
        self.snapshot_dir = snapshot_dir
        self.snapshot_interval_s = snapshot_interval_s
        self.stale_timeout_s = stale_timeout_s
        self.suggest_prefetch_depth = suggest_prefetch_depth
        self.event_log_dir = event_log_dir
        self.produce_coalesce_ms = produce_coalesce_ms
        # multi-tenant knobs — forwarded verbatim to every shard; the
        # per-tenant admission caps apply PER SHARD (the router does not
        # pre-count), which is the conservative reading of a pod-wide cap
        self.evict_idle_s = evict_idle_s
        self.max_resident = max_resident
        self.max_experiments = max_experiments
        self.max_experiments_per_tenant = max_experiments_per_tenant
        self.tenant_weights = tenant_weights
        # fused suggest plane: forwarded to every shard (each shard fuses
        # across ITS resident experiments — buckets never span shards)
        self.fuse_suggest = fuse_suggest
        self.fuse_bucket_max = fuse_bucket_max
        self.vnodes = vnodes
        self.ready_timeout_s = ready_timeout_s
        self._want_router = router
        self._want_restart = restart
        #: failover mode: a dead shard's experiments are recovered from
        #: its snapshot+WAL on disk and handed to the SURVIVORS instead
        #: of respawning it (requires ``snapshot_dir``; see _failover_shard)
        self._want_failover = failover and restart
        if failover and snapshot_dir is None:
            raise ValueError("failover mode needs a snapshot_dir to "
                             "recover a dead shard's state from")
        #: extra env per shard index, applied to the FIRST incarnation
        #: only — the chaos test arms METAOPT_TPU_FAULTS on one shard here
        self._shard_env = dict(shard_env or {})
        self._shard_ports = list(shard_ports or [])
        self.shard_map: Optional[Dict[str, Any]] = None
        self.router: Optional[ShardRouter] = None
        #: shard index → current incarnation; every past proc is also kept
        #: (in _all_procs) so stop() can reap and crashes() can count
        self._shards: Dict[int, _ShardProc] = {}
        self._all_procs: List[subprocess.Popen] = []
        #: wall time from each spawn to its ready line — entry 0 is the
        #: cold start, later entries are restart+recovery times
        self.recovery_times: List[float] = []
        #: wall time of each completed failover (death detected →
        #: survivors own every recovered experiment) — the
        #: coord_failover_time_s bench metric
        self.failover_times: List[float] = []
        self._failover_threads: List[threading.Thread] = []
        self._procs_lock = threading.Lock()
        self._stopping = threading.Event()
        self._watcher: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    @property
    def address(self) -> Tuple[str, int]:
        """The public seed address: router if running, else shard 0."""
        if self.router is not None:
            return self.router.address
        assert self.shard_map is not None, "supervisor not started"
        return shard_addrs(self.shard_map)[self.shard_map["shards"][0]["id"]]

    def shard_addresses(self) -> Dict[str, Tuple[str, int]]:
        assert self.shard_map is not None, "supervisor not started"
        return shard_addrs(self.shard_map)

    def start(self) -> "ShardSupervisor":
        while len(self._shard_ports) < self.n_shards:
            self._shard_ports.append(_free_port(self.host))
        with self._procs_lock:
            self.shard_map = make_shard_map(
                [(f"s{i}", self.host, self._shard_ports[i])
                 for i in range(self.n_shards)],
                vnodes=self.vnodes,
            )
        # spawn all shards first, then wait: cold starts overlap. Any
        # failure past the first spawn (a shard that never comes up, a
        # router port already bound) must reap every child already
        # spawned — a raised start() leaves nothing behind
        try:
            recs = [self._spawn(i, env_extra=self._shard_env.get(i))
                    for i in range(self.n_shards)]
            deadline = time.monotonic() + self.ready_timeout_s
            for i, rec in enumerate(recs):
                if not rec.ready.wait(max(0.0, deadline - time.monotonic())):
                    out = "".join(rec.lines)
                    raise RuntimeError(f"shard {i} failed to start: {out}")
            if self._want_router:
                self.router = ShardRouter(self.shard_map, host=self.host,
                                          port=self._public_port).start()
        except BaseException:
            self.stop()
            raise
        if self._want_restart:
            self._watcher = threading.Thread(
                target=self._watch, name="coord-shard-watch", daemon=True)
            self._watcher.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        if self._watcher is not None:
            self._watcher.join(timeout=10)
        with self._procs_lock:
            fthreads = list(self._failover_threads)
        for t in fthreads:
            t.join(timeout=30)
        if self.router is not None:
            self.router.stop()
        with self._procs_lock:
            procs = list(self._all_procs)
            recs = list(self._shards.values())
        for proc in procs:
            if proc.poll() is None:
                proc.send_signal(_signal_mod.SIGTERM)  # snapshots first
        for proc in procs:
            if proc.poll() is None:
                try:
                    proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5)
        for rec in recs:
            if rec.reader is not None:
                rec.reader.join(timeout=5)
        for proc in procs:
            if proc.stdout is not None:
                proc.stdout.close()

    def __enter__(self) -> "ShardSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- chaos hooks -------------------------------------------------------
    def kill_shard(self, i: int) -> None:
        """SIGKILL shard ``i``'s current incarnation (chaos tests)."""
        with self._procs_lock:
            proc = self._shards[i].proc
        proc.kill()

    def crashes(self) -> int:
        with self._procs_lock:
            procs = list(self._all_procs)
        return sum(1 for p in procs
                   if p.poll() == -_signal_mod.SIGKILL)

    # -- spawn / watch -----------------------------------------------------
    def _shard_argv(self, i: int) -> List[str]:
        assert self.shard_map is not None
        argv = [
            sys.executable, "-m", "metaopt_tpu.coord.shards",
            "--shard-id", f"s{i}",
            "--host", self.host,
            "--port", str(self._shard_ports[i]),
            "--shard-map", json.dumps(self.shard_map,
                                      separators=(",", ":")),
            "--snapshot-interval-s", str(self.snapshot_interval_s),
        ]
        if self.snapshot_dir:
            argv += ["--snapshot",
                     os.path.join(self.snapshot_dir,
                                  f"shard-{i}.snap.json")]
        if self.stale_timeout_s is not None:
            argv += ["--stale-timeout-s", str(self.stale_timeout_s)]
        if self.suggest_prefetch_depth != 1:
            argv += ["--suggest-prefetch-depth",
                     str(self.suggest_prefetch_depth)]
        if self.event_log_dir:
            argv += ["--event-log",
                     os.path.join(self.event_log_dir,
                                  f"shard-{i}.events.jsonl")]
        if self.produce_coalesce_ms is not None:
            argv += ["--produce-coalesce-ms",
                     str(self.produce_coalesce_ms)]
        if self.evict_idle_s is not None:
            argv += ["--evict-idle-s", str(self.evict_idle_s)]
        if self.max_resident is not None:
            argv += ["--max-resident", str(self.max_resident)]
        if self.max_experiments is not None:
            argv += ["--max-experiments", str(self.max_experiments)]
        if self.max_experiments_per_tenant is not None:
            argv += ["--max-experiments-per-tenant",
                     str(self.max_experiments_per_tenant)]
        if self.tenant_weights:
            argv += ["--tenant-weights",
                     json.dumps(self.tenant_weights,
                                separators=(",", ":"))]
        if self.fuse_suggest:
            argv += ["--fuse-suggest"]
        if self.fuse_bucket_max is not None:
            argv += ["--fuse-bucket-max", str(self.fuse_bucket_max)]
        return argv

    def _spawn(self, i: int, env_extra: Optional[Dict[str, str]] = None,
               disarm: bool = False) -> _ShardProc:
        env = dict(os.environ)
        # the child resolves `-m metaopt_tpu.coord.shards` from the repo
        # root whether or not the package is installed
        root = _repo_root()
        env["PYTHONPATH"] = (
            root + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else root
        )
        if env_extra:
            env.update(env_extra)
        if disarm:
            # restarts run clean: an armed chaos fault fires once per
            # incarnation, not in a crash loop
            env.pop("METAOPT_TPU_FAULTS", None)
        t0 = time.monotonic()
        proc = subprocess.Popen(
            self._shard_argv(i), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env,
        )
        rec = _ShardProc(proc, t0)
        rec.reader = threading.Thread(
            target=self._drain, args=(rec,),
            name=f"coord-shard-drain-{i}", daemon=True)
        rec.reader.start()
        with self._procs_lock:
            self._shards[i] = rec
            self._all_procs.append(proc)
        return rec

    def _drain(self, rec: _ShardProc) -> None:
        # recovery log lines (torn-tail truncation etc.) precede the ready
        # line on the merged pipe; after ready, keep draining so the shard
        # never blocks on a full pipe
        assert rec.proc.stdout is not None
        for line in rec.proc.stdout:
            if not rec.ready.is_set():
                rec.lines.append(line)
                if "coordinator ready" in line:
                    rec.elapsed = time.monotonic() - rec.t0
                    with self._procs_lock:
                        self.recovery_times.append(rec.elapsed)
                    rec.ready.set()

    def _watch(self) -> None:
        while not self._stopping.wait(0.02):
            with self._procs_lock:
                items = list(self._shards.items())
            for i, rec in items:
                if rec.proc.poll() is not None and not self._stopping.is_set():
                    with self._procs_lock:
                        survivors = len(self._shards) - 1
                    if self._want_failover and survivors >= 1:
                        log.warning("shard %d died (rc=%s); failing its "
                                    "experiments over to %d survivor(s)",
                                    i, rec.proc.returncode, survivors)
                        t = threading.Thread(
                            target=self._failover_shard, args=(i,),
                            name=f"coord-shard-failover-{i}", daemon=True)
                        with self._procs_lock:
                            # drop the dead incarnation from the live set
                            # FIRST so the watcher never double-fires
                            self._shards.pop(i, None)
                            self._failover_threads.append(t)
                        t.start()
                        continue
                    log.warning("shard %d died (rc=%s); restarting with "
                                "recovery", i, rec.proc.returncode)
                    # respawn is non-blocking (readiness lands via the
                    # drain thread), so one shard's replay never delays
                    # death detection for the others
                    self._spawn(i, disarm=True)

    def _failover_shard(self, i: int) -> None:
        """Recover dead shard ``i``'s experiments onto the survivors.

        Runs in its own ``coord-shard-failover-{i}`` thread so death
        detection (and failover of a SECOND shard) never waits on this
        one's WAL replay. The dead shard's snapshot + WAL are read
        straight off disk (:func:`~metaopt_tpu.coord.handoff.
        recover_shard_state`) and each experiment is pushed to its new
        owner through the same idempotent ``handoff_apply`` op a live
        migration uses — one recovery path, not two.
        """
        from metaopt_tpu.coord.handoff import (
            apply_recovered, call_admin, recover_shard_state)

        t0 = time.monotonic()
        dead_sid = f"s{i}"
        try:
            with self._procs_lock:
                assert self.shard_map is not None
                cur = self.shard_map
            new_map = without_shard(cur, dead_sid)
            assert self.snapshot_dir is not None
            snap = os.path.join(self.snapshot_dir, f"shard-{i}.snap.json")
            states = recover_shard_state(snap, snap + ".wal")
            table = RoutingTable(new_map)
            for exp, state in sorted(states.items()):
                apply_recovered(exp, state, table.addrs[table.owner(exp)],
                                new_map)
            # every survivor must adopt the shrunken map (the applies
            # taught only each experiment's new owner)
            for addr in table.addrs.values():
                try:
                    call_admin(addr, "shard_map_update",
                               {"shard_map": new_map}, window_s=5.0)
                except Exception:
                    log.warning("failover: map broadcast to %s failed",
                                addr, exc_info=True)
            with self._procs_lock:
                if map_version(self.shard_map) < map_version(new_map):
                    self.shard_map = new_map
                self.failover_times.append(time.monotonic() - t0)
            if self.router is not None:
                self.router.update_map(new_map)
            log.warning("failover of shard %d done: %d experiment(s) "
                        "redistributed in %.2fs", i, len(states),
                        time.monotonic() - t0)
        except Exception:
            # a failed failover must not kill the watcher's process —
            # the experiments stay recoverable on disk for a retry/drill
            log.exception("failover of shard %d failed", i)

    # -- live rebalance ----------------------------------------------------
    def handoff(self, experiment: str, dest_sid: str,
                drain_timeout_s: float = 10.0,
                window_s: float = 30.0) -> Optional[Dict[str, Any]]:
        """Migrate ``experiment`` to ``dest_sid`` live; None if already
        there. Runs the full prepare→ship→apply→commit protocol
        (:func:`~metaopt_tpu.coord.handoff.migrate_experiment`) and
        teaches the router + supervisor map the bumped version."""
        from metaopt_tpu.coord.handoff import migrate_experiment

        with self._procs_lock:
            assert self.shard_map is not None, "supervisor not started"
            cur = self.shard_map
        table = RoutingTable(cur)
        source_sid = table.owner(experiment)
        if source_sid == dest_sid:
            return None
        new_map = with_override(cur, experiment, dest_sid)
        others = [a for sid, a in table.addrs.items()
                  if sid not in (source_sid, dest_sid)]
        result = migrate_experiment(
            experiment, table.addrs[source_sid], table.addrs[dest_sid],
            dest_sid, new_map, other_addrs=others,
            drain_timeout_s=drain_timeout_s, window_s=window_s)
        with self._procs_lock:
            if map_version(self.shard_map) < map_version(new_map):
                self.shard_map = new_map
        if self.router is not None:
            self.router.update_map(new_map)
        return result


# ---------------------------------------------------------------------------
# shard subprocess entry: python -m metaopt_tpu.coord.shards
# ---------------------------------------------------------------------------

def _shard_main(argv: Optional[List[str]] = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m metaopt_tpu.coord.shards",
        description="run ONE coordinator shard (normally spawned by "
                    "ShardSupervisor / `mtpu serve --shards N`)",
    )
    ap.add_argument("--shard-id", required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--shard-map", default=None,
                    help="full shard map as inline JSON")
    ap.add_argument("--snapshot", default=None)
    ap.add_argument("--snapshot-interval-s", type=float, default=30.0)
    ap.add_argument("--stale-timeout-s", type=float, default=None)
    ap.add_argument("--event-log", default=None)
    ap.add_argument("--suggest-prefetch-depth", type=int, default=1)
    ap.add_argument("--produce-coalesce-ms", type=float, default=None)
    ap.add_argument("--evict-idle-s", type=float, default=None)
    ap.add_argument("--max-resident", type=int, default=None)
    ap.add_argument("--max-experiments", type=int, default=None)
    ap.add_argument("--max-experiments-per-tenant", type=int, default=None)
    ap.add_argument("--fuse-suggest", action="store_true", default=False)
    ap.add_argument("--fuse-bucket-max", type=int, default=None)
    ap.add_argument("--tenant-weights", default=None,
                    help="tenant→weight map as inline JSON")
    a = ap.parse_args(argv)

    from metaopt_tpu.coord.server import CoordServer, serve_forever

    extra: Dict[str, Any] = {}
    if a.produce_coalesce_ms is not None:
        extra["produce_coalesce_ms"] = a.produce_coalesce_ms
    if a.evict_idle_s is not None:
        extra["evict_idle_s"] = a.evict_idle_s
    if a.max_resident is not None:
        extra["max_resident"] = a.max_resident
    if a.max_experiments is not None:
        extra["max_experiments"] = a.max_experiments
    if a.max_experiments_per_tenant is not None:
        extra["max_experiments_per_tenant"] = a.max_experiments_per_tenant
    if a.tenant_weights:
        extra["tenant_weights"] = json.loads(a.tenant_weights)
    if a.fuse_suggest:
        extra["fuse_suggest"] = True
    if a.fuse_bucket_max is not None:
        extra["fuse_bucket_max"] = a.fuse_bucket_max
    serve_forever(CoordServer(
        host=a.host,
        port=a.port,
        snapshot_path=a.snapshot,
        snapshot_interval_s=a.snapshot_interval_s,
        stale_timeout_s=a.stale_timeout_s,
        event_log_path=a.event_log,
        suggest_prefetch_depth=a.suggest_prefetch_depth,
        shard_id=a.shard_id,
        shard_map=json.loads(a.shard_map) if a.shard_map else None,
        **extra,
    ))


if __name__ == "__main__":
    _shard_main()
