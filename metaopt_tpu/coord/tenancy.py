"""Multi-tenant produce scheduling (ISSUE 16 / ROADMAP item 1).

One coordinator ring now serves fleets of experiments owned by many
tenants, and the produce leg of ``worker_cycle`` is the contended
resource: a hosted algorithm fit is milliseconds-to-seconds of CPU, so a
hot tenant hammering one experiment with 32 workers can starve a thousand
one-worker tenants of suggestion throughput long before the socket plane
saturates. :class:`FairProduceScheduler` arbitrates that capacity.

The discipline is a windowed weighted deficit round-robin:

- Each produce request costs one grant. Within a scheduling window a
  tenant may hold at most ``share × (total grants so far) + burst``
  grants, where ``share`` is its weight over the weights of all *active*
  tenants (active = requested within ``active_window_s``).
- A denied request is NOT queued — the worker cycle simply skips its
  produce leg this round (it still completes/reserves/counts), retrying
  on its next cycle. Capacity therefore shifts, it is never parked: with
  a single active tenant every request is granted (work conservation),
  and a tenant that stops requesting ages out of the active set after
  ``active_window_s`` and stops constraining anyone.
- Optional absolute per-tenant ``quotas`` (grants per window) cap a
  tenant below its fair share — the operator knob for batch tenants.

The scheduler itself is deliberately lock-free: :class:`CoordServer`
serializes calls under its ``_tenant_lock`` (declared in
``analysis/registry.py``), which keeps this class trivially
unit-testable with a fake clock.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, Optional

__all__ = ["FairProduceScheduler", "jain_index"]


def jain_index(xs: Iterable[float]) -> float:
    """Jain's fairness index ``(Σx)² / (n·Σx²)`` over per-tenant shares.

    1.0 is perfectly fair; ``1/n`` is one tenant taking everything. The
    1k-experiment bench gates ``coord_fairness_jain_1k`` on this.
    """
    vals = [float(x) for x in xs]
    if not vals:
        return 1.0
    total = sum(vals)
    sq = sum(v * v for v in vals)
    if sq <= 0.0:
        return 1.0
    return (total * total) / (len(vals) * sq)


class FairProduceScheduler:
    def __init__(
        self,
        weights: Optional[Dict[str, float]] = None,
        quotas: Optional[Dict[str, int]] = None,
        window_s: float = 0.5,
        burst: int = 2,
        active_window_s: float = 2.0,
    ) -> None:
        self.weights = dict(weights or {})
        self.quotas = dict(quotas or {})
        self.window_s = float(window_s)
        self.burst = int(burst)
        self.active_window_s = float(active_window_s)
        self._window_start = 0.0
        #: grants inside the current window (reset on roll)
        self._granted: Dict[str, int] = {}
        #: tenant → last produce-request timestamp (active-set membership)
        self._last_req: Dict[str, float] = {}
        #: lifetime accounting, surfaced by the ``tenant_stats`` op
        self.total_granted: Dict[str, int] = {}
        self.total_denied: Dict[str, int] = {}

    def weight(self, tenant: str) -> float:
        w = float(self.weights.get(tenant, 1.0))
        return w if w > 0.0 else 1.0

    def _active(self, now: float) -> list:
        horizon = now - self.active_window_s
        # prune while scanning so the map tracks live tenants, not history
        dead = [t for t, ts in self._last_req.items() if ts < horizon]
        for t in dead:
            self._last_req.pop(t, None)
        return list(self._last_req)

    def admit(self, tenant: str, now: Optional[float] = None) -> bool:
        """One produce-leg admission decision for ``tenant``; True = run
        the produce leg now, False = skip it this cycle (retryable)."""
        if now is None:
            now = time.monotonic()
        self._last_req[tenant] = now
        if now - self._window_start >= self.window_s:
            self._window_start = now
            self._granted.clear()
        held = self._granted.get(tenant, 0)
        quota = self.quotas.get(tenant)
        if quota is not None and held >= int(quota):
            self.total_denied[tenant] = self.total_denied.get(tenant, 0) + 1
            return False
        active = self._active(now)
        if len(active) > 1:
            wsum = sum(self.weight(t) for t in active)
            share = self.weight(tenant) / wsum
            total = sum(self._granted.values())
            if held >= share * (total + 1) + self.burst:
                self.total_denied[tenant] = (
                    self.total_denied.get(tenant, 0) + 1)
                return False
        self._granted[tenant] = held + 1
        self.total_granted[tenant] = self.total_granted.get(tenant, 0) + 1
        return True

    def grant_order(self, tenants: Iterable[str]) -> Dict[str, float]:
        """Tenant → priority for the fused suggest plane's demand sweep.

        The :class:`~metaopt_tpu.coord.fuser.SuggestFuser` collects
        pending demand across ALL resident experiments each tick; it
        does not consume produce grants (fused refills are speculative
        background work, not reply-path capacity), but it ORDERS its
        sweep by each tenant's unmet share — weight divided by grants
        already held this window — so when a tick's bucket budget runs
        out, the tenants the produce plane has served least keep their
        prefetch pools warm first. Pure read: no window roll, no
        accounting mutation. Serialized under ``_tenant_lock`` like
        every other entry point.
        """
        out: Dict[str, float] = {}
        for t in tenants:
            held = self._granted.get(t, 0)
            out[t] = self.weight(t) / (1.0 + held)
        return out

    def stats(self) -> Dict[str, Dict[str, float]]:
        """Per-tenant lifetime accounting (``tenant_stats`` reply body)."""
        out: Dict[str, Dict[str, float]] = {}
        for t in set(self.total_granted) | set(self.total_denied):
            out[t] = {
                "granted": self.total_granted.get(t, 0),
                "denied": self.total_denied.get(t, 0),
                "weight": self.weight(t),
            }
        return out
