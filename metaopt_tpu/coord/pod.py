"""``jax.distributed`` glue: one coordinator per pod, agreed pod-wide.

The reference's multi-node story is "point every worker at the same Mongo
URL" (SURVEY.md §3.2). The pod-native story: the host running JAX process 0
starts the :class:`~metaopt_tpu.coord.server.CoordServer`, and the service
address is agreed across processes with one tiny all-broadcast over the
pod's existing collective channel — no out-of-band config needed. DCN-side
(multi-slice) workers can instead be pointed at ``coord://host:port``
explicitly, exactly like a Mongo URL.
"""

from __future__ import annotations

import logging
import socket
from typing import Optional, Tuple

from metaopt_tpu.coord.server import CoordServer

log = logging.getLogger(__name__)

_ADDR_BYTES = 64  # fixed-size frame for the broadcast: 62B host + 2B port


def _encode_addr(host: str, port: int):
    import numpy as np

    raw = host.encode("utf-8")[: _ADDR_BYTES - 2]
    buf = np.zeros(_ADDR_BYTES, dtype=np.uint8)
    buf[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
    buf[-2] = port >> 8
    buf[-1] = port & 0xFF
    return buf


def _decode_addr(buf) -> Tuple[str, int]:
    import numpy as np

    arr = np.asarray(buf, dtype=np.uint8)
    host = bytes(arr[:-2]).rstrip(b"\x00").decode("utf-8")
    return host, (int(arr[-2]) << 8) | int(arr[-1])


def _local_host_ip() -> str:
    """The address other pod hosts can reach us on (best effort)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))  # no packet sent; routes only
            return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())


def start_pod_coordinator(
    snapshot_path: Optional[str] = None,
    stale_timeout_s: Optional[float] = 60.0,
    event_log_path: Optional[str] = None,
    port: int = 0,
) -> Tuple[str, int, Optional[CoordServer]]:
    """Start (on process 0) or discover (elsewhere) the pod's coordinator.

    Returns ``(host, port, server)`` — ``server`` is non-None only on the
    hosting process, which must keep it alive and ``stop()`` it at exit.
    Single-process runs degenerate to a local server, so the same call works
    in tests, on one chip, and on a pod.
    """
    import jax

    if jax.process_count() == 1:
        server = CoordServer(
            host="127.0.0.1",
            port=port,
            snapshot_path=snapshot_path,
            stale_timeout_s=stale_timeout_s,
            event_log_path=event_log_path,
        ).start()
        h, p = server.address
        return h, p, server

    from jax.experimental import multihost_utils

    server: Optional[CoordServer] = None
    if jax.process_index() == 0:
        host = _local_host_ip()
        server = CoordServer(
            host="0.0.0.0",
            port=port,
            snapshot_path=snapshot_path,
            stale_timeout_s=stale_timeout_s,
            event_log_path=event_log_path,
        ).start()
        addr = _encode_addr(host, server.address[1])
    else:
        addr = _encode_addr("", 0)

    agreed = multihost_utils.broadcast_one_to_all(addr)
    host, p = _decode_addr(agreed)
    log.info(
        "pod coordinator at coord://%s:%d (process %d/%d)",
        host, p, jax.process_index(), jax.process_count(),
    )
    return host, p, server
