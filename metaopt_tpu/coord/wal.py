"""Write-ahead log for the coordinator — the durability layer under
:class:`~metaopt_tpu.coord.server.CoordServer`.

Before this module, a coordinator crash lost up to ``snapshot_interval_s``
(30s) of *acknowledged* writes plus the whole in-memory reply cache — so the
exactly-once guarantee the fused ``worker_cycle`` op builds on silently
broke across restarts. The WAL closes that hole: every acknowledged mutation
is on disk before its reply leaves the sender thread, and recovery is
``restore(snapshot) + replay(WAL tail)``.

Record formats (a log may mix them freely; each record declares its own)::

    v1:  {crc32:08x} {compact JSON}\\n
    v2:  "W2" {crc32 u32 BE} {len u32 BE} {binary body}

v1 is the original JSON-line record; v2 frames the wire format's binary
body (:func:`metaopt_tpu.coord.protocol.encode_body`) with the crc32 over
the binary bytes. The two are unambiguous at any record boundary: a v1
line starts with 8 lowercase-hex characters and a space, a v2 record with
the two magic bytes ``W2`` followed by a binary header — so
:func:`read_records` dispatches per record and a pre-existing v1 log keeps
appending v2 records in place (replay of the mixed tail is exercised
bit-for-bit by the codec property tests). A record the binary codec cannot
carry falls back to a v1 line, so a "binary" log is always recoverable.

The crc covers the record body bytes; a torn tail (partial last batch
after a kill -9 or power cut) fails the crc or the parse and
:func:`read_records` physically truncates the file at the first bad record —
everything before it was group-commit fsynced and is intact by construction.
Each record carries a monotonic ``seq``; a snapshot embeds the highest
``seq`` it reflects (``wal_seq``), so replay applies only the tail and the
log is compacted down to that tail after every snapshot.

Group commit reuses the leader/latecomer window pattern of the server's
``_ProduceCoalescer``: the first thread that needs durability becomes the
leader, optionally sleeps ``group_window_s``, then writes + fsyncs EVERY
record appended so far in one batch; threads that arrive while the leader is
in fsync wait on the condition variable and are released together when the
batch lands. Under fan-in the fsync cost therefore amortizes across the same
burst of requests that already coalesces produce calls — with the default
``group_window_s=0`` the fsync duration itself is the batching window (while
one fsync runs, the next batch accumulates), which keeps single-client
latency unchanged.

Appends are buffer-only (one lock, no I/O) and may be called under the
server's per-experiment locks; ``sync()`` does the I/O and must be called
OUTSIDE them (the server calls it from each connection's sender thread).

No background threads: group commit runs on caller threads, so the module
adds nothing to the coordinator's thread census (tests assert no leaked
``coord-*`` threads per test).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import struct
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

from metaopt_tpu.coord.protocol import (HAVE_WIRE_V2, ProtocolError,
                                        decode_body, encode_body)
from metaopt_tpu.utils import fsjournal as fsj
# re-exported: server.py and the snapshot/evict publishers import it from
# here; the implementation lives in the FS seam so every directory fsync
# lands in a recorded effect trace under `mtpu crashcheck`
from metaopt_tpu.utils.fsjournal import fsync_dir  # noqa: F401
from metaopt_tpu.utils.clock import SYSTEM_CLOCK, Clock

log = logging.getLogger(__name__)

_V2_MAGIC = b"W2"
_V2_HDR = struct.Struct(">2sII")  # magic, crc32(body), len(body)
# a single WAL record beyond this is a corrupt length field, not data —
# same ceiling as the wire's MAX_MSG_BYTES
_V2_MAX_BODY = 64 * 1024 * 1024


def _frame_v1(rec: Dict[str, Any]) -> bytes:
    payload = json.dumps(rec, separators=(",", ":"), default=str).encode()
    return b"%08x %s\n" % (zlib.crc32(payload), payload)


def _frame_v2(rec: Dict[str, Any]) -> bytes:
    try:
        # no default hook: a record msgpack can't carry natively (>64-bit
        # ints, stray objects) must take the v1 path wholesale so replay
        # yields exactly what a pure-v1 log would (json keeps big ints;
        # a msgpack default=str would silently stringify them)
        body = encode_body(rec)
    except ProtocolError:
        # per-record fallback: the log stays mixed rather than losing
        # the record or failing the append
        return _frame_v1(rec)
    return _V2_HDR.pack(_V2_MAGIC, zlib.crc32(body), len(body)) + body


# kept under the original name: tests and tooling frame v1 records with it
_frame = _frame_v1


def read_records(path: str, truncate_torn: bool = True
                 ) -> Tuple[List[Dict[str, Any]], int]:
    """Parse a WAL file (v1 lines and v2 binary records, freely mixed);
    returns ``(records, torn_bytes)``.

    Stops at the first record whose crc or parse fails — the torn tail of
    a crash mid-batch — and (by default) truncates the file there so a
    later append never interleaves new records with torn garbage.
    ``torn_bytes`` is how many bytes were dropped (0 = clean log).
    """
    records: List[Dict[str, Any]] = []
    good_end = 0
    torn = 0
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return records, 0
    pos = 0
    size = len(data)
    while pos < size:
        try:
            if data[pos:pos + 2] == _V2_MAGIC:
                # v2: fixed header + crc'd binary body (length-delimited,
                # so a body byte that happens to be 0x0a never splits it)
                if pos + _V2_HDR.size > size:
                    raise ValueError("torn v2 header")
                _, crc, length = _V2_HDR.unpack_from(data, pos)
                end = pos + _V2_HDR.size + length
                if length > _V2_MAX_BODY or end > size:
                    raise ValueError("torn v2 body")
                body = data[pos + _V2_HDR.size:end]
                if zlib.crc32(body) != crc:
                    raise ValueError("v2 crc mismatch")
                rec = decode_body(body)
                if not isinstance(rec, dict):
                    raise ValueError("v2 record is not a dict")
            else:
                nl = data.find(b"\n", pos)
                line = data[pos:nl] if nl != -1 else data[pos:]
                end = (nl + 1) if nl != -1 else size
                crc_hex, payload = line.split(b" ", 1)
                if int(crc_hex, 16) != zlib.crc32(payload):
                    raise ValueError("crc mismatch")
                rec = json.loads(payload)
        except (ValueError, json.JSONDecodeError, ProtocolError):
            torn = size - pos
            break
        records.append(rec)
        good_end = end
        pos = end
    if torn and truncate_torn:
        log.warning("WAL %s: torn tail (%d bytes after record %d) truncated",
                    path, torn, records[-1].get("seq", 0) if records else 0)
        fsj.truncate(path, good_end)
    return records, torn


def record_experiment(rec: Dict[str, Any]) -> Optional[str]:
    """Which experiment a WAL record belongs to, or ``None`` for global
    records (``shard_map`` adoption markers, unknown kinds).

    Hand-off ships exactly the records the destination needs to redo one
    experiment, so attribution must agree with how ``_apply_wal_record``
    reads each kind back: trial records via the embedded doc, experiment
    lifecycle ops via their name argument, reply records via the ``exp``
    tag stamped by ``_journal_reply``.
    """
    op = rec.get("op")
    if op == "put_trial":
        return (rec.get("trial") or {}).get("experiment")
    if op == "create_experiment":
        return (rec.get("config") or {}).get("name")
    if op in ("update_experiment", "delete_experiment"):
        return rec.get("name")
    if op == "set_signal":
        return rec.get("experiment")
    if op in ("evict", "hydrate"):
        # lazy eviction lifecycle (server._evict_fenced / hydrate): a
        # hand-off extracting an experiment's tail must carry these or
        # the destination replays trial records over a stale residency
        return rec.get("experiment")
    if op == "reply":
        return rec.get("exp")
    return None


class WriteAheadLog:
    """Append-buffered, group-commit-fsynced redo log.

    ``append(rec)`` is cheap (stamp a seq, frame, buffer) and safe under
    ledger locks; ``sync(target_seq)`` blocks until every record up to
    ``target_seq`` is fsynced, electing one caller as the batch leader.
    ``fsync=False`` keeps the write ordering but skips the fsync — for
    benchmarks isolating the syscall cost, never for production.

    ``binary`` selects the record framing for NEW records (default: v2
    binary when the codec is available). Replay always accepts both
    framings, so flipping it — or upgrading a server over an existing v1
    log — needs no migration: the log is simply mixed from that point on.
    """

    def __init__(self, path: str, fsync: bool = True,
                 group_window_s: float = 0.0,
                 binary: Optional[bool] = None,
                 clock: Optional[Clock] = None) -> None:
        self.path = path
        self.fsync = fsync
        self.group_window_s = group_window_s
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.binary = HAVE_WIRE_V2 if binary is None else (
            bool(binary) and HAVE_WIRE_V2)
        self._frame_rec = _frame_v2 if self.binary else _frame_v1
        self._buf_lock = threading.Lock()   # buffer + seq counter
        self._cv = threading.Condition()    # group-commit leader election
        self._pending: List[bytes] = []
        self._next_seq = 1
        self._appended = 0   # last seq handed out
        self._durable = 0    # last seq known fsynced
        self._syncing = False
        self._failed = False  # fsync/write failed: journaling degraded
        self._fence = 0      # open compaction fences (hand-off tail ships)
        #: per-thread share of ``_fence`` (thread id → open depth): lets
        #: ``compact`` distinguish its OWN caller's fence (snapshot wraps
        #: its compact in one to exclude concurrent extractions) from a
        #: foreign hand-off's — waiting on your own fence would deadlock
        self._fence_owners: Dict[int, int] = {}
        self._f: Optional[Any] = None
        self.batches = 0     # fsync batches written (amortization telemetry)
        self.records = 0

    # -- lifecycle --------------------------------------------------------
    def open(self, next_seq: int = 1) -> "WriteAheadLog":
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self._f = open(self.path, "ab")
        # single-threaded: open() runs before any serving thread exists
        self._next_seq = max(1, next_seq)  # mtpu: lint-ok MTL003 pre-serving
        self._appended = self._durable = (  # mtpu: lint-ok MTL003 pre-serving
            self._next_seq - 1)
        return self

    def close(self) -> None:
        with self._cv:
            while self._syncing:
                self._cv.wait(timeout=1.0)
            self._syncing = True
        try:
            with self._buf_lock:
                batch, self._pending = self._pending, []
                upto = self._appended
            if self._f is not None:
                try:
                    self._write_batch(batch)
                    # publish under the cv like sync()/compact(): a racing
                    # sync() latecomer polls _durable under the cv, and an
                    # unfenced write here could leave it waiting a full
                    # timeout on a stale value
                    with self._cv:
                        self._durable = max(self._durable, upto)
                except OSError:
                    log.exception("WAL close flush failed")
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
        finally:
            with self._cv:
                self._syncing = False
                self._cv.notify_all()

    @property
    def appended_seq(self) -> int:
        with self._buf_lock:
            return self._appended

    @property
    def durable_seq(self) -> int:
        with self._cv:
            return self._durable

    # -- hot path ---------------------------------------------------------
    def append(self, rec: Dict[str, Any]) -> int:
        """Stamp + buffer one record; returns its seq. No I/O here —
        callers that need durability follow with ``sync(seq)`` outside any
        ledger lock."""
        if self._f is None or self._failed:
            return 0
        with self._buf_lock:
            seq = self._next_seq
            self._next_seq += 1
            rec["seq"] = seq
            self._pending.append(self._frame_rec(rec))
            self._appended = seq
        return seq

    def sync(self, target_seq: int) -> None:
        """Block until every record up to ``target_seq`` is fsynced.

        Leader/latecomer group commit: the first waiter becomes leader,
        optionally sleeps the window out, then writes + fsyncs the WHOLE
        pending buffer (including records appended by threads that arrived
        during the wait); latecomers block on the condition variable and
        are all released when the batch lands.
        """
        if target_seq <= 0 or self._f is None:
            return
        while True:
            with self._cv:
                if self._durable >= target_seq or self._failed:
                    return
                if self._syncing:
                    self._cv.wait(timeout=1.0)
                    continue
                self._syncing = True
            break
        # leader from here
        try:
            if self.group_window_s > 0:
                # let the burst pile in — same amortization window doctrine
                # as _ProduceCoalescer (0 = fsync-duration batching only)
                self.clock.sleep(self.group_window_s)
            # one batch per leader, then hand off: keeping the leader role
            # across batches was measured SLOWER at 32-worker fan-in (the
            # leader's own acked client idles while it writes strangers'
            # batches, draining the pipeline)
            with self._buf_lock:
                batch, self._pending = self._pending, []
                upto = self._appended
            if batch:
                self._write_batch(batch)
            with self._cv:
                self._durable = max(self._durable, upto)
                self._cv.notify_all()
        except OSError:
            # durability is degraded, the service stays up: callers stop
            # waiting (and the server logs loudly) rather than deadlocking
            # every reply behind a dead disk
            log.exception("WAL write/fsync failed — durability degraded")
            # fence like _durable: latecomers poll _failed under the cv,
            # and an unfenced store could leave one waiting a full
            # timeout on a stale value
            with self._cv:
                self._failed = True
        finally:
            with self._cv:
                self._syncing = False
                self._cv.notify_all()

    def _write_batch(self, batch: List[bytes]) -> None:
        if not batch:
            return
        data = b"".join(batch)
        from metaopt_tpu.executor.faults import faults

        if faults.fire("torn_wal_tail"):
            # chaos: die mid-batch — half the bytes land, then SIGKILL.
            # Recovery must truncate the torn half-record and keep
            # everything previously acknowledged.
            self._f.write(data[: max(1, len(data) // 2)])
            self._f.flush()
            os.fsync(self._f.fileno())
            os.kill(os.getpid(), signal.SIGKILL)
        fsj.append(self._f, self.path, data, fsync=self.fsync)
        # counters are read by stats()/bench from other threads; the
        # lock is taken AFTER the I/O so fsync never runs under it
        with self._buf_lock:
            self.batches += 1
            self.records += len(batch)

    # -- hand-off ---------------------------------------------------------
    def compaction_fence(self) -> "_CompactionFence":
        """Context manager that blocks :meth:`compact` for its duration.

        A hand-off extracts an experiment's tail with :meth:`extract_tail`
        and then keeps referring to those seqs until the ownership commit;
        a compaction sneaking in between would rewrite the file out from
        under the ship. ``compact()`` waits while any fence is open;
        appends and syncs are unaffected.
        """
        return _CompactionFence(self)

    def extract_tail(self, experiment: str) -> List[Dict[str, Any]]:
        """All on-disk + buffered records attributed to ``experiment``.

        Takes the group-commit leader role so the pending buffer is
        flushed first and no concurrent batch interleaves with the read —
        the returned tail is therefore complete up to every acknowledged
        write at the moment of the call. Call under a
        :meth:`compaction_fence` when the result must stay valid until an
        ownership commit.
        """
        if self._f is None:
            return []
        while True:
            with self._cv:
                if self._syncing:
                    self._cv.wait(timeout=1.0)
                    continue
                self._syncing = True
            break
        upto = 0
        try:
            with self._buf_lock:
                batch, self._pending = self._pending, []
                upto = self._appended
            try:
                self._write_batch(batch)
            except OSError:
                log.exception("WAL extract_tail flush failed")
                with self._cv:
                    self._failed = True
                return []
            records, _ = read_records(self.path, truncate_torn=False)
            return [r for r in records
                    if record_experiment(r) == experiment]
        finally:
            with self._cv:
                if not self._failed:
                    self._durable = max(self._durable, upto)
                self._syncing = False
                self._cv.notify_all()

    def _fence_enter(self) -> None:
        tid = threading.get_ident()
        with self._cv:
            self._fence += 1
            self._fence_owners[tid] = self._fence_owners.get(tid, 0) + 1

    def _fence_exit(self) -> None:
        tid = threading.get_ident()
        with self._cv:
            self._fence = max(0, self._fence - 1)
            depth = self._fence_owners.get(tid, 0) - 1
            if depth > 0:
                self._fence_owners[tid] = depth
            else:
                self._fence_owners.pop(tid, None)
            self._cv.notify_all()

    def _foreign_fences(self) -> int:
        # mtpu: holds(_cv)
        return self._fence - self._fence_owners.get(threading.get_ident(), 0)

    def fence_held(self) -> bool:
        """True while the CALLING thread holds an open compaction fence —
        the assertion hook for paths required to run fenced."""
        with self._cv:
            return self._fence_owners.get(threading.get_ident(), 0) > 0

    # -- maintenance ------------------------------------------------------
    def compact(self, upto_seq: int) -> None:
        """Drop every record with ``seq <= upto_seq`` (they are reflected
        in the snapshot stamped ``wal_seq=upto_seq``); keep the tail.

        Takes the leader role so no concurrent batch writes interleave
        with the rewrite; appends keep buffering meanwhile and land in the
        fresh file on the next sync.
        """
        if self._f is None:
            return
        while True:
            with self._cv:
                # a hand-off fence holds compaction off entirely: the
                # shipped tail must stay on disk until ownership commits.
                # Only FOREIGN fences count — the snapshot path compacts
                # under its own fence (held against concurrent
                # extractions), which must not block itself.
                if self._syncing or self._foreign_fences() > 0:
                    self._cv.wait(timeout=1.0)
                    continue
                self._syncing = True
            break
        upto = 0
        try:
            # flush the buffer first so the rewrite sees every record
            with self._buf_lock:
                batch, self._pending = self._pending, []
                upto = self._appended
            try:
                self._write_batch(batch)
            except OSError:
                log.exception("WAL compact flush failed")
                with self._cv:
                    self._failed = True
                return
            records, _ = read_records(self.path, truncate_torn=False)
            tail = [r for r in records if r.get("seq", 0) > upto_seq]
            tmp = self.path + ".tmp"
            # rewritten in the log's own framing: compaction after an
            # upgrade is what migrates a mixed v1/v2 log to pure v2.
            # tmp is written + fsynced BEFORE the rename publishes it
            # (crash-atomic doctrine — MTP001).
            fsj.write_file(tmp, b"".join(self._frame_rec(r) for r in tail))
            # the marker precedes the rename: from the next effect on, a
            # crash state may legitimately lack records <= upto_seq (the
            # certifier must excuse them one event EARLY, never late)
            fsj.mark("wal_compacted", upto=upto_seq)
            fsj.replace(tmp, self.path)
            fsync_dir(self.path)
            try:
                self._f.close()
            except OSError:
                pass
            self._f = open(self.path, "ab")
        except OSError:
            log.exception("WAL compaction failed (log kept as-is)")
        finally:
            with self._cv:
                if not self._failed:
                    self._durable = max(self._durable, upto)
                self._syncing = False
                self._cv.notify_all()


class _CompactionFence:
    """``with wal.compaction_fence():`` — holds :meth:`compact` off."""

    def __init__(self, wal: WriteAheadLog) -> None:
        self._wal = wal

    def __enter__(self) -> "_CompactionFence":
        self._wal._fence_enter()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._wal._fence_exit()
