"""Wire framing for the coordinator channel.

One message = 4-byte big-endian length + UTF-8 JSON. Requests are
``{"op": str, "args": dict}``; responses ``{"ok": true, "result": ...}`` or
``{"ok": false, "error": <exception class name>, "msg": str}``. JSON over a
socket (not pickle) keeps the channel language-neutral and injection-safe;
trial documents already round-trip through dicts for the file ledger, so the
same ``to_dict``/``from_dict`` pair is the marshalling layer here.

The ``produce`` op's reply is ``{"registered": int, "algo_done": bool,
"coalesced": int}``: the server may group-commit concurrent produce requests
into one combined suggestion cycle (``CoordServer(produce_coalesce_ms=…)``),
in which case ``registered`` is the combined cycle's total and ``coalesced``
the number of requests it served — clients must treat ``registered`` as a
progress signal, not as "trials registered on my behalf alone".

The ``worker_cycle`` op fuses one whole worker trial cycle server-side
(stale sweep → produce → reserve → counts) into a single round-trip; a
server advertises it (and the other optional ops) via ``caps`` in the
``ping`` reply so clients can pick the fast path up front, and clients
additionally degrade per-op on an ``unknown op`` error for rolling
upgrades (see ``CoordLedgerClient.worker_cycle``). The produce leg of a
hosted cycle is answered from the algorithm's speculative suggest-ahead
pool when one is banked (``CoordServer(suggest_prefetch_depth=…)`` sets
how many pools the hosted tpe/gp_bo/cmaes instances keep prepared; the
coalescer re-arms the pool off the reply path after every cycle), so the
round-trip cost is the ledger mutation, not the suggestion kernel.

A reply may be served as preencoded bytes (:func:`send_payload`) when the
server's per-commit reply cache hits — the wire format is identical, the
JSON encode is just paid once per ledger mutation instead of once per
observer.

**Durability semantics** (WAL-enabled servers — see
:mod:`metaopt_tpu.coord.wal`): once the reply to a mutating op (or to
``worker_cycle``/``produce``) is on the wire, the mutation AND its
request-id reply-cache entry are fsynced — a client that received an ack
can rely on the write surviving a coordinator kill -9, and a retry that
straddles the crash is answered from the journaled reply cache with the
original reply (exactly-once across restarts). The ``ping`` reply carries
``incarnation`` (a per-process-start id) and ``durable`` (whether a WAL is
active): a client that reconnects and observes a changed incarnation knows
it crossed a restart, not just a dropped connection, and runs session
resumption (re-learn caps, re-assert held reservations via heartbeats).
Wire framing is unchanged — both fields are ignored by older clients.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, Optional

_HDR = struct.Struct(">I")
MAX_MSG_BYTES = 64 * 1024 * 1024  # a fetch of ~100k trial docs fits well under

#: Durability-contract registry, enforced statically by ``mtpu lint``
#: (metaopt_tpu/analysis/durability.py). Ops listed here mutate ledger or
#: signal state and their ``_dispatch`` branch MUST reach a journal point
#: (a sharded-ledger mutator call or a direct ``wal.append``) before the
#: reply is enqueued; all three sets must stay subsets of the server's
#: ``_DURABLE_OPS`` so the reply actually waits on the fsync barrier.
#: Adding a mutating op without declaring it here fails the lint gate.
JOURNALED_OPS = frozenset({
    "create_experiment", "update_experiment", "delete_experiment",
    "register", "reserve", "update_trial", "release_stale", "set_signal",
})
#: ops journaled via their cached reply record: the journaled reply
#: embeds the resulting docs and doubles as their redo (see
#: ``CoordServer._journal_reply`` / ``_apply_wal_record``)
REPLY_JOURNALED_OPS = frozenset({"worker_cycle"})
#: ops that mutate only through nested ledger calls, each of which
#: journals itself inside the sharded proxy
NESTED_JOURNALED_OPS = frozenset({"produce"})
#
# Deliberately absent: the hand-off admin plane (``handoff_prepare`` /
# ``handoff_apply`` / ``handoff_abort`` / ``shard_map_update``). Those ops
# are handled in ``CoordServer._handle`` (not ``_dispatch``), journal
# inside their own handlers, and are idempotent rather than reply-cached —
# declaring them in JOURNALED_OPS would make MTD001 look for a dispatch
# branch that intentionally does not exist. They ARE members of the
# server's ``_DURABLE_OPS`` (a strict superset of these registries), so
# their replies still wait on the fsync barrier.


class ProtocolError(RuntimeError):
    pass


def encode_msg(msg: Dict[str, Any]) -> bytes:
    """One message as wire payload bytes (sans length header)."""
    payload = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_MSG_BYTES:
        raise ProtocolError(f"message too large: {len(payload)} bytes")
    return payload


def send_payload(sock: socket.socket, payload: bytes) -> None:
    """Send pre-encoded payload bytes — the preserialized-reply fast path."""
    if len(payload) > MAX_MSG_BYTES:
        raise ProtocolError(f"message too large: {len(payload)} bytes")
    sock.sendall(_HDR.pack(len(payload)) + payload)


def send_msg(sock: socket.socket, msg: Dict[str, Any]) -> None:
    send_payload(sock, encode_msg(msg))


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None  # peer closed
        buf.extend(chunk)
    return bytes(buf)


def recv_payload(sock: socket.socket) -> Optional[bytes]:
    """Read one framed message's raw payload bytes; None on clean EOF
    before a header. The shard router relays replies with this — a frame
    forwarded verbatim needs no decode+re-encode round-trip."""
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    (length,) = _HDR.unpack(hdr)
    if length > MAX_MSG_BYTES:
        raise ProtocolError(f"frame too large: {length} bytes")
    payload = _recv_exact(sock, length)
    if payload is None:
        raise ProtocolError("peer closed mid-frame")
    return payload


def recv_msg(sock: socket.socket) -> Optional[Dict[str, Any]]:
    """Read one framed message; None on clean EOF before a header."""
    payload = recv_payload(sock)
    if payload is None:
        return None
    return json.loads(payload.decode("utf-8"))
